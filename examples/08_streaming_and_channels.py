"""Operator-pipelined Data execution + compiled actor chains (round 3).

Two r3 features side by side:
1. ``map_batches(fuse=False)`` makes a stage its own pipeline operator —
   its tasks overlap upstream ingest instead of fusing into it.
2. ``compile_chain`` pre-wires actor methods with shared-memory channels:
   repeated executions pay zero per-call control-plane traffic.

Run: JAX_PLATFORMS=cpu python examples/08_streaming_and_channels.py
"""

import time

import numpy as np

import ray_tpu
import ray_tpu.data as rd
from ray_tpu.experimental.channels import compile_chain, enable_channels


def main() -> None:
    ray_tpu.init(num_cpus=4)

    # ---- 1. streaming pipeline: slow ingest overlaps a slow map stage
    def featurize(batch):
        time.sleep(0.05)  # pretend this is CPU-heavy
        batch["z"] = batch["id"].astype(np.float64) / 100.0
        return batch

    t0 = time.perf_counter()
    ds = rd.range(4000, override_num_blocks=8) \
        .map_batches(featurize, fuse=False)   # its own pipeline operator
    total = sum(float(b["z"].sum()) for b in ds.iter_batches(batch_size=500))
    print(f"pipelined dataset: sum={total:.1f} "
          f"wall={time.perf_counter() - t0:.2f}s "
          f"(stages ran concurrently)")

    # ---- 2. compiled actor chain: tokenizer -> model -> postprocess
    @ray_tpu.remote
    @enable_channels
    class Tokenize:
        def f(self, text):
            return np.array([ord(c) % 97 for c in text], np.int32)

    @ray_tpu.remote
    @enable_channels
    class Score:
        def f(self, toks):
            return float((toks * toks).mean())

    @ray_tpu.remote
    @enable_channels
    class Label:
        def f(self, score):
            return "long-word-ish" if score > 500 else "short-word-ish"

    chain = compile_chain([(Tokenize.remote(), "f"),
                           (Score.remote(), "f"),
                           (Label.remote(), "f")])
    try:
        print("chain('hello'):", chain.execute("hello"))
        # pipelined: all three stages busy across in-flight requests
        t0 = time.perf_counter()
        for w in ["alpha", "beta", "gamma", "delta", "epsilon"] * 10:
            chain.execute_async(w)
        outs = [chain.result() for _ in range(50)]
        print(f"50 chained inferences in "
              f"{(time.perf_counter() - t0) * 1e3:.0f}ms "
              f"({outs[0]}, ...)")
    finally:
        chain.teardown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
