"""RLlib: PPO on CartPole, then a two-policy multi-agent variant."""
import ray_tpu
from ray_tpu.rllib import PPOConfig, make_multi_agent

ray_tpu.init(num_cpus=4)

# --- single-agent PPO
algo = (PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_workers=1, num_envs_per_worker=4,
                  rollout_fragment_length=128)
        .training(train_batch_size=512, sgd_minibatch_size=128,
                  num_sgd_iter=4, lr=3e-4, fcnet_hiddens=(64, 64))
        .debugging(seed=0)
        .build())
for i in range(3):
    r = algo.train()
    print(f"iter {i}: reward_mean={r['episode_reward_mean']:.1f} "
          f"steps={r['timesteps_total']}")
algo.stop()

# --- multi-agent: two independent learners share one env
ma_env = make_multi_agent("CartPole-v1")
algo = (PPOConfig()
        .environment(ma_env, env_config={"num_agents": 2})
        .rollouts(num_workers=0, rollout_fragment_length=128)
        .training(train_batch_size=256, sgd_minibatch_size=64,
                  num_sgd_iter=2, fcnet_hiddens=(32, 32))
        .multi_agent(
            policies={"p0", "p1"},
            policy_mapping_fn=lambda aid: "p0" if aid == "agent_0" else "p1")
        .build())
r = algo.train()
print("multi-agent info keys:", sorted(r["info"]))
algo.stop()
ray_tpu.shutdown()
