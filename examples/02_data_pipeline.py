"""Data: transforms, shuffle, split, device batches (reference: Ray Data)."""
import numpy as np

import ray_tpu
from ray_tpu import data as rd

ray_tpu.init()

ds = rd.from_items([{"x": float(i), "label": i % 10} for i in range(10_000)])
ds = (ds.map_batches(lambda b: {"x": b["x"] * 2, "label": b["label"]})
        .filter(lambda row: row["label"] != 9)
        .random_shuffle(seed=0)
        .repartition(8))

print("rows:", ds.count(), "schema:", ds.schema())
print("mean x:", ds.mean("x"), "labels:", sorted(ds.unique("label")))

# per-trainer shards (reference: Dataset.split(locality_hints))
shards = ds.split(4)
print("shard sizes:", [s.count() for s in shards])

# batches ready for jax.device_put / a training loop
for batch in ds.iter_batches(batch_size=4096):
    print("batch:", {k: (v.shape, v.dtype) for k, v in batch.items()})
    break

ray_tpu.shutdown()
