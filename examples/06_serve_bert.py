"""Serve: a BERT classifier deployment with batching + autoscaling.

The replica compiles its model in __init__ (warm start — requests never
hit a cold XLA compile) and serves both the handle path and HTTP.
"""
import numpy as np

import ray_tpu
from ray_tpu import serve

ray_tpu.init(num_cpus=4)
serve.start(serve.HTTPOptions(port=8011))


@serve.deployment(num_replicas=1,
                  autoscaling_config={"min_replicas": 1, "max_replicas": 3,
                                      "target_ongoing_requests": 4})
class BertClassifier:
    def __init__(self):
        import jax

        from ray_tpu.models import bert
        self.cfg = bert.tiny()
        self.params = bert.init_params(jax.random.key(0), self.cfg)
        self._jit = jax.jit(
            lambda p, ids: bert.classify(p, ids, self.cfg))
        # warm the compile cache before taking traffic
        self._jit(self.params, np.zeros((1, 16), np.int32))

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.005)
    async def classify_batch(self, ids_list):
        ids = np.stack(ids_list)
        logits = np.asarray(self._jit(self.params, ids))
        return [int(x) for x in logits.argmax(-1)]

    async def __call__(self, request):
        ids = np.asarray(request if not isinstance(request, serve.Request)
                         else request.json()["ids"], np.int32)
        return await self.classify_batch(ids)


handle = serve.run(BertClassifier.bind(), route_prefix="/classify")
ids = np.random.default_rng(0).integers(0, 100, (16,)).astype(np.int32)
print("prediction:", handle.remote(ids).result())
serve.shutdown()
ray_tpu.shutdown()
