"""Multi-host: a second host joins the cluster and runs tasks + actors.

Production shape:
  host A (head):   python -m ray_tpu start --client-server-port 10001
  host B (worker): RTPU_AUTH_KEY=<hex>  \
                   python -m ray_tpu join --address hostA:10001

This example simulates host B with a NodeAgent subprocess on localhost —
the transport (TCP tunnel, HMAC auth, tcp:// actor channels) is identical.
"""
import os
import subprocess
import sys
import time

import ray_tpu
from ray_tpu.util import state
from ray_tpu.util.client import ClientProxyServer
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy
from ray_tpu._private import worker as worker_mod

ray_tpu.init()

session = worker_mod.global_worker().session
proxy = ClientProxyServer(session, host="127.0.0.1", port=0)
port = proxy._listener.address[1]
env = dict(os.environ, RTPU_AUTH_KEY=session.auth_key().hex())
env.pop("RTPU_SESSION_DIR", None)
agent = subprocess.Popen(
    [sys.executable, "-m", "ray_tpu._private.node_agent",
     "--address", f"127.0.0.1:{port}", "--num-cpus", "2"], env=env)

# wait for the remote node to register
node_id = None
deadline = time.time() + 60
while time.time() < deadline and node_id is None:
    for n in state.list_nodes():
        if n["labels"].get("agent") == "1" and n["alive"]:
            node_id = n["node_id"]
    time.sleep(0.2)
print("remote node:", node_id)

pin = NodeAffinitySchedulingStrategy(node_id)


@ray_tpu.remote(scheduling_strategy=pin)
def where():
    return os.getpid()


@ray_tpu.remote(scheduling_strategy=pin)
class RemoteCounter:
    def __init__(self):
        self.n = 0

    def add(self):
        self.n += 1
        return self.n


print("remote task pid:", ray_tpu.get(where.remote(), timeout=60))
c = RemoteCounter.remote()
print("remote actor counts:", ray_tpu.get([c.add.remote() for _ in range(3)],
                                          timeout=60))

agent.terminate()
agent.wait(timeout=30)
proxy.stop()
ray_tpu.shutdown()
