"""Tune: random search + ASHA early stopping (reference: Ray Tune)."""
import numpy as np

import ray_tpu
from ray_tpu import tune
from ray_tpu.air import RunConfig
from ray_tpu.tune import TuneConfig, Tuner
from ray_tpu.tune.schedulers import ASHAScheduler

ray_tpu.init()


def objective(config):
    # toy objective: converges toward 1/(lr distance from 0.1)
    score = 0.0
    for step in range(20):
        score += max(0.0, 1.0 - abs(config["lr"] - 0.1) * 10)
        score += np.random.default_rng(step).normal(0, 0.05)
        tune.report({"score": score, "training_iteration": step + 1})


results = Tuner(
    objective,
    param_space={"lr": tune.loguniform(1e-3, 1.0),
                 "batch": tune.choice([16, 32, 64])},
    tune_config=TuneConfig(
        metric="score", mode="max", num_samples=12,
        scheduler=ASHAScheduler(metric="score", mode="max", max_t=20,
                                grace_period=4)),
    run_config=RunConfig(storage_path="/tmp/rtpu_example_tune"),
).fit()

best = results.get_best_result("score", "max")
print("best config:", best.metrics["config"], "score:",
      round(best.metrics["score"], 2))
ray_tpu.shutdown()
