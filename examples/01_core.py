"""Core API: tasks, objects, actors (reference: ray core walkthrough)."""
import numpy as np

import ray_tpu

ray_tpu.init()


# --- tasks: python functions running in parallel worker processes
@ray_tpu.remote
def square(x):
    return x * x


print("squares:", ray_tpu.get([square.remote(i) for i in range(8)]))


# --- objects: immutable values in shared memory, zero-copy reads
big = np.arange(1_000_000, dtype=np.float64)
ref = ray_tpu.put(big)


@ray_tpu.remote
def total(arr):          # arr is a zero-copy view onto the store
    return float(arr.sum())


print("sum:", ray_tpu.get(total.remote(ref)))


# --- actors: stateful workers with ordered method calls
@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0

    def add(self, k=1):
        self.n += k
        return self.n


c = Counter.remote()
futures = [c.add.remote() for _ in range(5)]
print("counter:", ray_tpu.get(futures))

# --- wait: first-completed consumption
fast, slow = ray_tpu.wait([square.remote(i) for i in range(4)],
                          num_returns=2)
print("first two done:", ray_tpu.get(fast))

ray_tpu.shutdown()
