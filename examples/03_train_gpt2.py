"""Train: data-parallel GPT-2 with JaxTrainer (reference: TorchTrainer).

Each worker builds the one-jit SPMD train program over its local devices;
metrics and checkpoints stream back through train.report.  On a pod slice
set ``ScalingConfig(topology="v4-32")`` — one worker per host, meshes
assembled by the JaxConfig backend via jax.distributed.
"""
import numpy as np

import ray_tpu
from ray_tpu import train
from ray_tpu.air import RunConfig, ScalingConfig
from ray_tpu.train import JaxTrainer

ray_tpu.init(num_cpus=4)


def train_loop(config):
    import jax

    from ray_tpu.models import gpt2
    from ray_tpu.parallel import mesh as mesh_lib, spmd
    from ray_tpu.parallel.mesh import MeshConfig

    cfg = gpt2.tiny(vocab=512, seq=128)
    mc = MeshConfig(data=1).resolved(len(jax.local_devices()))
    mesh = mesh_lib.build_mesh(mc, jax.local_devices())
    prog = spmd.build_train_program(
        loss_fn=lambda p, b: gpt2.loss_fn(p, b, cfg),
        init_params_fn=lambda r: gpt2.init_params(r, cfg),
        mesh=mesh, mesh_config=mc)
    state = prog.init_fn(jax.random.key(0))
    rng = np.random.default_rng(train.get_context().get_world_rank())
    for step in range(config["steps"]):
        toks = rng.integers(0, cfg.vocab_size, (8, 129)).astype(np.int32)
        batch = spmd.shard_batch(prog, {"inputs": toks[:, :-1],
                                        "targets": toks[:, 1:]})
        state, metrics = prog.step_fn(state, batch)
        train.report({"step": step, "loss": float(metrics["loss"])})


trainer = JaxTrainer(
    train_loop,
    train_loop_config={"steps": 5},
    scaling_config=ScalingConfig(num_workers=2),
    run_config=RunConfig(storage_path="/tmp/rtpu_example_train"))
result = trainer.fit()
print("final:", result.metrics)
ray_tpu.shutdown()
