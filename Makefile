# Convenience targets; everything here is also runnable through pytest.

PY ?= python

.PHONY: test sanitize fuzz bench lint rtlint jaxlint xlacheck \
	check-metrics microbench-quick \
	databench-quick servebench-quick llmbench-quick tracebench-quick \
	releasebench-quick fleetbench-quick obsbench-quick \
	profbench-quick failoverbench-quick trainbench-quick leakcheck

test:
	$(PY) -m pytest tests/ -x -q

# Lint gate (SURVEY.md §4 CI row): dependency-free flake8/clang-format
# stand-in — ast checks for Python, g++ -fsyntax-only -Wall for C++ —
# plus rtlint in incremental mode: passes whose git-changed input set
# is empty are skipped (interprocedural passes still run over their
# full inputs when any input moved — partial summaries are unsound).
# CI and `make rtlint` run the full tree.  Incremental timings on
# this tree (13 passes): full 8.1s, doc-only change 0.07s ("running
# nothing"), one-file util/ change 4.6s (6 of 13 passes; the §4q
# compute-plane passes only wake when ops/models/parallel/serve-llm/
# bench inputs move).
lint: jaxlint
	$(PY) tools/lint.py
	$(PY) -m tools.rtlint --changed-only

# rtlint (DESIGN.md §4d/§4f/§4p/§4q): machine-enforces the GCS locking
# discipline (lock-order DAG, no blocking under leaf locks),
# guarded-field annotations, wire-protocol exhaustiveness,
# spawned-thread hygiene, metrics-catalog honesty, resource lifecycle
# (close/transfer on every exit path incl. exception edges), wire
# reply discipline (exactly-one-reply per two-way dispatch arm),
# interprocedural blocking-flow (REACTOR_SAFE / hot-arm / bounded-
# timeout policies + the BLOCK_BOUNDS static==runtime identity),
# session-FSM conformance over the old x new version matrix, and the
# compute-plane jaxlint passes (§4q: donation discipline, retrace
# triggers, host-sync freedom of step paths, mesh-axis/activation-rule
# drift).
# Fixture corpus: tests/rtlint_fixtures/.  `--list-rules` prints the
# catalog.  `--waiver-audit` (CI) additionally fails on stale waivers.
rtlint:
	$(PY) -m tools.rtlint

# Compute-plane passes alone (DESIGN.md §4q): donation / retrace /
# host-sync / mesh-axes over ray_tpu/{ops,models,parallel,serve/llm}
# and the benches, pinned to the lock_watchdog.py declaration tables
# (STEP_PATHS / DONATED / COMPILE_BUDGETS) and mesh.py's AXES /
# ACTIVATION_RULES.  Also rides `make lint` and full `make rtlint`.
jaxlint:
	$(PY) -m tools.rtlint --pass donation --pass retrace \
		--pass hostsync --pass meshaxes

# Runtime half of the §4q contract (the XLA hygiene oracle): the
# train-step + LLM-engine suite under RAY_TPU_XLA_WATCHDOG=1 — zero
# host transfers inside step regions, zero steady-state recompiles
# over the declared COMPILE_BUDGETS, injected violations raise with
# site + stack (leakcheck pattern).
xlacheck:
	JAX_PLATFORMS=cpu RAY_TPU_XLA_WATCHDOG=1 $(PY) -m pytest \
		tests/test_xla_watchdog.py -q -x

# Runtime half of the resource pass (DESIGN.md §4f): the leak-hammer
# suite under RAY_TPU_RESOURCE_SANITIZER=1 — N pulls/tasks/actor churns
# through a live cluster, then assert zero net leaked
# sockets/fds/mmaps/threads/conns at clean shutdown (acquisition stacks
# reported otherwise).
leakcheck:
	JAX_PLATFORMS=cpu RAY_TPU_RESOURCE_SANITIZER=1 $(PY) -m pytest \
		tests/test_resource_sanitizer.py -q -x

# Every built-in rtpu_* metric used in the tree must be declared in
# ray_tpu/util/metrics_catalog.py — and every declared one must be live
# (rtlint's metrics pass; also runs as part of `make lint`/`rtlint`).
check-metrics:
	$(PY) -m tools.rtlint --pass metrics

# ASAN + TSAN over the native slab store (SURVEY.md §5.2): longer runs
# than the in-suite smoke (tests/test_native_sanitizers.py).
sanitize:
	RTPU_SANITIZE_SECONDS=20 $(PY) -m pytest \
		tests/test_native_sanitizers.py -q -x

# Seedable protocol fuzz (lease/refcount/lineage state machines) at
# multi-million-step depth (the in-suite run uses a smaller budget).
fuzz:
	RTPU_SIM_STEPS=2000000 $(PY) -m pytest \
		tests/test_protocol_sim.py -q -x

bench:
	$(PY) bench.py

# Control-plane microbenchmark smoke (CI): --quick scale, asserts
# completion + sane serial-RT latency bounds, and leaves a JSON artifact
# (benchmarks/results/microbench_ci.json) for the uploader.
microbench-quick:
	JAX_PLATFORMS=cpu $(PY) -m ray_tpu.scripts.cli microbenchmark --quick \
		--assert-sane --json benchmarks/results/microbench_ci.json \
		--label ci

# Data-plane transfer smoke (CI): same-run A/B of the streamed pooled
# pull vs the in-tree legacy (fresh-dial chunked) path, asserts the
# streamed path isn't slower + the warm pool beats dial-per-pull, and
# leaves a JSON artifact for the uploader.
databench-quick:
	JAX_PLATFORMS=cpu $(PY) benchmarks/data_bench.py --pull --quick \
		--assert-sane --json benchmarks/results/databench_ci.json \
		--label ci

# Serve data-path smoke (CI): tiny BERT through the real controller →
# router → replica path, scale-up + replica-kill recovery asserted,
# JSON artifact for the uploader.
servebench-quick:
	JAX_PLATFORMS=cpu $(PY) benchmarks/serve_bench.py --quick \
		--assert-sane --json benchmarks/results/servebench_ci.json \
		--label ci

# Tracing-overhead smoke (CI): serial task RTs with the always-on
# observability layer (timeline + flight recorder + wire trace field at
# default sampling) vs fully off, interleaved A/B in one process;
# asserts <5% overhead and leaves a JSON artifact for the uploader.
tracebench-quick:
	JAX_PLATFORMS=cpu $(PY) benchmarks/trace_bench.py --quick \
		--assert-sane --json benchmarks/results/tracebench_ci.json \
		--label ci

# Raylet lease-protocol smoke (CI): 2 simulated nodes (NodeAgent
# processes with per-node local schedulers) on this host running the
# many_tasks workload with fixed simulated work; asserts completion and
# that the fleet actually parallelizes (>1 effective worker slot).
# The committed full-scale artifact (release_suite_r10.json, --nodes-ab)
# shows the node-count scaling claim.
releasebench-quick:
	JAX_PLATFORMS=cpu $(PY) benchmarks/release_suite.py --nodes 2 \
		--node-cpus 2 --tasks 60 --task-ms 10 --assert-sane \
		--json benchmarks/results/releasebench_ci.json --label ci

# Fleet elasticity smoke (CI): seeded preemption trace over the
# 100-simulated-node fleet against the real autoscaler bin-packing
# loop; asserts determinism from the seed, zero stranded demand, zero
# double-placements, and elastic re-mesh >= 2x the restart-from-
# checkpoint goodput.  The second run is the closed-loop autopilot A/B
# (DESIGN.md §4n): the same weather plus degradation episodes, the
# real reflex engine actuating — asserts the autopilot beats the
# reactive ratio, drains stay inside the rate budget (zero actuation
# storms), and the forecast reflex reduces demand lag.  Committed
# full-scale artifacts: benchmarks/results/fleet_bench_r11.json
# (reactive), fleet_bench_r15.json (closed loop).
fleetbench-quick:
	JAX_PLATFORMS=cpu $(PY) benchmarks/fleet_bench.py --quick \
		--assert-sane --json benchmarks/results/fleetbench_ci.json \
		--label ci
	JAX_PLATFORMS=cpu $(PY) benchmarks/fleet_bench.py --quick \
		--closed-loop --assert-sane \
		--json benchmarks/results/fleetbench_ci.json --label ci-closed

# Observability-history smoke (CI): serial task RTs with the head TSDB
# ingesting every snapshot + detectors ticking + live metrics_query
# traffic vs tsdb_enabled=0, interleaved A/B in one process; asserts
# <5% overhead on the serial-RT floor and leaves a JSON artifact for
# the uploader.  The committed full-scale artifact is
# benchmarks/results/obs_bench_r12.json.
obsbench-quick:
	JAX_PLATFORMS=cpu $(PY) benchmarks/obs_bench.py --quick \
		--assert-sane --json benchmarks/results/obsbench_ci.json \
		--label ci

# Continuous-profiler smoke (CI): serial task RTs with every process
# sampling at 10Hz + deltas riding the metrics cadence + live
# profile_query traffic vs profiler_enabled=0, interleaved A/B in one
# process; asserts <5% overhead on the serial-RT floor and leaves a
# JSON artifact for the uploader.  The committed full-scale artifact
# is benchmarks/results/prof_bench_r16.json.
profbench-quick:
	JAX_PLATFORMS=cpu $(PY) benchmarks/prof_bench.py --quick \
		--assert-sane --json benchmarks/results/profbench_ci.json \
		--label ci

# Head-failover smoke (CI): SIGKILL the primary GCS with a warm
# standby attached and tasks in flight; asserts ZERO lost tasks on
# every trial and sub-second promote-to-first-settled-task (best of
# <=3 trials — shared runners jitter), JSON artifact for the uploader.
# The committed full-scale artifact is
# benchmarks/results/failover_bench_r13.json.
failoverbench-quick:
	JAX_PLATFORMS=cpu $(PY) benchmarks/failover_bench.py --quick \
		--assert-sane --json benchmarks/results/failoverbench_ci.json \
		--label ci

# Overlap-scheduled train-step smoke (CI): interleaved A/B of the
# decomposed-collective-matmul + sequence-parallel step vs the
# un-overlapped GSPMD step on the same (data, seq, tensor) mesh;
# asserts loss-trajectory parity and (where device traces exist) that
# the overlapped step exposes no more collective time than the
# baseline.  The committed full-scale artifact is
# benchmarks/results/overlap_bench_r14.json.
trainbench-quick:
	JAX_PLATFORMS=cpu $(PY) benchmarks/train_bench.py --quick \
		--assert-sane --json benchmarks/results/trainbench_ci.json \
		--label ci

# LLM serving smoke (CI): the continuous-batching engine vs the naive
# request-level baseline on one seeded diurnal+burst trace; asserts the
# engine completes every request and does not lose to the baseline
# (the committed full-scale artifact shows the 2x goodput target).
llmbench-quick:
	JAX_PLATFORMS=cpu $(PY) benchmarks/llm_bench.py --ab --quick \
		--assert-sane --json benchmarks/results/llmbench_ci.json \
		--label ci
