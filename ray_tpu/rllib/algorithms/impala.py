"""IMPALA: async actor-learner with V-trace off-policy correction.

Reference: ``rllib/algorithms/impala/`` (SURVEY.md §3.5) — rollout actors
continuously push batches to a learner queue; the learner applies V-trace
(Espeholt et al. 2018) to correct for policy lag, then broadcasts weights.
Rebuilt: the "queue" is the object store — each worker keeps exactly one
in-flight ``sample_with_weights`` future; the learner drains ready futures
with ``ray_tpu.wait`` and re-issues them carrying the freshest weights ref,
so sampling and the jitted learner step overlap without a learner thread.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.sample_batch import (
    ACTION_LOGP, ACTIONS, NEXT_OBS, OBS, REWARDS, SampleBatch, TERMINATEDS,
    TRUNCATEDS)


def vtrace(behavior_logp, target_logp, rewards, discounts, values,
           bootstrap_value, clip_rho: float = 1.0, clip_c: float = 1.0):
    """V-trace targets + policy-gradient advantages.

    All inputs time-major ``[T, B]``; ``bootstrap_value`` is ``[B]``.
    Returns ``(vs [T,B], pg_advantages [T,B])``.
    """
    rhos = jnp.exp(target_logp - behavior_logp)
    clipped_rhos = jnp.minimum(clip_rho, rhos)
    cs = jnp.minimum(clip_c, rhos)
    values_next = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * values_next - values)

    def backward(acc, t):
        delta, discount, c = t
        acc = delta + discount * c * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        backward, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, cs), reverse=True)
    vs = values + vs_minus_v
    vs_next = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = clipped_rhos * (rewards + discounts * vs_next - values)
    return vs, pg_adv


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or IMPALA)
        self._cfg.update({
            "lr": 5e-4, "num_workers": 2, "rollout_fragment_length": 50,
            "vtrace_clip_rho_threshold": 1.0,
            "vtrace_clip_pg_rho_threshold": 1.0,
            "vf_loss_coeff": 0.5, "entropy_coeff": 0.01, "grad_clip": 40.0,
            "num_batches_per_iteration": 10,
        })


class IMPALA(Algorithm):
    _default_config_cls = IMPALAConfig

    def setup(self, config: Dict[str, Any]) -> None:
        policy = self.workers.local_worker.policy
        apply_fn = policy.apply_fn
        dist = policy.dist_class
        self._optimizer = optax.chain(
            optax.clip_by_global_norm(config["grad_clip"]),
            optax.rmsprop(config["lr"], decay=0.99, eps=0.1))
        self._opt_state = self._optimizer.init(policy.params)
        gamma = float(config["gamma"])
        clip_rho = float(config["vtrace_clip_rho_threshold"])
        vf_coeff = float(config["vf_loss_coeff"])
        ent_coeff = float(config["entropy_coeff"])
        optimizer = self._optimizer

        def loss_fn(params, batch):
            # batch cols are [T, B, ...]; flatten for the net, reshape back.
            T, B = batch[REWARDS].shape
            obs = batch[OBS].reshape((T * B,) + batch[OBS].shape[2:])
            inputs, values = apply_fn(params, obs)
            actions = batch[ACTIONS].reshape((T * B,))
            target_logp = dist.logp(inputs, actions).reshape((T, B))
            entropy = dist.entropy(inputs).mean()
            values = values.reshape((T, B))
            last_obs = batch[NEXT_OBS][-1]
            _, bootstrap = apply_fn(params, last_obs)
            discounts = gamma * (1.0 - batch["dones"])
            vs, pg_adv = vtrace(
                batch[ACTION_LOGP], target_logp, batch[REWARDS],
                discounts, values, bootstrap, clip_rho, clip_rho)
            vs = jax.lax.stop_gradient(vs)
            pg_adv = jax.lax.stop_gradient(pg_adv)
            pi_loss = -(target_logp * pg_adv).mean()
            vf_loss = 0.5 * jnp.square(vs - values).mean()
            total = pi_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, (pi_loss, vf_loss, entropy)

        def update(params, opt_state, batch):
            grads, aux = jax.grad(loss_fn, has_aux=True)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            pi_loss, vf_loss, entropy = aux
            return params, opt_state, {
                "policy_loss": pi_loss, "vf_loss": vf_loss,
                "entropy": entropy}

        self._update = jax.jit(update)
        self._in_flight: Dict[Any, Any] = {}  # future -> worker
        self._trained_steps = 0

    def _to_time_major(self, batch: SampleBatch) -> Dict[str, jnp.ndarray]:
        """Worker fragments arrive env-major ([env0 t0..T, env1 t0..T, ...]);
        reshape to [T, B] for vtrace."""
        T = int(self.config["rollout_fragment_length"])
        B = batch.count // T
        out = {}
        for k in (OBS, ACTIONS, REWARDS, ACTION_LOGP, NEXT_OBS):
            v = batch[k][:B * T]
            out[k] = jnp.asarray(
                v.reshape((B, T) + v.shape[1:]).swapaxes(0, 1))
        dones = (batch[TERMINATEDS] | batch[TRUNCATEDS])[:B * T]
        out["dones"] = jnp.asarray(
            dones.reshape((B, T)).swapaxes(0, 1).astype(np.float32))
        return out

    def _learn_on(self, batch: SampleBatch) -> Dict[str, float]:
        policy = self.workers.local_worker.policy
        tm = self._to_time_major(batch)
        policy.params, self._opt_state, info = self._update(
            policy.params, self._opt_state, tm)
        self._trained_steps += batch.count
        return {k: float(v) for k, v in info.items()}

    def training_step(self) -> Dict[str, Any]:
        remotes = self.workers.remote_workers
        n_batches = int(self.config["num_batches_per_iteration"])
        info: Dict[str, float] = {}
        if not remotes:  # degenerate sync mode for tests
            for _ in range(n_batches):
                info = self._learn_on(self.workers.local_worker.sample())
            info["num_env_steps_trained"] = self._trained_steps
            return info
        # Prime one in-flight sample per worker.
        weights_ref = ray_tpu.put(
            self.workers.local_worker.get_weights())
        for w in remotes:
            if w not in [v for v in self._in_flight.values()]:
                self._in_flight[w.sample_with_weights.remote(
                    weights_ref)] = w
        processed = 0
        while processed < n_batches:
            ready, _ = ray_tpu.wait(list(self._in_flight),
                                    num_returns=1)
            fut = ready[0]
            worker = self._in_flight.pop(fut)
            batch = ray_tpu.get(fut)
            info = self._learn_on(batch)
            processed += 1
            # Re-issue immediately with the freshest weights.
            weights_ref = ray_tpu.put(
                self.workers.local_worker.get_weights())
            self._in_flight[worker.sample_with_weights.remote(
                weights_ref)] = worker
        info["num_env_steps_trained"] = self._trained_steps
        return info
