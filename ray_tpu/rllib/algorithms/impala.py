"""IMPALA: async actor-learner with V-trace off-policy correction.

Reference: ``rllib/algorithms/impala/`` (SURVEY.md §3.5) — rollout actors
continuously push batches to a learner queue; the learner applies V-trace
(Espeholt et al. 2018) to correct for policy lag, then broadcasts weights.
Rebuilt: the "queue" is the object store — each worker keeps exactly one
in-flight ``sample_with_weights`` future; the learner drains ready futures
with ``ray_tpu.wait`` and re-issues them carrying the freshest weights ref,
so sampling and the jitted learner step overlap without a learner thread.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.sample_batch import (
    ACTION_LOGP, ACTIONS, NEXT_OBS, OBS, REWARDS, SampleBatch, TERMINATEDS,
    TRUNCATEDS, concat_samples)


def vtrace(behavior_logp, target_logp, rewards, discounts, values,
           bootstrap_value, clip_rho: float = 1.0, clip_c: float = 1.0,
           clip_pg_rho: float = None):
    """V-trace targets + policy-gradient advantages.

    All inputs time-major ``[T, B]``; ``bootstrap_value`` is ``[B]``.
    Returns ``(vs [T,B], pg_advantages [T,B])``.  ``clip_pg_rho`` clips the
    importance weights of the pg advantages separately from the value
    targets (reference: vtrace_clip_pg_rho_threshold); defaults to
    ``clip_rho``.
    """
    rhos = jnp.exp(target_logp - behavior_logp)
    clipped_rhos = jnp.minimum(clip_rho, rhos)
    pg_rhos = jnp.minimum(
        clip_rho if clip_pg_rho is None else clip_pg_rho, rhos)
    cs = jnp.minimum(clip_c, rhos)
    values_next = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rhos * (rewards + discounts * values_next - values)

    def backward(acc, t):
        delta, discount, c = t
        acc = delta + discount * c * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        backward, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, cs), reverse=True)
    vs = values + vs_minus_v
    vs_next = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = pg_rhos * (rewards + discounts * vs_next - values)
    return vs, pg_adv


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or IMPALA)
        self._cfg.update({
            "lr": 5e-4, "num_workers": 2, "rollout_fragment_length": 50,
            "vtrace_clip_rho_threshold": 1.0,
            "vtrace_clip_pg_rho_threshold": 1.0,
            "vf_loss_coeff": 0.5, "entropy_coeff": 0.01, "grad_clip": 40.0,
            "num_batches_per_iteration": 10,
            # Weight broadcast cadence in learner updates (reference:
            # impala broadcast_interval) — actors run stale-by-at-most-this
            # policies; V-trace corrects the lag.  Weight pull is a full
            # device→host transfer, the learner's most expensive host op.
            "broadcast_interval": 1,
            # Fragments concatenated (along B) per learner update —
            # amortizes per-dispatch overhead into bigger XLA programs
            # (reference: train_batch_size assembly from fragments).
            "num_fragments_per_update": 1,
            # "auto" (default backend) | "cpu".  cpu pins the learner jit
            # and its inputs to host CPU devices: correct when the
            # accelerator interconnect is thinner than the sample stream
            # (e.g. a relay-attached chip at ~10MB/s: pixel fragments
            # upload slower than a host CPU can just learn on them).
            "learner_device": "auto",
            # True = barrier sampling (wait for every worker, then learn)
            # — the A/B control proving the async path's actor/learner
            # overlap (benchmarks/rllib_bench.py impala_overlap).
            "sync_sampling": False,
        })


class IMPALA(Algorithm):
    _default_config_cls = IMPALAConfig

    @staticmethod
    def _policy_surrogate(config):
        """Policy-loss term over (target_logp, behavior_logp, pg_adv) —
        plain V-trace policy gradient here; APPO overrides with the
        clipped PPO surrogate."""
        def pg(target_logp, behavior_logp, pg_adv):
            return -(target_logp * pg_adv).mean()
        return pg

    def setup(self, config: Dict[str, Any]) -> None:
        policy = self.workers.local_worker.policy
        apply_fn = policy.apply_fn
        dist = policy.dist_class
        self._learner_dev = None
        if str(config.get("learner_device", "auto")) == "cpu" \
                and jax.default_backend() != "cpu":
            self._learner_dev = jax.devices("cpu")[0]
            # learner state lives on host: sample ingest skips the
            # accelerator interconnect entirely
            policy.params = jax.device_put(policy.params, self._learner_dev)
        self._optimizer = optax.chain(
            optax.clip_by_global_norm(config["grad_clip"]),
            optax.rmsprop(config["lr"], decay=0.99, eps=0.1))
        self._opt_state = self._optimizer.init(policy.params)
        gamma = float(config["gamma"])
        clip_rho = float(config["vtrace_clip_rho_threshold"])
        clip_pg_rho = float(config["vtrace_clip_pg_rho_threshold"])
        vf_coeff = float(config["vf_loss_coeff"])
        ent_coeff = float(config["entropy_coeff"])
        optimizer = self._optimizer

        surrogate = self._policy_surrogate(config)

        def loss_fn(params, batch):
            # batch cols are [T, B, ...]; flatten for the net, reshape back.
            T, B = batch[REWARDS].shape
            obs = batch[OBS].reshape((T * B,) + batch[OBS].shape[2:])
            inputs, values = apply_fn(params, obs)
            actions = batch[ACTIONS].reshape((T * B,))
            target_logp = dist.logp(inputs, actions).reshape((T, B))
            entropy = dist.entropy(inputs).mean()
            values = values.reshape((T, B))
            _, bootstrap = apply_fn(params, batch["last_obs"])
            discounts = gamma * (1.0 - batch["dones"])
            vs, pg_adv = vtrace(
                batch[ACTION_LOGP], target_logp, batch[REWARDS],
                discounts, values, bootstrap, clip_rho,
                clip_pg_rho=clip_pg_rho)
            vs = jax.lax.stop_gradient(vs)
            pg_adv = jax.lax.stop_gradient(pg_adv)
            pi_loss = surrogate(target_logp, batch[ACTION_LOGP], pg_adv)
            vf_loss = 0.5 * jnp.square(vs - values).mean()
            total = pi_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, (pi_loss, vf_loss, entropy)

        def update(params, opt_state, batch):
            grads, aux = jax.grad(loss_fn, has_aux=True)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            pi_loss, vf_loss, entropy = aux
            return params, opt_state, {
                "policy_loss": pi_loss, "vf_loss": vf_loss,
                "entropy": entropy}

        self._update = jax.jit(update)
        self._in_flight: Dict[Any, Any] = {}  # future -> worker
        self._trained_steps = 0
        self._weights_ref = None
        self._updates_since_broadcast = 0

    def _to_time_major(self, batch: SampleBatch) -> Dict[str, jnp.ndarray]:
        """Worker fragments arrive env-major ([env0 t0..T, env1 t0..T, ...]);
        reshape to [T, B] for vtrace.

        NEXT_OBS is NOT shipped to the device: V-trace only bootstraps from
        the final observation of each env row, so only that [B, ...] slice
        uploads — for pixel fragments this halves learner ingest bytes
        (measured ~10MB/s host→device on the relay-attached chip, making
        ingest the IMPALA throughput ceiling)."""
        T = int(self.config["rollout_fragment_length"])
        B = batch.count // T
        put = (lambda a: jax.device_put(a, self._learner_dev)) \
            if self._learner_dev is not None else jnp.asarray
        out = {}
        for k in (OBS, ACTIONS, REWARDS, ACTION_LOGP):
            v = batch[k][:B * T]
            out[k] = put(v.reshape((B, T) + v.shape[1:]).swapaxes(0, 1))
        next_obs = batch[NEXT_OBS][:B * T]
        out["last_obs"] = put(
            next_obs.reshape((B, T) + next_obs.shape[1:])[:, -1])
        dones = (batch[TERMINATEDS] | batch[TRUNCATEDS])[:B * T]
        out["dones"] = put(
            dones.reshape((B, T)).swapaxes(0, 1).astype(np.float32))
        return out

    def _learn_on(self, batch: SampleBatch) -> Dict[str, Any]:
        """One async learner update; returns device scalars (NOT synced —
        forcing a host read per batch would serialize the device queue on
        the dispatch round-trip, which on a relay-attached chip costs
        100-240ms/sync and caps throughput at a few batches/s)."""
        policy = self.workers.local_worker.policy
        tm = self._to_time_major(batch)
        policy.params, self._opt_state, info = self._update(
            policy.params, self._opt_state, tm)
        self._trained_steps += batch.count
        return info

    def training_step(self) -> Dict[str, Any]:
        remotes = self.workers.remote_workers
        n_batches = int(self.config["num_batches_per_iteration"])
        dev_info: Dict[str, Any] = {}
        if not remotes:  # degenerate sync mode for tests
            for _ in range(n_batches):
                dev_info = self._learn_on(self.workers.local_worker.sample())
            info = {k: float(v) for k, v in dev_info.items()}
            info["num_env_steps_trained"] = self._trained_steps
            return info
        if bool(self.config.get("sync_sampling")):
            # Barrier mode — the A/B control for the actor/learner-overlap
            # benchmark (rllib_bench.py impala_overlap): broadcast, wait
            # for EVERY worker's fragment, learn, repeat.  The async path
            # below re-issues each worker the moment its fragment lands
            # and learns while the others are still sampling.
            from ray_tpu.rllib.evaluation import synchronous_parallel_sample
            for _ in range(n_batches):
                self.workers.sync_weights()
                dev_info = self._learn_on(
                    synchronous_parallel_sample(self.workers))
            info = {k: float(v) for k, v in dev_info.items()}
            info["num_env_steps_trained"] = self._trained_steps
            return info
        # Broadcast at most every `broadcast_interval` updates (reference:
        # IMPALA's broadcast_interval — actors run slightly stale policies
        # and V-trace corrects for the lag).  Pulling params off the device
        # per batch would cost a full device→host transfer + sync RTT per
        # 128-frame fragment.
        interval = max(1, int(self.config.get("broadcast_interval", 1)))
        per_update = max(1, int(self.config.get(
            "num_fragments_per_update", 1)))
        if self._weights_ref is None:
            self._weights_ref = ray_tpu.put(
                self.workers.local_worker.get_weights())
        for w in remotes:
            if w not in [v for v in self._in_flight.values()]:
                self._in_flight[w.sample_with_weights.remote(
                    self._weights_ref)] = w
        processed = 0
        pending: List[SampleBatch] = []
        while processed < n_batches:
            ready, _ = ray_tpu.wait(list(self._in_flight),
                                    num_returns=1)
            fut = ready[0]
            worker = self._in_flight.pop(fut)
            pending.append(ray_tpu.get(fut))
            # Re-issue immediately with the freshest broadcast ref.
            self._in_flight[worker.sample_with_weights.remote(
                self._weights_ref)] = worker
            if len(pending) < per_update:
                continue
            batch = pending[0] if len(pending) == 1 \
                else concat_samples(pending)
            pending = []
            dev_info = self._learn_on(batch)
            processed += 1
            self._updates_since_broadcast += 1
            if self._updates_since_broadcast >= interval:
                self._weights_ref = ray_tpu.put(
                    self.workers.local_worker.get_weights())
                self._updates_since_broadcast = 0
        # Single host sync for the whole iteration's metrics.
        info = {k: float(v) for k, v in dev_info.items()}
        info["num_env_steps_trained"] = self._trained_steps
        return info
