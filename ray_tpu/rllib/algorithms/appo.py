"""APPO: asynchronous PPO (IMPALA runner + clipped surrogate).

Reference: ``rllib/algorithms/appo/`` — the IMPALA architecture (async
actor fleet, V-trace off-policy correction, broadcast-interval weight
staleness) with PPO's clipped importance-ratio surrogate as the policy
loss instead of the plain V-trace policy gradient.  Gets PPO's trust-
region stability without PPO's synchronous sample barrier.

The entire execution path (futures pipeline, time-major reshape,
learner-device placement, sync_sampling A/B control) is inherited from
``IMPALA``; only the policy-surrogate term differs — the ratio uses the
BEHAVIOR logp as the "old" policy, so staleness itself is what gets
clipped.
"""

from __future__ import annotations

import jax.numpy as jnp

from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig


class APPOConfig(IMPALAConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or APPO)
        self._cfg.update({
            "clip_param": 0.3,          # reference APPO default (0.4 torch)
            # APPO leans on the surrogate clip rather than aggressive
            # rho-clipping for stability
            "entropy_coeff": 0.005,
        })


class APPO(IMPALA):
    _default_config_cls = APPOConfig

    @staticmethod
    def _policy_surrogate(config):
        clip = float(config.get("clip_param", 0.3))

        def clipped(target_logp, behavior_logp, pg_adv):
            ratio = jnp.exp(target_logp - behavior_logp)
            return -jnp.minimum(
                ratio * pg_adv,
                jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * pg_adv).mean()
        return clipped
