"""Ape-X: distributed prioritized experience replay DQN (APEX-DQN).

Reference: ``rllib/algorithms/apex_dqn/`` (+ the Ape-X paper's
architecture) — the one reference EXECUTION PATTERN the framework lacked
(VERDICT r3 missing #6): a fleet of replay-buffer ACTORS sits between the
rollout workers and the learner.  Rollout workers (each with its own
exploration epsilon from the Ape-X ladder) stream fragments into replay
shards; the learner pulls prioritized minibatches from the shards, applies
importance-weighted TD updates, and pushes the new TD errors back as
priorities — all three planes overlap through in-flight futures.

TPU-first notes: the learner update is one jitted program (weighted
double-DQN TD) and rollout batches route worker→replay-shard as
ObjectRefs — the driver never materializes fragment data, so on a
multi-host cluster the bytes ride the P2P object plane straight between
the two actors.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.dqn import DQNConfig, DQNPolicy
from ray_tpu.rllib.sample_batch import (
    ACTIONS, NEXT_OBS, OBS, REWARDS, TERMINATEDS)

_REPLAY_KEYS = (OBS, ACTIONS, REWARDS, NEXT_OBS, TERMINATEDS)


class PrioritizedReplay:
    """Proportional prioritized replay over column arrays (one shard).

    Reference: ``rllib/utils/replay_buffers/prioritized_episode_buffer``.
    New entries get the running max priority (optimistic: every sample is
    seen at least once); ``sample`` draws ∝ p^alpha and returns the
    importance weights for beta-annealed bias correction.  Ring overwrite
    between a sample and its priority update can retarget a few indices —
    same benign race the reference's sharded buffers accept.
    """

    def __init__(self, capacity: int, alpha: float = 0.6, seed: int = 0):
        self.capacity = int(capacity)
        self.alpha = float(alpha)
        self._cols: Dict[str, np.ndarray] = {}
        self._prio = np.zeros(self.capacity, np.float64)
        self._idx = 0
        self._size = 0
        self._max_prio = 1.0
        self._rng = np.random.default_rng(seed)

    def add_batch(self, batch) -> int:
        n = int(batch.count if hasattr(batch, "count")
                else len(batch[REWARDS]))
        idx = (self._idx + np.arange(n)) % self.capacity
        for k in _REPLAY_KEYS:
            v = np.asarray(batch[k])
            if k not in self._cols:
                self._cols[k] = np.zeros((self.capacity,) + v.shape[1:],
                                         v.dtype)
            self._cols[k][idx] = v[:n]
        self._prio[idx] = self._max_prio
        self._idx = (self._idx + n) % self.capacity
        self._size = min(self._size + n, self.capacity)
        return self._size

    def sample(self, n: int, beta: float = 0.4):
        """→ (columns dict, indices, importance weights) or None if empty."""
        if self._size == 0:
            return None
        p = self._prio[:self._size] ** self.alpha
        tot = p.sum()
        if tot <= 0:
            probs = np.full(self._size, 1.0 / self._size)
        else:
            probs = p / tot
        idx = self._rng.choice(self._size, size=n, p=probs)
        w = (self._size * probs[idx]) ** (-float(beta))
        w = (w / w.max()).astype(np.float32)
        cols = {k: v[idx] for k, v in self._cols.items()}
        return cols, idx.astype(np.int64), w

    def update_priorities(self, idx, prios) -> None:
        pr = np.abs(np.asarray(prios, np.float64)) + 1e-6
        self._prio[np.asarray(idx)] = pr
        self._max_prio = max(self._max_prio, float(pr.max()))

    def size(self) -> int:
        return self._size


def apex_epsilons(n: int, base: float = 0.4, ladder: float = 7.0
                  ) -> List[float]:
    """The Ape-X exploration ladder: eps_i = base^(1 + i/(N-1)*ladder)."""
    if n <= 1:
        return [base]
    return [float(base ** (1.0 + ladder * i / (n - 1))) for i in range(n)]


class APEXConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or APEX)
        self._cfg.update({
            "num_replay_shards": 2,
            "prioritized_replay_alpha": 0.6,
            "prioritized_replay_beta": 0.4,
            "apex_epsilon_base": 0.4,
            "apex_epsilon_ladder": 7.0,
            "broadcast_interval": 4,       # learner updates per broadcast
            "num_updates_per_iteration": 16,
            "learning_starts": 256,
        })


class APEX(Algorithm):
    _default_config_cls = APEXConfig

    def setup(self, config: Dict[str, Any]) -> None:
        policy: DQNPolicy = self.workers.local_worker.policy
        self._optimizer = optax.adam(config["lr"])
        self._opt_state = self._optimizer.init(policy.params)
        self.target_params = policy.params
        self._since_target = 0
        self._since_broadcast = 0
        self._added = 0
        self._updates = 0
        gamma = float(config["gamma"])
        double_q = bool(config["double_q"])
        q_apply = policy.q_apply
        optimizer = self._optimizer

        def loss_fn(params, target_params, mb):
            q = q_apply(params, mb[OBS])
            q_taken = jnp.take_along_axis(
                q, mb[ACTIONS][:, None].astype(jnp.int32), axis=1)[:, 0]
            q_next_target = q_apply(target_params, mb[NEXT_OBS])
            if double_q:
                best = jnp.argmax(q_apply(params, mb[NEXT_OBS]), axis=-1)
                q_next = jnp.take_along_axis(
                    q_next_target, best[:, None], axis=1)[:, 0]
            else:
                q_next = q_next_target.max(axis=-1)
            target = mb[REWARDS] + gamma * (1.0 - mb["dones"]) * \
                jax.lax.stop_gradient(q_next)
            td = q_taken - target
            # importance-weighted Huber-free TD loss; per-sample |td| out
            # for the priority push-back
            return (mb["is_weights"] * jnp.square(td)).mean(), jnp.abs(td)

        def update(params, target_params, opt_state, mb):
            grads, td = jax.grad(loss_fn, has_aux=True)(
                params, target_params, mb)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, td

        self._update = jax.jit(update)

        n_shards = int(config["num_replay_shards"])
        alpha = float(config["prioritized_replay_alpha"])
        if self.workers.remote_workers:
            cap = int(config["buffer_size"]) // max(1, n_shards)
            replay_cls = ray_tpu.remote(PrioritizedReplay).options(num_cpus=0)
            self.replay_shards = [
                replay_cls.remote(cap, alpha, seed=i)
                for i in range(n_shards)]
            # exploration ladder: one epsilon per rollout worker, set once;
            # later broadcasts are params-only and preserve it
            eps = apex_epsilons(len(self.workers.remote_workers),
                                float(config["apex_epsilon_base"]),
                                float(config["apex_epsilon_ladder"]))
            params = policy.get_weights()["params"]
            ray_tpu.get([
                w.set_weights.remote({"params": params, "epsilon": e})
                for w, e in zip(self.workers.remote_workers, eps)])
        else:  # degenerate single-process mode (tests)
            self.replay_shards = []
            # the sharded capacity split only makes sense for the fleet:
            # one local buffer gets the user's FULL configured size
            self._local_replay = PrioritizedReplay(
                int(config["buffer_size"]), alpha)
        self._sample_futs: Dict[Any, Any] = {}   # worker sample futures
        self._replay_futs: Dict[Any, int] = {}   # shard sample futures
        # shards whose last sample() came back empty: re-issued only after
        # the next add_batch routes to them (a blind re-issue would spin
        # the wait→sample RPC loop at full speed against an empty shard)
        self._shard_idle: set = set()
        self._route_rr = 0
        self._weights_ref = None

    def stop(self) -> None:
        for shard in getattr(self, "replay_shards", ()):
            try:
                ray_tpu.kill(shard)
            except Exception:  # noqa: BLE001 - already dead
                pass
        self.replay_shards = []
        super().stop()

    # ------------------------------------------------------------- learner
    def _device_mb(self, cols: Dict[str, np.ndarray], w: np.ndarray):
        return {
            OBS: jnp.asarray(cols[OBS], jnp.float32),
            ACTIONS: jnp.asarray(cols[ACTIONS]),
            REWARDS: jnp.asarray(cols[REWARDS], jnp.float32),
            NEXT_OBS: jnp.asarray(cols[NEXT_OBS], jnp.float32),
            "dones": jnp.asarray(cols[TERMINATEDS].astype(np.float32)),
            "is_weights": jnp.asarray(w, jnp.float32),
        }

    def _learn(self, cols, idx, w, shard=None) -> Dict[str, Any]:
        policy = self.workers.local_worker.policy
        policy.params, self._opt_state, td = self._update(
            policy.params, self.target_params, self._opt_state,
            self._device_mb(cols, w))
        self._updates += 1
        self._since_target += 1
        self._since_broadcast += 1
        td_host = np.asarray(td)
        if shard is not None:
            shard.update_priorities.remote(idx, td_host)  # fire-and-forget
        else:
            self._local_replay.update_priorities(idx, td_host)
        if self._since_target >= int(
                self.config["target_network_update_freq"]):
            self.target_params = policy.params
            self._since_target = 0
        return {"mean_td_error": float(td_host.mean())}

    def _maybe_broadcast(self) -> None:
        if self._since_broadcast < int(self.config["broadcast_interval"]):
            return
        self._since_broadcast = 0
        # params-only: workers keep their ladder epsilons
        self._weights_ref = ray_tpu.put(
            {"params": self.workers.local_worker.policy.get_weights()
             ["params"]})

    # ------------------------------------------------------------- stepping
    def training_step(self) -> Dict[str, Any]:
        if not self.workers.remote_workers:
            return self._training_step_local()
        cfg = self.config
        frag = int(cfg["rollout_fragment_length"]) * \
            int(cfg.get("num_envs_per_worker", 1))
        n_updates = int(cfg["num_updates_per_iteration"])
        batch_size = int(cfg["train_batch_size"])
        beta = float(cfg["prioritized_replay_beta"])
        info: Dict[str, Any] = {}
        # keep one sample in flight per rollout worker
        for w in self.workers.remote_workers:
            if w not in self._sample_futs.values():
                self._sample_futs[w.sample_with_weights.remote(
                    self._weights_ref)] = w
        done_updates = 0
        warm = self._added >= int(cfg["learning_starts"])
        # keep one prioritized sample in flight per shard once warm
        # (parked shards stay parked — an add_batch routing to them wakes
        # them below; re-issuing here would stack a second sample chain
        # on the same shard)
        if warm:
            for i, shard in enumerate(self.replay_shards):
                if i not in self._replay_futs.values() \
                        and i not in self._shard_idle:
                    self._replay_futs[shard.sample.remote(
                        batch_size, beta)] = i
        while done_updates < n_updates:
            futs = list(self._sample_futs) + list(self._replay_futs)
            if not futs:
                break
            ready, _ = ray_tpu.wait(futs, num_returns=1)
            fut = ready[0]
            if fut in self._sample_futs:
                worker = self._sample_futs.pop(fut)
                # route the fragment REF to a shard — data never lands on
                # the driver (worker→shard direct on multi-host planes)
                si = self._route_rr % len(self.replay_shards)
                self._route_rr += 1
                self.replay_shards[si].add_batch.remote(fut)
                self._added += frag
                self._sample_futs[worker.sample_with_weights.remote(
                    self._weights_ref)] = worker
                if not warm and self._added >= int(cfg["learning_starts"]):
                    warm = True
                    for i, shard in enumerate(self.replay_shards):
                        self._replay_futs[shard.sample.remote(
                            batch_size, beta)] = i
                elif warm and si in self._shard_idle:
                    # data just routed to a drained shard: wake it
                    self._shard_idle.discard(si)
                    self._replay_futs[self.replay_shards[si].sample.remote(
                        batch_size, beta)] = si
            else:
                i = self._replay_futs.pop(fut)
                shard = self.replay_shards[i]
                out = ray_tpu.get(fut)
                if out is not None:
                    # a stale park flag here would let the next routed
                    # fragment wake the shard into a SECOND chain
                    self._shard_idle.discard(i)
                    cols, idx, w = out
                    info.update(self._learn(cols, idx, w, shard))
                    done_updates += 1
                    self._maybe_broadcast()
                    self._replay_futs[shard.sample.remote(
                        batch_size, beta)] = i
                else:
                    # empty shard: park it until an add_batch routes here
                    # (an immediate re-issue would spin the RPC loop)
                    self._shard_idle.add(i)
            if not warm and not self._sample_futs:
                break
            if not warm and done_updates == 0 and \
                    self._added >= n_updates * frag * 4:
                break  # pure warmup iteration: don't loop forever
        info.update({
            "num_env_steps_sampled": self._added,
            "learner_updates": self._updates,
            "replay_shards": len(self.replay_shards),
        })
        return info

    def _training_step_local(self) -> Dict[str, Any]:
        cfg = self.config
        policy = self.workers.local_worker.policy
        # single-process mode has no exploration ladder: anneal epsilon
        # like DQN does (without this the behavior policy would stay at
        # initial_epsilon=1.0 — uniform-random — forever)
        frac = min(1.0, self._added / float(cfg["epsilon_timesteps"]))
        policy.epsilon = float(
            cfg["initial_epsilon"] + frac *
            (cfg["final_epsilon"] - cfg["initial_epsilon"]))
        batch = self.workers.local_worker.sample()
        self._added += batch.count
        self._local_replay.add_batch(batch)
        info: Dict[str, Any] = {"num_env_steps_sampled": self._added,
                                "buffer_size": self._local_replay.size()}
        if self._added < int(cfg["learning_starts"]):
            return info
        for _ in range(int(cfg["num_updates_per_iteration"])):
            out = self._local_replay.sample(
                int(cfg["train_batch_size"]),
                float(cfg["prioritized_replay_beta"]))
            if out is None:
                break
            cols, idx, w = out
            info.update(self._learn(cols, idx, w))
        info["learner_updates"] = self._updates
        return info
