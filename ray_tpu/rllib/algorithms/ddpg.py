"""DDPG + TD3: deterministic-policy-gradient continuous control.

Reference: ``rllib/algorithms/ddpg/`` and ``rllib/algorithms/td3/``
(SURVEY.md §2.5; Lillicrap et al. 2016, Fujimoto et al. 2018).  The
continuous off-policy family SAC didn't cover (VERDICT r4 missing #7):

- **DDPG**: deterministic tanh actor μ(s), ONE Q critic, polyak target
  networks for both, Gaussian action-space exploration noise.
- **TD3** = DDPG + the paper's three fixes, each a config knob here:
  ``twin_q`` (clipped double-Q), ``policy_delay`` (delayed actor
  updates), ``target_noise``/``target_noise_clip`` (target policy
  smoothing).

TPU-native shape: actor+critics+targets update in ONE jitted step.  The
policy delay is a ``jnp.where`` mask over the candidate actor update
(the actor grad is computed every step and DISCARDED on non-actor
steps — compiled-program uniformity traded against ~half an actor
backward of wasted FLOPs, negligible beside the critic work), so the
delayed variant is still a single compiled program, not Python
branching.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import models
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn import ReplayBuffer
from ray_tpu.rllib.evaluation import synchronous_parallel_sample
from ray_tpu.rllib.sample_batch import (
    ACTION_DIST_INPUTS, ACTION_LOGP, NEXT_OBS, OBS, REWARDS,
    TERMINATEDS, VF_PREDS)


class DDPGPolicy:
    """Deterministic tanh actor for Box action spaces; exploration adds
    Gaussian noise in the raw (-1,1) action space (reference:
    ``ou_base_scale``/gaussian exploration — gaussian here, the TD3
    paper's choice)."""

    def __init__(self, observation_space, action_space,
                 config: Optional[dict] = None):
        config = config or {}
        self.observation_space = observation_space
        self.action_space = action_space
        self.config = config
        obs_dim = models.flat_obs_dim(observation_space)
        self.act_dim = int(np.prod(action_space.shape))
        self.low = np.asarray(action_space.low, np.float32)
        self.high = np.asarray(action_space.high, np.float32)
        hiddens = tuple(config.get("fcnet_hiddens", (256, 256)))
        self._num_layers = len(hiddens) + 1
        self.model_config = models.ModelConfig(
            obs_dim=obs_dim, num_outputs=self.act_dim, hiddens=hiddens)
        seed = config.get("seed", 0)
        self.params = models.init_q_net(jax.random.key(seed),
                                        self.model_config)
        self.explore_noise = float(config.get("exploration_noise", 0.1))
        self._rng = np.random.default_rng(seed + 1)
        n_layers = self._num_layers

        @jax.jit
        def _mu(params, obs):
            return jnp.tanh(models.q_net_apply(params, obs, n_layers))

        self._mu = _mu

    def _scale(self, a: np.ndarray) -> np.ndarray:
        return self.low + (a + 1.0) * 0.5 * (self.high - self.low)

    def compute_actions(self, obs: np.ndarray, explore: bool = True):
        a = np.asarray(self._mu(self.params, jnp.asarray(obs, jnp.float32)))
        if explore:
            a = np.clip(a + self._rng.normal(
                0.0, self.explore_noise, a.shape).astype(np.float32),
                -1.0, 1.0)
        n = len(a)
        extras = {VF_PREDS: np.zeros(n, np.float32),
                  ACTION_LOGP: np.zeros(n, np.float32),
                  ACTION_DIST_INPUTS: np.zeros((n, self.act_dim),
                                               np.float32)}
        return self._scale(a).astype(np.float32), {**extras, "raw_action": a}

    def compute_single_action(self, obs, explore: bool = True):
        a, extras = self.compute_actions(obs[None], explore)
        return a[0], {k: v[0] for k, v in extras.items()}

    def value(self, obs: np.ndarray) -> np.ndarray:
        return np.zeros(len(obs), np.float32)  # replay-based learner

    def get_weights(self):
        return {"params": jax.tree_util.tree_map(np.asarray, self.params)}

    def set_weights(self, weights):
        self.params = jax.tree_util.tree_map(jnp.asarray, weights["params"])


class DDPGConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DDPG)
        self._cfg.update({
            "policy_class": DDPGPolicy,
            "actor_lr": 1e-3, "critic_lr": 1e-3,
            "gamma": 0.99, "tau": 0.005,
            "buffer_size": 100_000, "learning_starts": 256,
            "train_batch_size": 256, "num_sgd_per_step": 1,
            "rollout_fragment_length": 1,
            "fcnet_hiddens": (256, 256),
            "exploration_noise": 0.1,
            # --- the TD3 knobs (DDPG defaults = all off) ---
            "twin_q": False,
            "policy_delay": 1,
            "target_noise": 0.0,
            "target_noise_clip": 0.5,
        })


class TD3Config(DDPGConfig):
    """DDPG + twin critics + delayed policy + target smoothing
    (reference: ``TD3Config`` defaults)."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or TD3)
        self._cfg.update({
            "twin_q": True,
            "policy_delay": 2,
            "target_noise": 0.2,
            "target_noise_clip": 0.5,
            "exploration_noise": 0.1,
        })


class DDPG(Algorithm):
    _default_config_cls = DDPGConfig

    def setup(self, config: Dict[str, Any]) -> None:
        policy: DDPGPolicy = self.workers.local_worker.policy
        obs_dim = policy.model_config.obs_dim
        act_dim = policy.act_dim
        hiddens = tuple(config["fcnet_hiddens"])
        q_cfg = models.ModelConfig(obs_dim=obs_dim + act_dim, num_outputs=1,
                                   hiddens=hiddens)
        q_layers = len(hiddens) + 1
        seed = config.get("seed") or 0
        k1, k2 = jax.random.split(jax.random.key(seed + 100))
        self.q1 = models.init_q_net(k1, q_cfg)
        self.q2 = models.init_q_net(k2, q_cfg)   # unused unless twin_q
        self.actor_t = policy.params
        self.q1_t, self.q2_t = self.q1, self.q2
        self.buffer = ReplayBuffer(
            int(config["buffer_size"]),
            keys=(OBS, "raw_action", REWARDS, NEXT_OBS, TERMINATEDS))
        self._rng = np.random.default_rng(seed)
        self._learn_key = jax.random.key(seed + 7)
        self._n_updates = 0

        actor_opt = optax.adam(config["actor_lr"])
        critic_opt = optax.adam(config["critic_lr"])
        self._actor_state = actor_opt.init(policy.params)
        self._critic_state = critic_opt.init((self.q1, self.q2))

        gamma = float(config["gamma"])
        tau = float(config["tau"])
        twin_q = bool(config["twin_q"])
        policy_delay = int(config["policy_delay"])
        t_noise = float(config["target_noise"])
        t_clip = float(config["target_noise_clip"])
        a_layers = policy._num_layers

        def mu(ap, obs):
            return jnp.tanh(models.q_net_apply(ap, obs, a_layers))

        def q_apply(qp, obs, act):
            return models.q_net_apply(
                qp, jnp.concatenate([obs, act], -1), q_layers)[:, 0]

        def update(actor_p, actor_t, q1, q2, q1_t, q2_t,
                   actor_s, critic_s, n_updates, mb, key):
            # target action with TD3 smoothing noise (0 noise = DDPG)
            next_a = mu(actor_t, mb[NEXT_OBS])
            if t_noise > 0.0:
                noise = jnp.clip(
                    t_noise * jax.random.normal(key, next_a.shape),
                    -t_clip, t_clip)
                next_a = jnp.clip(next_a + noise, -1.0, 1.0)
            qn1 = q_apply(q1_t, mb[NEXT_OBS], next_a)
            q_next = jnp.minimum(qn1, q_apply(q2_t, mb[NEXT_OBS], next_a)) \
                if twin_q else qn1
            target = mb[REWARDS] + gamma * (1 - mb["dones"]) * \
                jax.lax.stop_gradient(q_next)

            def critic_loss(qs):
                q1_, q2_ = qs
                loss = jnp.square(
                    q_apply(q1_, mb[OBS], mb["raw_action"]) - target).mean()
                if twin_q:
                    loss = loss + jnp.square(
                        q_apply(q2_, mb[OBS], mb["raw_action"])
                        - target).mean()
                return loss

            c_grads = jax.grad(critic_loss)((q1, q2))
            c_updates, critic_s = critic_opt.update(c_grads, critic_s,
                                                    (q1, q2))
            q1, q2 = optax.apply_updates((q1, q2), c_updates)

            # delayed deterministic-policy-gradient actor step: the
            # candidate grad+update is computed EVERY step and masked in
            # with jnp.where only on actor steps (uniform program; the
            # discarded actor backward is cheap beside the critics)
            def actor_loss(ap):
                return -q_apply(q1, mb[OBS], mu(ap, mb[OBS])).mean()

            a_grads = jax.grad(actor_loss)(actor_p)
            a_updates, cand_actor_s = actor_opt.update(a_grads, actor_s,
                                                       actor_p)
            cand_actor = optax.apply_updates(actor_p, a_updates)
            do_actor = (n_updates % policy_delay) == 0
            pick = lambda new, old: jnp.where(do_actor, new, old)  # noqa: E731
            actor_p = jax.tree_util.tree_map(pick, cand_actor, actor_p)
            actor_s = jax.tree_util.tree_map(pick, cand_actor_s, actor_s)
            # polyak target sync (actor target only moves with the actor)
            sync = lambda t, s: (1 - tau) * t + tau * s  # noqa: E731
            q1_t = jax.tree_util.tree_map(sync, q1_t, q1)
            q2_t = jax.tree_util.tree_map(sync, q2_t, q2)
            actor_t = jax.tree_util.tree_map(
                lambda t, s: jnp.where(do_actor, (1 - tau) * t + tau * s,
                                       t), actor_t, actor_p)
            metrics = {"critic_loss": critic_loss((q1, q2)),
                       "q_mean": q_apply(q1, mb[OBS],
                                         mb["raw_action"]).mean()}
            return (actor_p, actor_t, q1, q2, q1_t, q2_t, actor_s,
                    critic_s, metrics)

        self._update = jax.jit(update)

    def training_step(self) -> Dict[str, Any]:
        policy = self.workers.local_worker.policy
        batch = synchronous_parallel_sample(self.workers)
        self.buffer.add_batch(batch)
        info: Dict[str, Any] = {"buffer_size": len(self.buffer)}
        if len(self.buffer) < int(self.config["learning_starts"]):
            return info
        for _ in range(int(self.config["num_sgd_per_step"])):
            mb = self.buffer.sample(int(self.config["train_batch_size"]),
                                    self._rng)
            device_mb = {
                OBS: jnp.asarray(mb[OBS]),
                "raw_action": jnp.asarray(mb["raw_action"]),
                REWARDS: jnp.asarray(mb[REWARDS]),
                NEXT_OBS: jnp.asarray(mb[NEXT_OBS]),
                "dones": jnp.asarray(mb[TERMINATEDS].astype(np.float32)),
            }
            self._learn_key, sub = jax.random.split(self._learn_key)
            (policy.params, self.actor_t, self.q1, self.q2, self.q1_t,
             self.q2_t, self._actor_state, self._critic_state,
             metrics) = self._update(
                policy.params, self.actor_t, self.q1, self.q2, self.q1_t,
                self.q2_t, self._actor_state, self._critic_state,
                jnp.asarray(self._n_updates), device_mb, sub)
            self._n_updates += 1
            info.update({k: float(v) for k, v in metrics.items()})
        self.workers.sync_weights()
        info["num_updates"] = self._n_updates
        return info


class TD3(DDPG):
    _default_config_cls = TD3Config
