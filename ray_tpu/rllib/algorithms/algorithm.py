"""Algorithm base: the train()/training_step() driver.

Reference: ``rllib/algorithms/algorithm.py`` (SURVEY.md §2.5, §3.5) —
``Algorithm.train()`` wraps one ``training_step()`` with metric collection,
iteration bookkeeping, and checkpointing.  ``AlgorithmConfig`` keeps the
reference's fluent builder surface (``.environment().rollouts().training()``)
over a plain dict.
"""

from __future__ import annotations

import copy
import os
import pickle
import time
from typing import Any, Dict, Optional

from ray_tpu.rllib.evaluation import WorkerSet, collect_metrics


class AlgorithmConfig:
    """Fluent config builder.  ``.to_dict()`` or pass directly to an
    Algorithm class; unknown keys flow through to workers/policies."""

    def __init__(self, algo_class: Optional[type] = None):
        self.algo_class = algo_class
        self._cfg: Dict[str, Any] = {
            "env": None, "env_config": {},
            "num_workers": 0, "num_envs_per_worker": 1,
            "rollout_fragment_length": 200, "num_cpus_per_worker": 1,
            "gamma": 0.99, "lr": 5e-4, "train_batch_size": 4000,
            "fcnet_hiddens": (64, 64), "seed": None,
        }

    # Fluent sections (reference names).
    def environment(self, env=None, *, env_config=None, **kw):
        if env is not None:
            self._cfg["env"] = env
        if env_config is not None:
            self._cfg["env_config"] = env_config
        self._cfg.update(kw)
        return self

    def rollouts(self, **kw):
        self._cfg.update(kw)
        return self

    env_runners = rollouts

    def training(self, **kw):
        self._cfg.update(kw)
        return self

    def resources(self, **kw):
        self._cfg.update(kw)
        return self

    def debugging(self, *, seed=None, **kw):
        if seed is not None:
            self._cfg["seed"] = seed
        self._cfg.update(kw)
        return self

    def multi_agent(self, *, policies=None, policy_mapping_fn=None,
                    **kw) -> "AlgorithmConfig":
        """Reference: ``AlgorithmConfig.multi_agent(policies=...,
        policy_mapping_fn=...)``.  ``policies`` may be a set/list of ids
        (all-default policies) or {pid: (cls, obs_space, act_space,
        config)} specs."""
        ma = dict(self._cfg.get("multiagent") or {})
        if policies is not None:
            if isinstance(policies, (set, list, tuple)):
                policies = {pid: None for pid in policies}
            ma["policies"] = dict(policies)
        if policy_mapping_fn is not None:
            ma["policy_mapping_fn"] = policy_mapping_fn
        ma.update(kw)
        self._cfg["multiagent"] = ma
        return self

    def framework(self, *_a, **_kw):  # jax-only; accepted for API parity
        return self

    def update(self, other: Dict[str, Any]) -> "AlgorithmConfig":
        self._cfg.update(other)
        return self

    def to_dict(self) -> Dict[str, Any]:
        return copy.deepcopy(self._cfg)

    def build(self, env=None) -> "Algorithm":
        if env is not None:
            self._cfg["env"] = env
        cls = self.algo_class or Algorithm
        return cls(config=self)

    def __getitem__(self, key):
        return self._cfg[key]


class Algorithm:
    """Drives training: subclasses override ``default_config`` and
    ``training_step``."""

    _default_config_cls = AlgorithmConfig
    # Algorithms that can consume a MultiAgentBatch opt in; everything
    # else must fail loudly at build time, not with an obscure TypeError
    # deep inside training_step.
    _supports_multi_agent = False

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return cls._default_config_cls(cls)

    def __init__(self, config: Any = None, env: Any = None, **overrides):
        base = self.get_default_config().to_dict()
        if isinstance(config, AlgorithmConfig):
            config = config.to_dict()
        # normalize the worker-count alias per user-supplied dict (the
        # reference spells it both ways across versions; WorkerSet reads
        # "num_workers"; an explicit num_workers in the SAME dict wins)
        def _normalize(d):
            if d and "num_rollout_workers" in d:
                d = dict(d)
                d.setdefault("num_workers", d["num_rollout_workers"])
                del d["num_rollout_workers"]
            return d

        base.update(_normalize(config) or {})
        base.update(_normalize(overrides))
        if env is not None:
            base["env"] = env
        if base.get("env") is None:
            raise ValueError("no env specified")
        if base.get("multiagent") and not self._supports_multi_agent:
            raise NotImplementedError(
                f"{type(self).__name__} does not support multi-agent "
                f"training (PPO does); remove the multi_agent(...) config")
        self.config = base
        self.iteration = 0
        self._timesteps_total = 0
        self._time_total = 0.0
        self.workers = WorkerSet(base)
        self.setup(base)

    def setup(self, config: Dict[str, Any]) -> None:
        """Algorithm-specific state (learner jit fns, buffers)."""

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def train(self) -> Dict[str, Any]:
        start = time.perf_counter()
        info = self.training_step() or {}
        elapsed = time.perf_counter() - start
        self.iteration += 1
        self._time_total += elapsed
        metrics = collect_metrics(self.workers)
        self._timesteps_total = metrics.pop("num_env_steps_sampled")
        result = {
            "training_iteration": self.iteration,
            "timesteps_total": self._timesteps_total,
            "time_this_iter_s": elapsed,
            "time_total_s": self._time_total,
            **metrics,
            "info": info,
        }
        # Tune-compatible aliases (reference result dict carries both).
        result["env_runners"] = {
            "episode_return_mean": metrics.get("episode_reward_mean")}
        return result

    def evaluate(self, num_episodes: int = 5) -> Dict[str, Any]:
        """Greedy-policy rollouts on a fresh local env."""
        from ray_tpu.rllib import env as env_lib
        e = env_lib.create_env(self.config["env"],
                               self.config.get("env_config"))
        pol = self.workers.local_worker.policy
        rewards = []
        for ep in range(num_episodes):
            obs, _ = e.reset(seed=10_000 + ep)
            total, done = 0.0, False
            while not done:
                a, _ = pol.compute_single_action(obs, explore=False)
                obs, r, term, trunc, _ = e.step(a)
                total += float(r)
                done = term or trunc
            rewards.append(total)
        return {"evaluation": {
            "episode_reward_mean": sum(rewards) / len(rewards)}}

    def get_policy(self):
        return self.workers.local_worker.policy

    def get_weights(self) -> dict:
        return self.workers.local_worker.get_weights()

    def set_weights(self, weights: dict) -> None:
        self.workers.local_worker.set_weights(weights)
        self.workers.sync_weights()

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "wb") as f:
            pickle.dump({
                "weights": self.get_weights(),
                "iteration": self.iteration,
                "timesteps_total": self._timesteps_total,
                "config": {k: v for k, v in self.config.items()
                           if _picklable(v)},
                "extra_state": self.get_extra_state(),
            }, f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "rb") as f:
            state = pickle.load(f)
        self.set_weights(state["weights"])
        self.iteration = state["iteration"]
        self._timesteps_total = state["timesteps_total"]
        self.set_extra_state(state.get("extra_state"))

    def get_extra_state(self) -> Any:
        return None

    def set_extra_state(self, state: Any) -> None:
        pass

    def stop(self) -> None:
        self.workers.stop()


def _picklable(v) -> bool:
    try:
        pickle.dumps(v)
        return True
    except Exception:  # noqa: BLE001
        return False
