"""ES: evolution strategies (OpenAI-ES style derivative-free RL).

Reference: ``rllib/algorithms/es/`` (SURVEY.md §2.5) — per iteration,
sample antithetic parameter perturbations, evaluate each as a full episode
on the rollout workers (embarrassingly parallel via framework tasks), then
update θ along the fitness-weighted average of the noise (rank-normalized).
No backprop: the whole learner is the jitted perturbation/update math.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib import models
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.env import create_env


def _flatten(params) -> np.ndarray:
    return np.concatenate([np.asarray(x).ravel()
                           for x in jax.tree_util.tree_leaves(params)])


def _unflatten(flat: np.ndarray, params):
    leaves, treedef = jax.tree_util.tree_flatten(params)
    out, off = [], 0
    for leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        out.append(jnp.asarray(flat[off:off + n]).reshape(leaf.shape))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


@ray_tpu.remote
def _es_rollout(env_spec, env_config, model_config_dict, flat_params,
                deterministic_env_seed: int) -> float:
    """One episode with the given flat parameters; returns total reward."""
    cfg = models.ModelConfig(**model_config_dict)
    template = models.init_q_net(jax.random.key(0), cfg)
    params = _unflatten(flat_params, template)
    n_layers = len(cfg.hiddens) + 1
    env = create_env(env_spec, env_config)
    obs, _ = env.reset(seed=deterministic_env_seed)
    total, done = 0.0, False
    while not done:
        logits = models.q_net_apply(
            params, jnp.asarray(obs, jnp.float32)[None], n_layers)
        act = int(jnp.argmax(logits[0]))
        obs, r, term, trunc, _ = env.step(act)
        total += float(r)
        done = term or trunc
    return total


class ESConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ES)
        self._cfg.update({
            "episodes_per_batch": 16,     # perturbation pairs per iter
            "noise_std": 0.1,
            "step_size": 0.02,
            "fcnet_hiddens": (32, 32),
            "num_workers": 0,             # rollouts are tasks, not actors
        })


class ES(Algorithm):
    _default_config_cls = ESConfig

    def setup(self, config: Dict[str, Any]) -> None:
        env = create_env(config["env"], config.get("env_config"))
        hiddens = tuple(config["fcnet_hiddens"])
        self.model_config = models.ModelConfig(
            obs_dim=models.flat_obs_dim(env.observation_space),
            num_outputs=int(env.action_space.n), hiddens=hiddens)
        seed = config.get("seed") or 0
        self.theta = _flatten(models.init_q_net(jax.random.key(seed),
                                                self.model_config))
        self._rng = np.random.default_rng(seed)
        self._iter = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n_pairs = int(cfg["episodes_per_batch"])
        std = float(cfg["noise_std"])
        dim = len(self.theta)
        noise = self._rng.standard_normal((n_pairs, dim)).astype(np.float32)
        self._iter += 1
        env_seed = 10_000 + self._iter  # common seed: antithetic pairs
        # fan out 2*n_pairs episodes as parallel tasks (+ and - directions)
        mc = self.model_config.__dict__
        refs = []
        for i in range(n_pairs):
            for sign in (1.0, -1.0):
                refs.append(_es_rollout.remote(
                    cfg["env"], cfg.get("env_config"), mc,
                    self.theta + sign * std * noise[i], env_seed + i))
        rewards = np.asarray(ray_tpu.get(refs), np.float32).reshape(n_pairs, 2)

        # rank-normalize fitness (robust to reward scale), antithetic diff
        flat = rewards.ravel()
        ranks = np.empty(len(flat), np.float32)
        ranks[flat.argsort()] = np.linspace(-0.5, 0.5, len(flat))
        ranks = ranks.reshape(n_pairs, 2)
        advantage = ranks[:, 0] - ranks[:, 1]
        grad = (advantage[:, None] * noise).mean(0) / std
        self.theta = self.theta + float(cfg["step_size"]) * grad

        return {"episode_reward_mean": float(rewards.mean()),
                "episode_reward_max": float(rewards.max()),
                "episodes_this_iter": 2 * n_pairs,
                "theta_norm": float(np.linalg.norm(self.theta))}

    def train(self) -> Dict[str, Any]:
        result = super().train()
        # ES samples via tasks, not the worker set — surface its episode
        # stats at the top level where tune/tests expect them
        result.update(result["info"])
        return result

    # ES has no rollout-worker set; evaluation runs the greedy policy
    def evaluate(self, episodes: int = 3) -> Dict[str, Any]:
        ref = [_es_rollout.remote(self.config["env"],
                                  self.config.get("env_config"),
                                  self.model_config.__dict__, self.theta,
                                  20_000 + i)
               for i in range(episodes)]
        rs = ray_tpu.get(ref)
        return {"evaluation_reward_mean": float(np.mean(rs))}
