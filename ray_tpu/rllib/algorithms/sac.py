"""SAC: soft actor-critic for continuous control.

Reference: ``rllib/algorithms/sac/`` (SURVEY.md §2.5) — off-policy
maximum-entropy RL: a squashed-Gaussian actor, twin Q critics with target
networks (clipped double-Q), and automatic entropy-temperature tuning
against a target entropy of ``-dim(A)``.  The learner is one jitted update
(actor + critics + alpha in a single compiled step).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import models
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn import ReplayBuffer
from ray_tpu.rllib.evaluation import synchronous_parallel_sample
from ray_tpu.rllib.sample_batch import (
    ACTION_DIST_INPUTS, ACTION_LOGP, NEXT_OBS, OBS, REWARDS,
    TERMINATEDS, VF_PREDS)

LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def _actor_apply(params, obs, num_layers):
    out = models.q_net_apply(params, obs, num_layers)  # (B, 2*act_dim)
    mean, log_std = jnp.split(out, 2, axis=-1)
    return mean, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)


def _sample_squashed(params, obs, key, num_layers):
    """Reparameterized tanh-Gaussian sample + log-prob (with the tanh
    Jacobian correction from the SAC paper)."""
    mean, log_std = _actor_apply(params, obs, num_layers)
    std = jnp.exp(log_std)
    eps = jax.random.normal(key, mean.shape)
    pre = mean + std * eps
    act = jnp.tanh(pre)
    logp = (-0.5 * (eps ** 2 + 2 * log_std + math.log(2 * math.pi))).sum(-1)
    logp = logp - jnp.log(1 - act ** 2 + 1e-6).sum(-1)
    return act, logp


class SACPolicy:
    """Squashed-Gaussian actor for Box action spaces."""

    def __init__(self, observation_space, action_space,
                 config: Optional[dict] = None):
        config = config or {}
        self.observation_space = observation_space
        self.action_space = action_space
        self.config = config
        obs_dim = models.flat_obs_dim(observation_space)
        self.act_dim = int(np.prod(action_space.shape))
        self.low = np.asarray(action_space.low, np.float32)
        self.high = np.asarray(action_space.high, np.float32)
        hiddens = tuple(config.get("fcnet_hiddens", (256, 256)))
        self._num_layers = len(hiddens) + 1
        self.model_config = models.ModelConfig(
            obs_dim=obs_dim, num_outputs=2 * self.act_dim, hiddens=hiddens)
        seed = config.get("seed", 0)
        self.params = models.init_q_net(jax.random.key(seed),
                                        self.model_config)
        self._key = jax.random.key(seed + 1)
        n_layers = self._num_layers

        @jax.jit
        def _act(params, obs, key, deterministic):
            mean, log_std = _actor_apply(params, obs, n_layers)
            det = jnp.tanh(mean)
            sto, _ = _sample_squashed(params, obs, key, n_layers)
            return jnp.where(deterministic, det, sto)

        self._act = _act

    def _scale(self, a: np.ndarray) -> np.ndarray:
        return self.low + (a + 1.0) * 0.5 * (self.high - self.low)

    def compute_actions(self, obs: np.ndarray, explore: bool = True):
        self._key, sub = jax.random.split(self._key)
        a = np.asarray(self._act(self.params,
                                 jnp.asarray(obs, jnp.float32), sub,
                                 not explore))
        n = len(a)
        extras = {VF_PREDS: np.zeros(n, np.float32),
                  ACTION_LOGP: np.zeros(n, np.float32),
                  ACTION_DIST_INPUTS: np.zeros((n, 2 * self.act_dim),
                                               np.float32)}
        # env sees the scaled action; the buffer stores the raw tanh output
        return self._scale(a).astype(np.float32), {**extras, "raw_action": a}

    def compute_single_action(self, obs, explore: bool = True):
        a, extras = self.compute_actions(obs[None], explore)
        return a[0], {k: v[0] for k, v in extras.items()}

    def value(self, obs: np.ndarray) -> np.ndarray:
        # GAE bootstrap hook; unused by the SAC learner (replay-based)
        return np.zeros(len(obs), np.float32)

    def get_weights(self):
        return {"params": jax.tree_util.tree_map(np.asarray, self.params)}

    def set_weights(self, weights):
        self.params = jax.tree_util.tree_map(jnp.asarray, weights["params"])


class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SAC)
        self._cfg.update({
            "policy_class": SACPolicy,
            "actor_lr": 3e-4, "critic_lr": 3e-4, "alpha_lr": 3e-4,
            "gamma": 0.99, "tau": 0.005,
            "buffer_size": 100_000, "learning_starts": 256,
            "train_batch_size": 256, "num_sgd_per_step": 1,
            "rollout_fragment_length": 1,
            "fcnet_hiddens": (256, 256),
        })


class SAC(Algorithm):
    _default_config_cls = SACConfig

    def setup(self, config: Dict[str, Any]) -> None:
        policy = self.workers.local_worker.policy
        obs_dim = policy.model_config.obs_dim
        act_dim = policy.act_dim
        hiddens = tuple(config["fcnet_hiddens"])
        q_cfg = models.ModelConfig(obs_dim=obs_dim + act_dim, num_outputs=1,
                                   hiddens=hiddens)
        self._q_layers = len(hiddens) + 1
        seed = config.get("seed") or 0
        k1, k2 = jax.random.split(jax.random.key(seed + 100))
        self.q1 = models.init_q_net(k1, q_cfg)
        self.q2 = models.init_q_net(k2, q_cfg)
        self.q1_t, self.q2_t = self.q1, self.q2
        self.log_alpha = jnp.zeros(())
        self.buffer = ReplayBuffer(
            int(config["buffer_size"]),
            keys=(OBS, "raw_action", REWARDS, NEXT_OBS, TERMINATEDS))
        self._rng = np.random.default_rng(seed)
        self._learn_key = jax.random.key(seed + 7)

        actor_opt = optax.adam(config["actor_lr"])
        critic_opt = optax.adam(config["critic_lr"])
        alpha_opt = optax.adam(config["alpha_lr"])
        self._actor_state = actor_opt.init(policy.params)
        self._critic_state = critic_opt.init((self.q1, self.q2))
        self._alpha_state = alpha_opt.init(self.log_alpha)

        gamma = float(config["gamma"])
        tau = float(config["tau"])
        target_entropy = -float(act_dim)
        a_layers = policy._num_layers
        q_layers = self._q_layers

        def q_apply(qp, obs, act):
            return models.q_net_apply(
                qp, jnp.concatenate([obs, act], -1), q_layers)[:, 0]

        def update(actor_p, q1, q2, q1_t, q2_t, log_alpha,
                   actor_s, critic_s, alpha_s, mb, key):
            k1, k2 = jax.random.split(key)
            alpha = jnp.exp(log_alpha)

            # critics: clipped double-Q against the entropy-regularized
            # bootstrap target
            next_a, next_logp = _sample_squashed(actor_p, mb[NEXT_OBS], k1,
                                                 a_layers)
            q_next = jnp.minimum(q_apply(q1_t, mb[NEXT_OBS], next_a),
                                 q_apply(q2_t, mb[NEXT_OBS], next_a))
            target = mb[REWARDS] + gamma * (1 - mb["dones"]) * \
                jax.lax.stop_gradient(q_next - alpha * next_logp)

            def critic_loss(qs):
                q1_, q2_ = qs
                l1 = jnp.square(q_apply(q1_, mb[OBS], mb["raw_action"])
                                - target).mean()
                l2 = jnp.square(q_apply(q2_, mb[OBS], mb["raw_action"])
                                - target).mean()
                return l1 + l2

            c_grads = jax.grad(critic_loss)((q1, q2))
            c_updates, critic_s = critic_opt.update(c_grads, critic_s,
                                                    (q1, q2))
            q1, q2 = optax.apply_updates((q1, q2), c_updates)

            # actor: maximize E[min Q - alpha * logp]
            def actor_loss(ap):
                a, logp = _sample_squashed(ap, mb[OBS], k2, a_layers)
                q = jnp.minimum(q_apply(q1, mb[OBS], a),
                                q_apply(q2, mb[OBS], a))
                return (alpha * logp - q).mean(), logp

            a_grads, logp = jax.grad(actor_loss, has_aux=True)(actor_p)
            a_updates, actor_s = actor_opt.update(a_grads, actor_s, actor_p)
            actor_p = optax.apply_updates(actor_p, a_updates)

            # temperature: drive entropy toward the target
            def alpha_loss(la):
                return (-jnp.exp(la) *
                        jax.lax.stop_gradient(logp + target_entropy)).mean()

            al_grad = jax.grad(alpha_loss)(log_alpha)
            al_update, alpha_s = alpha_opt.update(al_grad, alpha_s, log_alpha)
            log_alpha = optax.apply_updates(log_alpha, al_update)

            # polyak target sync
            q1_t = jax.tree_util.tree_map(
                lambda t, s: (1 - tau) * t + tau * s, q1_t, q1)
            q2_t = jax.tree_util.tree_map(
                lambda t, s: (1 - tau) * t + tau * s, q2_t, q2)
            metrics = {"alpha": jnp.exp(log_alpha),
                       "entropy": -logp.mean()}
            return (actor_p, q1, q2, q1_t, q2_t, log_alpha,
                    actor_s, critic_s, alpha_s, metrics)

        self._update = jax.jit(update)

    def training_step(self) -> Dict[str, Any]:
        policy = self.workers.local_worker.policy
        batch = synchronous_parallel_sample(self.workers)
        self.buffer.add_batch(batch)
        info: Dict[str, Any] = {"buffer_size": len(self.buffer)}
        if len(self.buffer) < int(self.config["learning_starts"]):
            return info
        for _ in range(int(self.config["num_sgd_per_step"])):
            mb = self.buffer.sample(int(self.config["train_batch_size"]),
                                    self._rng)
            device_mb = {
                OBS: jnp.asarray(mb[OBS]),
                "raw_action": jnp.asarray(mb["raw_action"]),
                REWARDS: jnp.asarray(mb[REWARDS]),
                NEXT_OBS: jnp.asarray(mb[NEXT_OBS]),
                "dones": jnp.asarray(mb[TERMINATEDS].astype(np.float32)),
            }
            self._learn_key, sub = jax.random.split(self._learn_key)
            (policy.params, self.q1, self.q2, self.q1_t, self.q2_t,
             self.log_alpha, self._actor_state, self._critic_state,
             self._alpha_state, metrics) = self._update(
                policy.params, self.q1, self.q2, self.q1_t, self.q2_t,
                self.log_alpha, self._actor_state, self._critic_state,
                self._alpha_state, device_mb, sub)
            info.update({k: float(v) for k, v in metrics.items()})
        self.workers.sync_weights()
        return info
