"""PPO: synchronous on-policy sampling + clipped-surrogate SGD.

Reference: ``rllib/algorithms/ppo/ppo.py`` (SURVEY.md §3.5) — sample across
the WorkerSet, run SGD epochs over minibatches, broadcast weights.  Rebuilt
TPU-first: the ENTIRE update (all epochs × all minibatches, with a fresh
shuffle per epoch) is one jitted XLA program via nested ``lax.scan``, so the
learner launches a single device computation per iteration instead of
hundreds of small optimizer steps.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.evaluation import synchronous_parallel_sample
from ray_tpu.rllib.sample_batch import (
    ACTION_DIST_INPUTS, ACTION_LOGP, ACTIONS, ADVANTAGES, OBS, SampleBatch,
    VALUE_TARGETS, VF_PREDS)


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PPO)
        self._cfg.update({
            "lr": 5e-5, "lambda": 0.95, "clip_param": 0.2,
            "vf_clip_param": 10.0, "vf_loss_coeff": 1.0,
            "entropy_coeff": 0.0, "kl_coeff": 0.2, "kl_target": 0.01,
            "num_sgd_iter": 10, "sgd_minibatch_size": 128,
            "train_batch_size": 4000, "grad_clip": 0.5,
        })


class PPO(Algorithm):
    _default_config_cls = PPOConfig
    _supports_multi_agent = True

    def setup(self, config: Dict[str, Any]) -> None:
        cfg = config
        lw = self.workers.local_worker
        self._ma = hasattr(lw, "policies")
        if self._ma:
            # one learner (update fn + optimizer state + adaptive KL) per
            # policy in the map (reference: multi-agent train_one_step);
            # a per-policy config in the spec tuple overrides the shared
            # algorithm config for THAT policy's learner (lr, clip, ...)
            from ray_tpu.rllib.multi_agent import _policy_spec
            specs = cfg["multiagent"]["policies"]
            self._learners = {}
            for pid, pol in lw.policies.items():
                pconf = _policy_spec(specs.get(pid))[3]
                self._learners[pid] = self._build_learner(
                    pol, {**cfg, **pconf})
        else:
            self._learners = {"default_policy":
                              self._build_learner(lw.policy, cfg)}
        self._kl_target = float(cfg["kl_target"])
        self._key = jax.random.key(cfg.get("seed") or 0)

    def _build_learner(self, policy, cfg) -> Dict[str, Any]:
        apply_fn = policy.apply_fn
        dist = policy.dist_class
        optimizer = optax.chain(
            optax.clip_by_global_norm(cfg["grad_clip"]),
            optax.adam(cfg["lr"]))
        clip = cfg["clip_param"]
        vf_clip = cfg["vf_clip_param"]
        vf_coeff = cfg["vf_loss_coeff"]
        ent_coeff = cfg["entropy_coeff"]
        num_epochs = int(cfg["num_sgd_iter"])
        mb_size = int(cfg["sgd_minibatch_size"])

        def loss_fn(params, mb, kl_coeff):
            inputs, values = apply_fn(params, mb[OBS])
            logp = dist.logp(inputs, mb[ACTIONS])
            ratio = jnp.exp(logp - mb[ACTION_LOGP])
            adv = mb[ADVANTAGES]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
            # Clipped value loss (reference vf_clip_param semantics).
            vf_err = jnp.square(values - mb[VALUE_TARGETS])
            v_clipped = mb[VF_PREDS] + jnp.clip(
                values - mb[VF_PREDS], -vf_clip, vf_clip)
            vf_err_clipped = jnp.square(v_clipped - mb[VALUE_TARGETS])
            vf_loss = jnp.maximum(vf_err, vf_err_clipped).mean()
            entropy = dist.entropy(inputs).mean()
            kl = dist.kl(mb[ACTION_DIST_INPUTS], inputs).mean()
            total = (-surr.mean() + vf_coeff * vf_loss
                     - ent_coeff * entropy + kl_coeff * kl)
            return total, (kl, entropy, vf_loss, -surr.mean())

        def update(params, opt_state, batch, kl_coeff, key):
            n = batch[OBS].shape[0]
            num_mb = max(n // mb_size, 1)
            usable = num_mb * mb_size

            def epoch_step(carry, ekey):
                params, opt_state = carry
                perm = jax.random.permutation(ekey, n)[:usable]
                shuffled = jax.tree_util.tree_map(
                    lambda v: v[perm].reshape((num_mb, mb_size)
                                              + v.shape[1:]), batch)

                def mb_step(carry, mb):
                    params, opt_state = carry
                    grads, aux = jax.grad(loss_fn, has_aux=True)(
                        params, mb, kl_coeff)
                    updates, opt_state = optimizer.update(grads, opt_state,
                                                          params)
                    params = optax.apply_updates(params, updates)
                    return (params, opt_state), jnp.stack(aux)

                carry, auxes = jax.lax.scan(mb_step, (params, opt_state),
                                            shuffled)
                return carry, auxes[-1]  # last-minibatch stats per epoch

            (params, opt_state), stats = jax.lax.scan(
                epoch_step, (params, opt_state), jax.random.split(
                    key, num_epochs))
            kl, entropy, vf_loss, pi_loss = stats[-1]
            return params, opt_state, {
                "kl": kl, "entropy": entropy, "vf_loss": vf_loss,
                "policy_loss": pi_loss}

        return {"policy": policy, "update": jax.jit(update),
                "opt_state": optimizer.init(policy.params),
                "kl_coeff": float(cfg["kl_coeff"])}

    def _update_one(self, learner: Dict[str, Any],
                    batch: SampleBatch) -> Dict[str, float]:
        policy = learner["policy"]
        device_batch = {k: jnp.asarray(batch[k]) for k in
                        (OBS, ACTIONS, ACTION_LOGP, ACTION_DIST_INPUTS,
                         ADVANTAGES, VALUE_TARGETS, VF_PREDS)}
        self._key, sub = jax.random.split(self._key)
        policy.params, learner["opt_state"], info = learner["update"](
            policy.params, learner["opt_state"], device_batch,
            learner["kl_coeff"], sub)
        info = {k: float(v) for k, v in info.items()}
        # Adaptive KL penalty (reference: ``update_kl``).
        if info["kl"] > 2.0 * self._kl_target:
            learner["kl_coeff"] *= 1.5
        elif info["kl"] < 0.5 * self._kl_target:
            learner["kl_coeff"] *= 0.5
        info["kl_coeff"] = learner["kl_coeff"]
        return info

    def training_step(self) -> Dict[str, Any]:
        batch = synchronous_parallel_sample(self.workers)
        if self._ma:
            info: Dict[str, Any] = {}
            for pid, sb in batch.policy_batches.items():
                if sb.count:
                    info[pid] = self._update_one(self._learners[pid], sb)
        else:
            info = self._update_one(self._learners["default_policy"], batch)
        info["num_env_steps_trained"] = batch.count
        self.workers.sync_weights()
        return info

    def get_extra_state(self):
        return {"kl_coeff": {pid: l["kl_coeff"]
                             for pid, l in self._learners.items()}}

    def set_extra_state(self, state):
        if state and "kl_coeff" in state:
            kc = state["kl_coeff"]
            if isinstance(kc, dict):
                for pid, v in kc.items():
                    if pid in self._learners:
                        self._learners[pid]["kl_coeff"] = v
            else:  # pre-multi-agent checkpoints
                for l in self._learners.values():
                    l["kl_coeff"] = kc
