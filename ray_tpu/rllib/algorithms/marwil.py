"""MARWIL + BC: offline RL from recorded experiences.

Reference: ``rllib/algorithms/marwil/`` (Wang et al. 2018,
"Exponentially Weighted Imitation Learning") and
``rllib/algorithms/bc/`` — learn a policy from a fixed dataset with no
environment interaction:

- value head regresses monte-carlo returns;
- advantage = return − V(s), normalized by a running mean-square (the
  paper's c² estimate);
- policy loss = −E[exp(β·Â) · log π(a|s)] — β=0 is exactly behavior
  cloning, which is what the ``BC`` subclass pins.

The env in the config is used only for spaces and ``evaluate()``; the
training loop touches nothing but the dataset (``config["input"]``, a
JSON-lines episode dir — see ``rllib/offline.py``), one jitted update
per minibatch.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.offline import OfflineData
from ray_tpu.rllib.sample_batch import ACTIONS, OBS


class MARWILConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MARWIL)
        self._cfg.update({
            "input": None,              # path to JSON-lines episode data
            "beta": 1.0,                # 0 = behavior cloning
            "lr": 1e-4, "train_batch_size": 512,
            "vf_loss_coeff": 1.0, "grad_clip": 40.0,
            "updates_per_iteration": 50,
            # running ⟨Â²⟩ update rate (reference: moving_average_sqd_adv_norm)
            "vf_norm_rate": 1e-3,
        })

    def offline_data(self, *, input=None, **kw):  # noqa: A002 - ref name
        if input is not None:
            self._cfg["input"] = input
        self._cfg.update(kw)
        return self


class MARWIL(Algorithm):
    _default_config_cls = MARWILConfig

    def setup(self, config: Dict[str, Any]) -> None:
        if not config.get("input"):
            raise ValueError(
                f"{type(self).__name__} is offline: set config['input'] to "
                "a JSON-lines episode dir (rllib/offline.py)")
        self.data = OfflineData(config["input"],
                                gamma=float(config["gamma"]))
        policy = self.workers.local_worker.policy
        apply_fn = policy.apply_fn
        dist = policy.dist_class
        beta = float(config["beta"])
        vf_coeff = float(config["vf_loss_coeff"])
        rate = float(config["vf_norm_rate"])
        self._optimizer = optax.chain(
            optax.clip_by_global_norm(float(config["grad_clip"])),
            optax.adam(float(config["lr"])))
        self._opt_state = self._optimizer.init(policy.params)
        # running ⟨Â²⟩ for the exponent's normalization (paper's c²)
        self._sq_norm = jnp.asarray(100.0)
        optimizer = self._optimizer

        def loss_fn(params, sq_norm, obs, actions, returns):
            inputs, values = apply_fn(params, obs)
            logp = dist.logp(inputs, actions)
            adv = returns - values
            vf_loss = 0.5 * jnp.square(adv).mean()
            if beta != 0.0:
                sq_norm = sq_norm + rate * (
                    jnp.square(jax.lax.stop_gradient(adv)).mean() - sq_norm)
                w = jnp.exp(beta * jax.lax.stop_gradient(adv)
                            / jnp.sqrt(sq_norm + 1e-8))
                # clip the exponentiated weights (paper appendix: bounded
                # importance keeps the estimator finite)
                w = jnp.minimum(w, 20.0)
            else:
                w = 1.0                  # BC: plain log-likelihood
            pi_loss = -(w * logp).mean()
            total = pi_loss + vf_coeff * vf_loss
            return total, (sq_norm, pi_loss, vf_loss)

        def update(params, opt_state, sq_norm, obs, actions, returns):
            grads, (sq_norm, pi_l, vf_l) = jax.grad(
                loss_fn, has_aux=True)(params, sq_norm, obs, actions,
                                       returns)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state,
                    sq_norm, pi_l, vf_l)

        self._update = jax.jit(update)
        self._rng = np.random.default_rng(config.get("seed") or 0)
        self._trained = 0

    def training_step(self) -> Dict[str, Any]:
        policy = self.workers.local_worker.policy
        if float(self.config["beta"]) != 0.0:
            # refresh truncated episodes' bootstrapped returns against
            # the current value head (one batched forward per iteration)
            self.data.rebuild_returns(policy.value)
        bs = int(self.config["train_batch_size"])
        # report the MEAN over the iteration's minibatches (reference
        # behavior): the last-minibatch value alone is sampling noise —
        # on a converged BC run it wanders ±5% and makes
        # monotonic-descent checks flaky
        pi_ls, vf_ls = [], []
        for _ in range(int(self.config["updates_per_iteration"])):
            mb = self.data.minibatch(self._rng, bs)
            (policy.params, self._opt_state, self._sq_norm, pi_l,
             vf_l) = self._update(policy.params, self._opt_state,
                                  self._sq_norm, mb[OBS], mb[ACTIONS],
                                  mb["returns"])
            # keep the raw device scalars: a float() here would force a
            # device sync per minibatch and serialize the update loop
            pi_ls.append(pi_l)
            vf_ls.append(vf_l)
            self._trained += len(mb[OBS])
        pi_l = float(np.mean([np.asarray(x) for x in pi_ls])) \
            if pi_ls else 0.0
        vf_l = float(np.mean([np.asarray(x) for x in vf_ls])) \
            if vf_ls else 0.0
        return {"policy_loss": float(pi_l), "vf_loss": float(vf_l),
                "num_steps_trained": self._trained,
                "dataset_episodes": self.data.episodes,
                "dataset_transitions": self.data.count}


class BCConfig(MARWILConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or BC)
        self._cfg.update({"beta": 0.0, "vf_loss_coeff": 0.0})


class BC(MARWIL):
    """Behavior cloning = MARWIL with β=0 (reference: ``rllib/algorithms/
    bc/`` subclasses MARWIL the same way)."""

    _default_config_cls = BCConfig
