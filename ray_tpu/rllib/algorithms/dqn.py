"""DQN: off-policy Q-learning with replay + target network (double-DQN).

Reference: ``rllib/algorithms/dqn/`` (SURVEY.md §2.5) — epsilon-greedy
rollouts feed a replay buffer; the learner samples uniform minibatches and
minimizes the double-DQN TD error against a periodically-synced target net.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib import models
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.evaluation import synchronous_parallel_sample
from ray_tpu.rllib.sample_batch import (
    ACTIONS, NEXT_OBS, OBS, REWARDS, SampleBatch, TERMINATEDS, VF_PREDS,
    ACTION_LOGP, ACTION_DIST_INPUTS)


class DQNPolicy:
    """Epsilon-greedy policy over a Q-network (replaces the actor-critic
    Policy inside RolloutWorker via ``config['policy_class']``)."""

    def __init__(self, observation_space, action_space,
                 config: Optional[dict] = None):
        config = config or {}
        self.observation_space = observation_space
        self.action_space = action_space
        self.config = config
        self.model_config = models.make_model_config(
            observation_space, action_space,
            {"fcnet_hiddens": (64, 64), **config})
        seed = config.get("seed", 0)
        # catalog: MLP Q-net for flat obs, Nature-CNN torso + linear Q
        # head for rank-3 (pixel) obs
        self.params, self.q_apply = models.make_q_net(
            jax.random.key(seed), self.model_config)
        self.epsilon = float(config.get("initial_epsilon", 1.0))
        self._rng = np.random.default_rng(seed)
        self._q = jax.jit(self.q_apply)

    def compute_actions(self, obs: np.ndarray, explore: bool = True):
        q = np.asarray(self._q(self.params, jnp.asarray(obs, jnp.float32)))
        actions = q.argmax(axis=-1)
        if explore:
            mask = self._rng.uniform(size=len(actions)) < self.epsilon
            rand = self._rng.integers(0, q.shape[-1], size=len(actions))
            actions = np.where(mask, rand, actions)
        # VF_PREDS/logp filled so GAE postprocessing stays well-defined
        # (unused by the DQN learner).
        extras = {VF_PREDS: q.max(axis=-1).astype(np.float32),
                  ACTION_LOGP: np.zeros(len(actions), np.float32),
                  ACTION_DIST_INPUTS: q.astype(np.float32)}
        return actions.astype(np.int64), extras

    def compute_single_action(self, obs, explore: bool = True):
        a, extras = self.compute_actions(obs[None], explore)
        return a[0], {k: v[0] for k, v in extras.items()}

    def value(self, obs: np.ndarray) -> np.ndarray:
        q = self._q(self.params, jnp.asarray(obs, jnp.float32))
        return np.asarray(q.max(axis=-1))

    def get_weights(self):
        return {"params": models.pull_params(self.params),
                "epsilon": self.epsilon}

    def set_weights(self, weights):
        self.params = jax.tree_util.tree_map(jnp.asarray, weights["params"])
        # absent => keep: Ape-X broadcasts params-only dicts so each
        # worker keeps its own exploration-ladder epsilon
        self.epsilon = weights.get("epsilon", self.epsilon)


class ReplayBuffer:
    """Uniform ring buffer over column arrays (reference:
    ``rllib/utils/replay_buffers``)."""

    DEFAULT_KEYS = (OBS, ACTIONS, REWARDS, NEXT_OBS, TERMINATEDS)

    def __init__(self, capacity: int, keys: Optional[tuple] = None):
        self.capacity = capacity
        self.keys = tuple(keys) if keys else self.DEFAULT_KEYS
        self._cols: Dict[str, np.ndarray] = {}
        self._idx = 0
        self._size = 0

    def add_batch(self, batch: SampleBatch) -> None:
        n = batch.count
        for k in self.keys:
            v = batch[k]
            if k not in self._cols:
                self._cols[k] = np.zeros((self.capacity,) + v.shape[1:],
                                         v.dtype)
            idx = (self._idx + np.arange(n)) % self.capacity
            self._cols[k][idx] = v
        self._idx = (self._idx + n) % self.capacity
        self._size = min(self._size + n, self.capacity)

    def sample(self, n: int, rng: np.random.Generator) -> SampleBatch:
        idx = rng.integers(0, self._size, size=n)
        return SampleBatch({k: v[idx] for k, v in self._cols.items()})

    def __len__(self) -> int:
        return self._size


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DQN)
        self._cfg.update({
            "policy_class": DQNPolicy,
            "lr": 5e-4, "buffer_size": 50_000, "learning_starts": 1000,
            "train_batch_size": 32, "target_network_update_freq": 500,
            "initial_epsilon": 1.0, "final_epsilon": 0.02,
            "epsilon_timesteps": 10_000, "gamma": 0.99,
            "rollout_fragment_length": 4, "double_q": True,
            "num_sgd_per_step": 1,
        })


class DQN(Algorithm):
    _default_config_cls = DQNConfig

    def setup(self, config: Dict[str, Any]) -> None:
        policy = self.workers.local_worker.policy
        self.buffer = ReplayBuffer(int(config["buffer_size"]))
        self._optimizer = optax.adam(config["lr"])
        self._opt_state = self._optimizer.init(policy.params)
        self.target_params = policy.params
        self._steps_since_target_sync = 0
        self._sampled = 0
        self._rng = np.random.default_rng(config.get("seed") or 0)
        gamma = float(config["gamma"])
        double_q = bool(config["double_q"])
        q_apply = policy.q_apply
        optimizer = self._optimizer

        def loss_fn(params, target_params, mb):
            q = q_apply(params, mb[OBS])
            q_taken = jnp.take_along_axis(
                q, mb[ACTIONS][:, None].astype(jnp.int32), axis=1)[:, 0]
            q_next_target = q_apply(target_params, mb[NEXT_OBS])
            if double_q:
                q_next_online = q_apply(params, mb[NEXT_OBS])
                best = jnp.argmax(q_next_online, axis=-1)
                q_next = jnp.take_along_axis(
                    q_next_target, best[:, None], axis=1)[:, 0]
            else:
                q_next = q_next_target.max(axis=-1)
            target = mb[REWARDS] + gamma * (1.0 - mb["dones"]) * \
                jax.lax.stop_gradient(q_next)
            td = q_taken - target
            return jnp.square(td).mean(), jnp.abs(td).mean()

        def update(params, target_params, opt_state, mb):
            grads, td = jax.grad(loss_fn, has_aux=True)(
                params, target_params, mb)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, td

        self._update = jax.jit(update)

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._sampled / float(cfg["epsilon_timesteps"]))
        return float(cfg["initial_epsilon"] + frac *
                     (cfg["final_epsilon"] - cfg["initial_epsilon"]))

    def training_step(self) -> Dict[str, Any]:
        policy = self.workers.local_worker.policy
        policy.epsilon = self._epsilon()
        batch = synchronous_parallel_sample(self.workers)
        self._sampled += batch.count
        self.buffer.add_batch(batch)
        info: Dict[str, Any] = {"epsilon": policy.epsilon,
                                "buffer_size": len(self.buffer)}
        if len(self.buffer) < int(self.config["learning_starts"]):
            return info
        for _ in range(int(self.config["num_sgd_per_step"])):
            mb = self.buffer.sample(int(self.config["train_batch_size"]),
                                    self._rng)
            device_mb = {
                OBS: jnp.asarray(mb[OBS]),
                ACTIONS: jnp.asarray(mb[ACTIONS]),
                REWARDS: jnp.asarray(mb[REWARDS]),
                NEXT_OBS: jnp.asarray(mb[NEXT_OBS]),
                "dones": jnp.asarray(mb[TERMINATEDS].astype(np.float32)),
            }
            policy.params, self._opt_state, td = self._update(
                policy.params, self.target_params, self._opt_state,
                device_mb)
            self._steps_since_target_sync += 1
            info["mean_td_error"] = float(td)
        if self._steps_since_target_sync >= \
                int(self.config["target_network_update_freq"]):
            self.target_params = policy.params
            self._steps_since_target_sync = 0
        self.workers.sync_weights()
        return info
