"""A3C: asynchronous advantage actor-critic (gradient-push workers).

Reference: ``rllib/algorithms/a3c/`` (Mnih et al. 2016) — the one
reference execution pattern where workers push GRADIENTS, not samples:
each rollout worker computes ∇L on its own fragment locally and the
learner applies arriving gradients Hogwild-style, re-issuing the worker
with fresh weights.  Versus IMPALA, the learner never touches
observations — for fat observations on a thin interconnect the gradient
(∝ parameter count) is the cheaper thing to ship.

TPU-native shape: the worker-side grad is ONE jitted XLA call over the
whole fragment (policy.compute_gradients builds it lazily from the same
actor-critic apply_fn the sampler uses); the learner's apply is a jitted
optax step.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np
import optax

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig


class A3CConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or A3C)
        self._cfg.update({
            "lr": 1e-4, "num_workers": 2, "rollout_fragment_length": 50,
            "vf_loss_coeff": 0.5, "entropy_coeff": 0.01, "grad_clip": 40.0,
            "grads_per_iteration": 10,
        })


class A3C(Algorithm):
    _default_config_cls = A3CConfig

    def setup(self, config: Dict[str, Any]) -> None:
        policy = self.workers.local_worker.policy
        self._optimizer = optax.chain(
            optax.clip_by_global_norm(float(config["grad_clip"])),
            optax.rmsprop(float(config["lr"]), decay=0.99, eps=0.1))
        self._opt_state = self._optimizer.init(policy.params)
        opt = self._optimizer

        def apply_grads(params, opt_state, grads):
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._apply_grads = jax.jit(apply_grads)
        self._grad_kw = {
            "vf_loss_coeff": float(config["vf_loss_coeff"]),
            "entropy_coeff": float(config["entropy_coeff"]),
        }
        self._in_flight: Dict[Any, Any] = {}
        self._trained_steps = 0

    def training_step(self) -> Dict[str, Any]:
        remotes = self.workers.remote_workers
        n = int(self.config["grads_per_iteration"])
        policy = self.workers.local_worker.policy
        info: Dict[str, Any] = {}
        if not remotes:  # degenerate sync mode for tests
            for _ in range(n):
                grads, count, info = \
                    self.workers.local_worker.compute_gradients(
                        None, **self._grad_kw)
                policy.params, self._opt_state = self._apply_grads(
                    policy.params, self._opt_state, grads)
                self._trained_steps += count
            info = {k: float(v) for k, v in info.items()}
            info["num_env_steps_trained"] = self._trained_steps
            return info
        # Hogwild: keep one gradient computation in flight per worker;
        # each completion is applied immediately and the worker re-issued
        # with the freshest weights.
        weights_ref = ray_tpu.put(policy.get_weights())
        for w in remotes:
            if w not in self._in_flight.values():
                self._in_flight[w.compute_gradients.remote(
                    weights_ref, **self._grad_kw)] = w
        applied = 0
        while applied < n:
            ready, _ = ray_tpu.wait(list(self._in_flight), num_returns=1)
            fut = ready[0]
            worker = self._in_flight.pop(fut)
            grads, count, info = ray_tpu.get(fut)
            policy.params, self._opt_state = self._apply_grads(
                policy.params, self._opt_state, grads)
            self._trained_steps += count
            applied += 1
            weights_ref = ray_tpu.put(policy.get_weights())
            self._in_flight[worker.compute_gradients.remote(
                weights_ref, **self._grad_kw)] = worker
        info = {k: float(np.asarray(v)) for k, v in info.items()}
        info["num_env_steps_trained"] = self._trained_steps
        return info
