from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.apex import APEX, APEXConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.algorithms.ddpg import DDPG, DDPGConfig, TD3, TD3Config
from ray_tpu.rllib.algorithms.es import ES, ESConfig
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.a3c import A3C, A3CConfig
from ray_tpu.rllib.algorithms.marwil import BC, BCConfig, MARWIL, MARWILConfig

__all__ = ["Algorithm", "AlgorithmConfig", "PPO", "PPOConfig",
           "IMPALA", "IMPALAConfig", "DQN", "DQNConfig", "APEX", "APEXConfig",
           "SAC", "SACConfig", "ES", "ESConfig", "APPO", "APPOConfig",
           "A3C", "A3CConfig", "MARWIL", "MARWILConfig", "BC", "BCConfig",
           "DDPG", "DDPGConfig", "TD3", "TD3Config"]
