"""Environment layer: registry, RandomEnv, and synchronous vectorization.

Reference: ``rllib/env/`` (SURVEY.md §2.5) — RLlib wraps gym envs and steps
them in a vectorized inner loop inside each RolloutWorker.  Rebuilt against
the gymnasium 1.x API (``reset() -> (obs, info)``, ``step() -> (obs, r,
terminated, truncated, info)``); ``RandomEnv`` mirrors the reference's
fake-env test pattern (``rllib/env/tests``, SURVEY.md §4) so worker/algorithm
tests run without real env dynamics.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

_ENV_REGISTRY: Dict[str, Callable[[dict], Any]] = {}


def register_env(name: str, creator: Callable[[dict], Any]) -> None:
    """Reference: ``ray.tune.registry.register_env``."""
    _ENV_REGISTRY[name] = creator


class _Box:
    def __init__(self, low, high, shape, dtype=np.float32):
        self.low, self.high = low, high
        self.shape = tuple(shape)
        self.dtype = dtype

    def sample(self, rng=None):
        rng = rng or np.random.default_rng()
        lo = np.broadcast_to(np.asarray(self.low, self.dtype), self.shape)
        hi = np.broadcast_to(np.asarray(self.high, self.dtype), self.shape)
        return rng.uniform(lo, hi).astype(self.dtype)


class _Discrete:
    def __init__(self, n: int):
        self.n = int(n)
        self.shape = ()
        self.dtype = np.int64

    def sample(self, rng=None):
        rng = rng or np.random.default_rng()
        return int(rng.integers(self.n))


def make_box(low, high, shape, dtype=np.float32):
    try:
        from gymnasium import spaces
        return spaces.Box(low=low, high=high, shape=shape, dtype=dtype)
    except ImportError:
        return _Box(low, high, shape, dtype)


def make_discrete(n: int):
    try:
        from gymnasium import spaces
        return spaces.Discrete(n)
    except ImportError:
        return _Discrete(n)


class RandomEnv:
    """Uniform-random observations/rewards; episode length is configurable.
    The reference's fake-env test workhorse."""

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.obs_dim = int(config.get("obs_dim", 4))
        self.num_actions = int(config.get("num_actions", 2))
        self.episode_len = int(config.get("episode_len", 20))
        self.observation_space = make_box(-1.0, 1.0, (self.obs_dim,))
        self.action_space = make_discrete(self.num_actions)
        self._rng = np.random.default_rng(config.get("seed"))
        self._t = 0

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        self._t += 1
        terminated = False
        truncated = self._t >= self.episode_len
        return self._obs(), float(self._rng.uniform()), terminated, \
            truncated, {}

    def _obs(self):
        return self._rng.uniform(-1, 1, (self.obs_dim,)).astype(np.float32)


register_env("RandomEnv", lambda cfg: RandomEnv(cfg))


class RandomPixelEnv:
    """Atari-shaped random pixels (default 84×84×4 uint8) — the pixel
    analog of RandomEnv, used for conv-policy plumbing tests and pixel
    rollout throughput benchmarks (reference: baseline #3 'IMPALA Atari
    pixel' runs 84×84×4 stacked frames; no ALE ships in this image, so
    throughput is measured against synthetic frames of the same shape)."""

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.size = int(config.get("size", 84))
        self.frames = int(config.get("frames", 4))
        self.num_actions = int(config.get("num_actions", 6))
        self.episode_len = int(config.get("episode_len", 128))
        shape = (self.size, self.size, self.frames)
        self.observation_space = make_box(0, 255, shape, np.uint8)
        self.action_space = make_discrete(self.num_actions)
        self._rng = np.random.default_rng(config.get("seed"))
        self._t = 0

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        self._t += 1
        return self._obs(), float(self._rng.uniform()), False, \
            self._t >= self.episode_len, {}

    def _obs(self):
        return self._rng.integers(
            0, 256, (self.size, self.size, self.frames), dtype=np.uint8)


class PixelSquareEnv:
    """Learnable pixel task: a bright square sits in the LEFT or RIGHT
    half of the frame; action 0 = "left", 1 = "right"; reward 1.0 for
    naming the correct side, else 0.  A random policy averages 0.5 —
    only a net that actually *sees* the frame beats it, which makes this
    the conv-policy learning test (an in-tree stand-in for Atari; the
    reference uses ALE which this image does not ship)."""

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.size = int(config.get("size", 84))
        self.frames = int(config.get("frames", 4))
        self.square = int(config.get("square", max(8, self.size // 7)))
        self.episode_len = int(config.get("episode_len", 16))
        if self.square >= self.size // 2:
            raise ValueError(
                f"square ({self.square}) must fit inside one half of the "
                f"frame (size {self.size} → half {self.size // 2}); pass a "
                f"smaller 'square' or a larger 'size'")
        shape = (self.size, self.size, self.frames)
        self.observation_space = make_box(0, 255, shape, np.uint8)
        self.action_space = make_discrete(2)
        self._rng = np.random.default_rng(config.get("seed"))
        self._t = 0
        self._side = 0

    def _obs(self):
        obs = np.zeros((self.size, self.size, self.frames), np.uint8)
        self._side = int(self._rng.integers(2))
        half = self.size // 2
        x0 = int(self._rng.integers(0, half - self.square)) \
            + (half if self._side else 0)
        y0 = int(self._rng.integers(0, self.size - self.square))
        obs[y0:y0 + self.square, x0:x0 + self.square, :] = 255
        return obs

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        return self._obs(), {}

    def step(self, action):
        reward = 1.0 if int(action) == self._side else 0.0
        self._t += 1
        return self._obs(), reward, False, self._t >= self.episode_len, {}


register_env("RandomPixelEnv", lambda cfg: RandomPixelEnv(cfg))
register_env("PixelSquareEnv", lambda cfg: PixelSquareEnv(cfg))


class SlowEnv:
    """Wraps any registered env with a fixed per-step latency
    (``env_config: {"inner": name, "inner_config": {...},
    "step_delay_ms": float}``).

    Models the simulator/remote-game envs async IMPALA exists for: the
    actor spends most of a step WAITING, not computing — exactly the
    latency the actor/learner pipeline hides (reference: IMPALA paper's
    motivation; used by ``rllib_bench.py impala_overlap``)."""

    def __init__(self, cfg: Optional[dict] = None):
        import time as _t
        cfg = cfg or {}
        self._delay = float(cfg.get("step_delay_ms", 2.0)) / 1e3
        self._sleep = _t.sleep
        self._inner = create_env(cfg.get("inner", "RandomEnv"),
                                 cfg.get("inner_config", {}))
        self.observation_space = self._inner.observation_space
        self.action_space = self._inner.action_space

    def reset(self, seed: Optional[int] = None):
        return self._inner.reset(seed=seed)

    def step(self, action):
        self._sleep(self._delay)
        return self._inner.step(action)


register_env("SlowEnv", lambda cfg: SlowEnv(cfg))


def create_env(env: Any, env_config: Optional[dict] = None):
    """Resolve an env spec: registered name, gymnasium id, class, or
    callable."""
    env_config = env_config or {}
    if isinstance(env, str):
        if env in _ENV_REGISTRY:
            return _ENV_REGISTRY[env](env_config)
        import gymnasium
        return gymnasium.make(env, **env_config)
    if isinstance(env, type):
        return env(env_config)
    if callable(env):
        return env(env_config)
    raise ValueError(f"cannot create env from {env!r}")


class VectorEnv:
    """N sub-envs stepped synchronously with auto-reset.

    Reference behavior: ``rllib/env/vector_env.py`` — on termination or
    truncation the sub-env resets immediately and the *reset* obs is
    returned, while done flags mark the boundary for the sampler.
    """

    def __init__(self, env_creator: Callable[[], Any], num_envs: int,
                 seed: Optional[int] = None):
        self.envs = [env_creator() for _ in range(num_envs)]
        self.num_envs = num_envs
        self.observation_space = self.envs[0].observation_space
        self.action_space = self.envs[0].action_space
        self._seed = seed

    def reset_all(self) -> np.ndarray:
        obs = []
        for i, e in enumerate(self.envs):
            seed = None if self._seed is None else self._seed + i
            o, _ = e.reset(seed=seed)
            obs.append(o)
        return np.stack(obs)

    def step(self, actions: np.ndarray):
        """Returns (obs, final_obs, rewards, terminateds, truncateds).

        ``obs`` feeds the next policy step (post-auto-reset at done slots);
        ``final_obs`` is the true successor observation (pre-reset), needed
        to bootstrap truncated episodes correctly.
        """
        obs, finals, rews, terms, truncs = [], [], [], [], []
        for e, a in zip(self.envs, actions):
            o, r, term, trunc, _ = e.step(a)
            finals.append(o)
            if term or trunc:
                o, _ = e.reset()
            obs.append(o)
            rews.append(r)
            terms.append(term)
            truncs.append(trunc)
        return (np.stack(obs), np.stack(finals),
                np.asarray(rews, np.float32),
                np.asarray(terms), np.asarray(truncs))


# ---------------------------------------------------------------- multi-agent
class MultiAgentEnv:
    """Multi-agent env API (reference: ``rllib/env/multi_agent_env.py``).

    ``reset() -> (obs_dict, info_dict)``; ``step(action_dict) ->
    (obs, rewards, terminateds, truncateds, infos)`` — all keyed by agent
    id; ``terminateds``/``truncateds`` additionally carry ``"__all__"``.
    Agents that are done stop appearing in subsequent dicts.
    """

    agents: list
    observation_space: Any = None   # per-agent space (homogeneous default)
    action_space: Any = None

    def reset(self, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError


def make_multi_agent(env_name_or_creator):
    """Lift a single-agent env into an N-agent ``MultiAgentEnv`` of
    independent copies (reference: ``ray.rllib.env.make_multi_agent``).
    ``env_config["num_agents"]`` picks N (default 2)."""

    class _IndependentMultiAgent(MultiAgentEnv):
        def __init__(self, config: Optional[dict] = None):
            config = dict(config or {})
            self.num_agents = int(config.pop("num_agents", 2))
            if isinstance(env_name_or_creator, str):
                mk = lambda: create_env(env_name_or_creator, config)  # noqa: E731
            else:
                mk = lambda: env_name_or_creator(config)  # noqa: E731
            self.envs = [mk() for _ in range(self.num_agents)]
            self.agents = [f"agent_{i}" for i in range(self.num_agents)]
            self.observation_space = self.envs[0].observation_space
            self.action_space = self.envs[0].action_space
            self._done = [False] * self.num_agents

        def reset(self, seed: Optional[int] = None):
            obs, infos = {}, {}
            for i, (aid, e) in enumerate(zip(self.agents, self.envs)):
                o, inf = e.reset(seed=None if seed is None else seed + i)
                obs[aid], infos[aid] = o, inf
            self._done = [False] * self.num_agents
            return obs, infos

        def step(self, action_dict: Dict[str, Any]):
            obs, rews, terms, truncs, infos = {}, {}, {}, {}, {}
            for i, (aid, e) in enumerate(zip(self.agents, self.envs)):
                if self._done[i] or aid not in action_dict:
                    continue
                o, r, term, trunc, inf = e.step(action_dict[aid])
                obs[aid], rews[aid], infos[aid] = o, float(r), inf
                terms[aid], truncs[aid] = bool(term), bool(trunc)
                if term or trunc:
                    self._done[i] = True
            terms["__all__"] = all(self._done)
            truncs["__all__"] = False
            return obs, rews, terms, truncs, infos

    return _IndependentMultiAgent
