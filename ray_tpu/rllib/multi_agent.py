"""Multi-agent rollout worker: policy map + per-agent experience routing.

Reference: RLlib's multi-agent support in ``rllib/evaluation/
rollout_worker.py`` + ``rllib/policy/policy_map.py`` (SURVEY.md §2.5):
a worker holds a MAP of policies, a ``policy_mapping_fn(agent_id)``
routes each agent's experience to one policy, and sampling yields a
``MultiAgentBatch`` of per-policy ``SampleBatch``es.

Config shape (reference parity)::

    config["multiagent"] = {
        "policies": {pid: (policy_cls|None, obs_space|None,
                           act_space|None, config|None), ...}
                    # or just {pid: None} for all-defaults,
        "policy_mapping_fn": lambda agent_id, **kw: pid,
    }
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib import env as env_lib
from ray_tpu.rllib.policy import Policy, compute_gae
from ray_tpu.rllib.sample_batch import (
    ACTIONS, EPS_ID, MultiAgentBatch, NEXT_OBS, OBS, REWARDS, SampleBatch,
    TERMINATEDS, TRUNCATEDS, concat_samples)


def _policy_spec(spec):
    if spec is None:
        return None, None, None, {}
    if isinstance(spec, (tuple, list)):
        cls, obs_sp, act_sp, conf = (list(spec) + [None] * 4)[:4]
        return cls, obs_sp, act_sp, (conf or {})
    return None, None, None, dict(spec)


class MultiAgentRolloutWorker:
    """Steps one MultiAgentEnv; same external surface as RolloutWorker
    (``sample``/``get_weights``/``set_weights``/``get_metrics``), but
    weights and batches are keyed by policy id."""

    def __init__(self, config: Dict[str, Any], worker_index: int = 0):
        self.config = dict(config)
        self.worker_index = worker_index
        seed = config.get("seed")
        if seed is not None:
            seed = int(seed) + 1000 * worker_index
            np.random.seed(seed)
        self.env = env_lib.create_env(config["env"],
                                      config.get("env_config"))
        if not isinstance(self.env, env_lib.MultiAgentEnv):
            raise ValueError("multiagent config requires a MultiAgentEnv")
        ma = config["multiagent"]
        self.mapping = ma["policy_mapping_fn"]
        self.policies: Dict[str, Policy] = {}
        for j, (pid, spec) in enumerate(sorted(ma["policies"].items())):
            cls, obs_sp, act_sp, pconf = _policy_spec(spec)
            cls = cls or config.get("policy_class") or Policy
            merged = dict(config)
            merged.update(pconf)
            merged["seed"] = (seed or 0) + 17 + j
            self.policies[pid] = cls(
                obs_sp or self.env.observation_space,
                act_sp or self.env.action_space, merged)
        self.fragment_length = int(config.get("rollout_fragment_length", 200))
        self.gamma = float(config.get("gamma", 0.99))
        self.lam = float(config.get("lambda", 0.95))
        self._obs, _ = self.env.reset(seed=seed)
        self._eps_id = 1_000_000 * worker_index
        # per-agent open-episode column buffers
        self._buf: Dict[str, Dict[str, list]] = collections.defaultdict(
            lambda: collections.defaultdict(list))
        self._ep_reward = 0.0
        self._ep_len = 0
        self._completed: collections.deque = collections.deque(maxlen=100)
        self._total_steps = 0

    # ------------------------------------------------------------- sampling
    def _agent_pid(self, aid: str) -> str:
        try:
            return self.mapping(aid)
        except TypeError:
            return self.mapping(aid, None)

    def _finalize_agent(self, aid: str, terminated: bool) -> Optional[SampleBatch]:
        cols = self._buf.pop(aid, None)
        if not cols or not cols[OBS]:
            return None
        pid = self._agent_pid(aid)
        batch = SampleBatch({k: np.asarray(v) for k, v in cols.items()})
        last_value = 0.0 if terminated else float(
            self.policies[pid].value(batch[NEXT_OBS][-1:])[0])
        return pid, compute_gae(batch, last_value, self.gamma, self.lam)

    def sample(self) -> MultiAgentBatch:
        out: Dict[str, List[SampleBatch]] = collections.defaultdict(list)
        env_steps = 0
        for _ in range(self.fragment_length):
            # group live agents by policy, act batched per policy
            by_pid: Dict[str, List[str]] = collections.defaultdict(list)
            for aid in self._obs:
                by_pid[self._agent_pid(aid)].append(aid)
            action_dict: Dict[str, Any] = {}
            extras_by_agent: Dict[str, Dict[str, np.ndarray]] = {}
            for pid, aids in by_pid.items():
                obs = np.stack([self._obs[a] for a in aids])
                actions, extras = self.policies[pid].compute_actions(obs)
                for i, a in enumerate(aids):
                    action_dict[a] = actions[i]
                    extras_by_agent[a] = {k: v[i] for k, v in extras.items()}
            prev_obs = self._obs
            obs, rews, terms, truncs, _ = self.env.step(action_dict)
            env_steps += 1
            self._total_steps += 1
            for aid in action_dict:
                b = self._buf[aid]
                b[OBS].append(prev_obs[aid])
                b[ACTIONS].append(action_dict[aid])
                b[REWARDS].append(np.float32(rews.get(aid, 0.0)))
                term = bool(terms.get(aid, False))
                trunc = bool(truncs.get(aid, False))
                # true successor obs: present unless the agent just ended
                b[NEXT_OBS].append(obs.get(aid, prev_obs[aid]))
                b[TERMINATEDS].append(term)
                b[TRUNCATEDS].append(trunc)
                b[EPS_ID].append(np.int64(self._eps_id))
                for k, v in extras_by_agent[aid].items():
                    b[k].append(v)
                self._ep_reward += rews.get(aid, 0.0)
                if term or trunc:
                    fin = self._finalize_agent(aid, terminated=term)
                    if fin:
                        out[fin[0]].append(fin[1])
            self._ep_len += 1
            if terms.get("__all__") or truncs.get("__all__"):
                # a global TRUNCATION (time limit) must bootstrap V(s') for
                # agents without their own terminal flag, same convention
                # as the single-agent worker; terminated=0-bootstrap only
                # on a true global terminal
                all_terminal = bool(terms.get("__all__"))
                for aid in list(self._buf):
                    fin = self._finalize_agent(aid, terminated=all_terminal)
                    if fin:
                        out[fin[0]].append(fin[1])
                self._completed.append((self._ep_reward, self._ep_len))
                self._ep_reward, self._ep_len = 0.0, 0
                self._eps_id += 1
                self._obs, _ = self.env.reset()
            else:
                self._obs = obs
        # fragment cut: close open per-agent episodes with a bootstrap
        for aid in list(self._buf):
            fin = self._finalize_agent(aid, terminated=False)
            if fin:
                out[fin[0]].append(fin[1])
        self._eps_id += 1  # new ids so the next fragment splits cleanly
        return MultiAgentBatch(
            {pid: concat_samples(v) for pid, v in out.items()}, env_steps)

    def sample_with_weights(self, weights: Optional[dict]) -> MultiAgentBatch:
        if weights is not None:
            self.set_weights(weights)
        return self.sample()

    # ------------------------------------------------------------- plumbing
    def get_weights(self) -> dict:
        return {pid: p.get_weights() for pid, p in self.policies.items()}

    def set_weights(self, weights: dict) -> None:
        for pid, w in weights.items():
            if pid in self.policies:
                self.policies[pid].set_weights(w)

    def get_metrics(self) -> Dict[str, Any]:
        eps = list(self._completed)
        self._completed.clear()
        return {"episode_rewards": [r for r, _ in eps],
                "episode_lens": [l for _, l in eps],
                "num_env_steps": self._total_steps}

    def get_spaces(self):
        return (self.env.observation_space, self.env.action_space)

    @property
    def policy(self):  # single-policy convenience (evaluate(), etc.)
        return next(iter(self.policies.values()))
