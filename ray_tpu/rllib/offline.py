"""Offline RL data plane: JSON episode logs → training batches.

Reference: ``rllib/offline/`` (``JsonWriter``/``JsonReader``,
``input_``/``output`` config) — experiences recorded as JSON-lines files
that offline algorithms (BC/MARWIL) train from without touching an env.

Format: one JSON object per line, one EPISODE per object::

    {"obs": [[...], ...], "actions": [...], "rewards": [...],
     "terminated": true}

``OfflineData`` loads every episode, computes discounted monte-carlo
returns (the MARWIL target), and serves uniform transition minibatches
as numpy column dicts — on TPU the whole minibatch feeds one jitted
update.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from ray_tpu.rllib.sample_batch import (ACTIONS, NEXT_OBS, OBS, REWARDS,
                                        SampleBatch, TERMINATEDS, TRUNCATEDS)


class JsonWriter:
    """Append SampleBatches as episode rows (reference: ``JsonWriter``)."""

    def __init__(self, path: str, max_file_size: int = 64 * 1024 * 1024):
        os.makedirs(path, exist_ok=True)
        self._dir = path
        self._max = max_file_size
        self._idx = 0
        self._f = None

    def _file(self):
        if self._f is None or self._f.tell() > self._max:
            if self._f:
                self._f.close()
            self._f = open(os.path.join(
                self._dir, f"output-{self._idx:05d}.json"), "a")
            self._idx += 1
        return self._f

    def write(self, batch: SampleBatch) -> None:
        for ep in batch.split_by_episode():
            terminated = bool(ep[TERMINATEDS][-1])
            row = {
                "obs": np.asarray(ep[OBS]).tolist(),
                "actions": np.asarray(ep[ACTIONS]).tolist(),
                "rewards": np.asarray(ep[REWARDS], np.float64).tolist(),
                "terminated": terminated,
            }
            if not terminated and NEXT_OBS in ep:
                # truncated / fragment-cut: keep the final observation
                # so readers can BOOTSTRAP the return instead of
                # pretending the episode's value ended at truncation
                row["final_obs"] = np.asarray(ep[NEXT_OBS][-1]).tolist()
            f = self._file()
            f.write(json.dumps(row) + "\n")
            f.flush()

    def close(self) -> None:
        if self._f:
            self._f.close()
            self._f = None


class JsonReader:
    """Iterate episode rows from JSON-lines files (reference:
    ``JsonReader``)."""

    def __init__(self, path: str):
        import glob
        if os.path.isdir(path):
            self._files = sorted(glob.glob(os.path.join(path, "*.json")))
        else:
            self._files = [path]
        if not self._files:
            raise FileNotFoundError(f"no offline data under {path!r}")

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        for f in self._files:
            with open(f) as fh:
                for line in fh:
                    line = line.strip()
                    if line:
                        yield json.loads(line)


class OfflineData:
    """All episodes in memory as flat transition columns + MC returns.

    Truncated episodes (``terminated: false``) carry biased zero-tail
    returns unless bootstrapped: ``rebuild_returns(value_fn)`` redoes
    the return computation with V(final_obs) seeding the accumulator —
    MARWIL refreshes this against its own improving value head
    (reference: postprocessing bootstraps truncated trajectories with
    the current policy's value estimate)."""

    def __init__(self, path: str, gamma: float = 0.99):
        self.gamma = float(gamma)
        self._ep_rewards: List[np.ndarray] = []
        self._ep_truncated: List[bool] = []
        self._final_obs: List[Optional[np.ndarray]] = []
        obs: List[np.ndarray] = []
        actions: List[np.ndarray] = []
        self.episodes = 0
        for row in JsonReader(path):
            obs.append(np.asarray(row["obs"], np.float32))
            actions.append(np.asarray(row["actions"]))
            self._ep_rewards.append(np.asarray(row["rewards"], np.float32))
            truncated = not bool(row.get("terminated", True))
            self._ep_truncated.append(truncated)
            fo = row.get("final_obs")
            self._final_obs.append(
                np.asarray(fo, np.float32) if fo is not None else None)
            self.episodes += 1
        if not obs:
            raise ValueError(f"offline dataset at {path!r} is empty")
        self.obs = np.concatenate(obs)
        self.actions = np.concatenate(actions)
        self.count = len(self.obs)
        self.rebuild_returns(None)

    def rebuild_returns(self, value_fn=None) -> None:
        """Recompute MC returns; ``value_fn(obs_batch) -> values`` seeds
        truncated episodes' accumulators (one batched call)."""
        boots = np.zeros(self.episodes, np.float32)
        if value_fn is not None:
            idx = [i for i in range(self.episodes)
                   if self._ep_truncated[i] and
                   self._final_obs[i] is not None]
            if idx:
                vals = np.asarray(value_fn(
                    np.stack([self._final_obs[i] for i in idx])))
                boots[idx] = vals.astype(np.float32)
        rets = []
        for i, r in enumerate(self._ep_rewards):
            ret = np.zeros_like(r)
            acc = float(boots[i])
            for t in range(len(r) - 1, -1, -1):
                acc = r[t] + self.gamma * acc
                ret[t] = acc
            rets.append(ret)
        self.returns = np.concatenate(rets)

    def minibatch(self, rng: np.random.Generator,
                  size: int) -> Dict[str, np.ndarray]:
        idx = rng.integers(0, self.count, size=min(size, self.count))
        return {OBS: self.obs[idx], ACTIONS: self.actions[idx],
                "returns": self.returns[idx]}


def record_rollouts(policy, env_name: str, path: str, *,
                    episodes: int = 20, env_config: Optional[dict] = None,
                    explore: bool = True, seed: int = 0) -> int:
    """Roll a policy in an env and write the episodes as offline data
    (the test/demo producer; reference: ``rllib rollout --out``)."""
    from ray_tpu.rllib import env as env_lib
    e = env_lib.create_env(env_name, env_config)
    w = JsonWriter(path)
    steps = 0
    for ep in range(episodes):
        o, _ = e.reset(seed=seed + ep)
        cols = {OBS: [], ACTIONS: [], REWARDS: [], NEXT_OBS: [],
                TERMINATEDS: [], TRUNCATEDS: [], "eps_id": []}
        done = False
        while not done:
            a, _ = policy.compute_single_action(
                np.asarray(o, np.float32), explore=explore)
            o2, r, term, trunc, _ = e.step(a)
            cols[OBS].append(np.asarray(o, np.float32))
            cols[ACTIONS].append(a)
            cols[REWARDS].append(float(r))
            cols[NEXT_OBS].append(np.asarray(o2, np.float32))
            cols[TERMINATEDS].append(bool(term))
            cols[TRUNCATEDS].append(bool(trunc))
            cols["eps_id"].append(ep)
            o = o2
            done = term or trunc
            steps += 1
        w.write(SampleBatch({k: np.asarray(v) for k, v in cols.items()}))
    w.close()
    return steps
