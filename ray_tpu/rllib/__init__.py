"""ray_tpu.rllib: reinforcement learning on TPU actors.

Reference: ``rllib/`` (SURVEY.md §2.5, §3.5).  Rollout workers are CPU
actors stepping vectorized envs with one jitted policy call per step; the
learner is a jitted XLA program (PPO: all SGD epochs in one jit; IMPALA:
V-trace update) that on TPU hardware runs on the chip.
"""

from ray_tpu.rllib.sample_batch import (MultiAgentBatch, SampleBatch,
                                        concat_samples)
from ray_tpu.rllib.env import (MultiAgentEnv, RandomEnv, VectorEnv,
                               make_multi_agent, register_env)
from ray_tpu.rllib.policy import Policy, compute_gae
from ray_tpu.rllib.evaluation import (
    RolloutWorker, WorkerSet, collect_metrics, synchronous_parallel_sample)
from ray_tpu.rllib.multi_agent import MultiAgentRolloutWorker
from ray_tpu.rllib.algorithms import (
    A3C, A3CConfig, APEX, APEXConfig, APPO, APPOConfig, Algorithm,
    AlgorithmConfig, BC, BCConfig, DQN, DQNConfig, IMPALA, IMPALAConfig,
    MARWIL, MARWILConfig, PPO,
    PPOConfig)
from ray_tpu.rllib.algorithms.impala import vtrace

__all__ = [
    "SampleBatch", "MultiAgentBatch", "concat_samples", "RandomEnv",
    "VectorEnv", "register_env", "MultiAgentEnv", "make_multi_agent",
    "Policy", "compute_gae", "RolloutWorker", "MultiAgentRolloutWorker",
    "WorkerSet", "collect_metrics", "synchronous_parallel_sample",
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "IMPALA",
    "IMPALAConfig", "DQN", "DQNConfig", "APEX", "APEXConfig", "vtrace",
    "APPO", "APPOConfig", "A3C", "A3CConfig", "MARWIL", "MARWILConfig",
    "BC", "BCConfig",
]
