"""Policy/value networks and action distributions — pure JAX.

Reference: ``rllib/models/`` catalog + ``ModelV2`` (SURVEY.md §2.5).  The
reference builds torch/tf modules; here networks are (init, apply) function
pairs over pytrees so the whole learner step jits into one XLA program —
the MXU sees a handful of batched matmuls per update, nothing else.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, jax.Array]


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    obs_dim: int
    num_outputs: int          # logits dim (discrete: n; gaussian: 2*act_dim)
    hiddens: Tuple[int, ...] = (256, 256)
    free_log_std: bool = False
    # Pixel path (reference: rllib/models catalog CNNs): non-empty
    # conv_filters → a shared conv torso ((out_ch, kernel, stride) per
    # layer, VALID padding, relu) + dense head feeds separate linear
    # pi/vf (or Q) heads.  obs are NHWC uint8-scale [0,255]; the torso
    # divides by 255.
    obs_shape: Tuple[int, ...] = ()
    conv_filters: Tuple[Tuple[int, int, int], ...] = ()
    conv_dense: int = 512


# The Nature DQN / IMPALA torso (Mnih et al. 2015): the reference's
# default Atari conv stack in rllib/models/catalog.py.
NATURE_CNN_FILTERS = ((32, 8, 4), (64, 4, 2), (64, 3, 1))


def make_model_config(observation_space, action_space,
                      config: dict) -> ModelConfig:
    """Catalog entry point (reference: ModelCatalog): rank-3 Box obs get
    the Nature CNN unless ``config['conv_filters']`` overrides."""
    obs_shape = tuple(observation_space.shape)
    conv = config.get("conv_filters")
    if conv is None and len(obs_shape) == 3:
        conv = NATURE_CNN_FILTERS
    return ModelConfig(
        obs_dim=flat_obs_dim(observation_space),
        num_outputs=num_dist_inputs(action_space),
        hiddens=tuple(config.get("fcnet_hiddens", (256, 256))),
        obs_shape=obs_shape,
        conv_filters=tuple(tuple(f) for f in conv) if conv else (),
        conv_dense=int(config.get("conv_dense", 512)))


def _init_linear(key, fan_in, fan_out, scale=np.sqrt(2)):
    """Orthogonal init — the standard PPO-stability choice."""
    w = jax.random.orthogonal(key, max(fan_in, fan_out))[:fan_in, :fan_out]
    return {"w": (w * scale).astype(jnp.float32),
            "b": jnp.zeros((fan_out,), jnp.float32)}


def init_actor_critic(key: jax.Array, cfg: ModelConfig) -> Params:
    """Separate policy and value towers (reference default: two MLPs)."""
    sizes = (cfg.obs_dim, *cfg.hiddens)
    keys = jax.random.split(key, 2 * len(cfg.hiddens) + 2)
    params: Params = {}
    for tower in ("pi", "vf"):
        off = 0 if tower == "pi" else len(cfg.hiddens) + 1
        for i, (fi, fo) in enumerate(zip(sizes[:-1], sizes[1:])):
            params[f"{tower}_{i}"] = _init_linear(keys[off + i], fi, fo)
    params["pi_out"] = _init_linear(keys[len(cfg.hiddens)],
                                    sizes[-1], cfg.num_outputs, scale=0.01)
    params["vf_out"] = _init_linear(keys[-1], sizes[-1], 1, scale=1.0)
    return params


def actor_critic_apply(params: Params, obs: jax.Array,
                       num_hidden: int) -> Tuple[jax.Array, jax.Array]:
    """Returns (dist_inputs [B, num_outputs], values [B])."""
    x = obs
    for i in range(num_hidden):
        p = params[f"pi_{i}"]
        x = jnp.tanh(x @ p["w"] + p["b"])
    logits = x @ params["pi_out"]["w"] + params["pi_out"]["b"]
    v = obs
    for i in range(num_hidden):
        p = params[f"vf_{i}"]
        v = jnp.tanh(v @ p["w"] + p["b"])
    values = (v @ params["vf_out"]["w"] + params["vf_out"]["b"])[:, 0]
    return logits, values


# ------------------------------------------------------------- conv torso

def _conv_out_hw(hw: int, kernel: int, stride: int) -> int:
    return (hw - kernel) // stride + 1


def conv_torso_feature_dim(cfg: ModelConfig) -> int:
    return cfg.conv_dense


def init_conv_torso(key: jax.Array, cfg: ModelConfig) -> Params:
    """Shared conv feature net: conv stack (VALID, relu) → dense(relu)."""
    H, W, C = cfg.obs_shape
    keys = jax.random.split(key, len(cfg.conv_filters) + 1)
    params: Params = {}
    in_c = C
    for i, (out_c, k, s) in enumerate(cfg.conv_filters):
        fan_in = k * k * in_c
        w = jax.random.normal(keys[i], (k, k, in_c, out_c), jnp.float32)
        params[f"conv_{i}"] = {"w": w * np.sqrt(2.0 / fan_in),
                               "b": jnp.zeros((out_c,), jnp.float32)}
        H, W, in_c = _conv_out_hw(H, k, s), _conv_out_hw(W, k, s), out_c
    params["dense"] = _init_linear(keys[-1], H * W * in_c, cfg.conv_dense)
    return params


def conv_torso_apply(params: Params, obs: jax.Array,
                     cfg: ModelConfig) -> jax.Array:
    """(B, H, W, C) [0,255] → (B, conv_dense) relu features."""
    x = obs.astype(jnp.float32) / 255.0
    for i, (_, _, s) in enumerate(cfg.conv_filters):
        p = params[f"conv_{i}"]
        x = jax.lax.conv_general_dilated(
            x, p["w"], window_strides=(s, s), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + p["b"]
        x = jax.nn.relu(x)
    x = x.reshape(x.shape[0], -1)
    p = params["dense"]
    return jax.nn.relu(x @ p["w"] + p["b"])


def init_actor_critic_conv(key: jax.Array, cfg: ModelConfig) -> Params:
    """Shared conv torso + separate linear pi/vf heads (the reference's
    Atari actor-critic shape)."""
    kt, kp, kv = jax.random.split(key, 3)
    feat = conv_torso_feature_dim(cfg)
    return {"torso": init_conv_torso(kt, cfg),
            "pi_out": _init_linear(kp, feat, cfg.num_outputs, scale=0.01),
            "vf_out": _init_linear(kv, feat, 1, scale=1.0)}


def actor_critic_conv_apply(params: Params, obs: jax.Array,
                            cfg: ModelConfig
                            ) -> Tuple[jax.Array, jax.Array]:
    f = conv_torso_apply(params["torso"], obs, cfg)
    logits = f @ params["pi_out"]["w"] + params["pi_out"]["b"]
    values = (f @ params["vf_out"]["w"] + params["vf_out"]["b"])[:, 0]
    return logits, values


def init_q_net_conv(key: jax.Array, cfg: ModelConfig) -> Params:
    kt, kq = jax.random.split(key)
    return {"torso": init_conv_torso(kt, cfg),
            "q_out": _init_linear(kq, conv_torso_feature_dim(cfg),
                                  cfg.num_outputs, scale=1.0)}


def q_net_conv_apply(params: Params, obs: jax.Array,
                     cfg: ModelConfig) -> jax.Array:
    f = conv_torso_apply(params["torso"], obs, cfg)
    return f @ params["q_out"]["w"] + params["q_out"]["b"]


# ------------------------------------------------- catalog dispatchers

def make_actor_critic(key: jax.Array, cfg: ModelConfig):
    """(params, apply(params, obs) -> (dist_inputs, values)) per catalog."""
    if cfg.conv_filters:
        return (init_actor_critic_conv(key, cfg),
                lambda p, obs: actor_critic_conv_apply(p, obs, cfg))
    n_hidden = len(cfg.hiddens)
    return (init_actor_critic(key, cfg),
            lambda p, obs: actor_critic_apply(p, obs, n_hidden))


def make_q_net(key: jax.Array, cfg: ModelConfig):
    """(params, apply(params, obs) -> q-values) per catalog."""
    if cfg.conv_filters:
        return (init_q_net_conv(key, cfg),
                lambda p, obs: q_net_conv_apply(p, obs, cfg))
    n_layers = len(cfg.hiddens) + 1
    return (init_q_net(key, cfg),
            lambda p, obs: q_net_apply(p, obs, n_layers))


def init_q_net(key: jax.Array, cfg: ModelConfig) -> Params:
    sizes = (cfg.obs_dim, *cfg.hiddens, cfg.num_outputs)
    keys = jax.random.split(key, len(sizes) - 1)
    return {f"q_{i}": _init_linear(k, fi, fo)
            for i, (k, fi, fo) in enumerate(zip(keys, sizes[:-1], sizes[1:]))}


def q_net_apply(params: Params, obs: jax.Array, num_layers: int) -> jax.Array:
    x = obs
    for i in range(num_layers):
        p = params[f"q_{i}"]
        x = x @ p["w"] + p["b"]
        if i < num_layers - 1:
            x = jnp.tanh(x)
    return x


# ---------------------------------------------------------------- dists

class Categorical:
    """Discrete action distribution over logits."""

    @staticmethod
    def sample(logits: jax.Array, key: jax.Array) -> jax.Array:
        return jax.random.categorical(key, logits)

    @staticmethod
    def logp(logits: jax.Array, actions: jax.Array) -> jax.Array:
        logp_all = jax.nn.log_softmax(logits)
        return jnp.take_along_axis(
            logp_all, actions[:, None].astype(jnp.int32), axis=1)[:, 0]

    @staticmethod
    def entropy(logits: jax.Array) -> jax.Array:
        logp = jax.nn.log_softmax(logits)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

    @staticmethod
    def kl(logits_p: jax.Array, logits_q: jax.Array) -> jax.Array:
        lp, lq = jax.nn.log_softmax(logits_p), jax.nn.log_softmax(logits_q)
        return jnp.sum(jnp.exp(lp) * (lp - lq), axis=-1)

    @staticmethod
    def deterministic(logits: jax.Array) -> jax.Array:
        return jnp.argmax(logits, axis=-1)


class DiagGaussian:
    """Continuous actions; dist_inputs = concat(mean, log_std)."""

    @staticmethod
    def _split(inputs):
        mean, log_std = jnp.split(inputs, 2, axis=-1)
        return mean, jnp.clip(log_std, -20.0, 2.0)

    @staticmethod
    def sample(inputs: jax.Array, key: jax.Array) -> jax.Array:
        mean, log_std = DiagGaussian._split(inputs)
        return mean + jnp.exp(log_std) * jax.random.normal(key, mean.shape)

    @staticmethod
    def logp(inputs: jax.Array, actions: jax.Array) -> jax.Array:
        mean, log_std = DiagGaussian._split(inputs)
        z = (actions - mean) / jnp.exp(log_std)
        return jnp.sum(-0.5 * z**2 - log_std
                       - 0.5 * jnp.log(2 * jnp.pi), axis=-1)

    @staticmethod
    def entropy(inputs: jax.Array) -> jax.Array:
        _, log_std = DiagGaussian._split(inputs)
        return jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)

    @staticmethod
    def kl(inputs_p: jax.Array, inputs_q: jax.Array) -> jax.Array:
        mp, lp = DiagGaussian._split(inputs_p)
        mq, lq = DiagGaussian._split(inputs_q)
        return jnp.sum(lq - lp + (jnp.exp(2 * lp) + (mp - mq) ** 2)
                       / (2 * jnp.exp(2 * lq)) - 0.5, axis=-1)

    @staticmethod
    def deterministic(inputs: jax.Array) -> jax.Array:
        mean, _ = DiagGaussian._split(inputs)
        return mean


# ------------------------------------------------- fast weight transfer

@jax.jit
def _flatten_tree(params):
    return jnp.concatenate(
        [x.reshape(-1).astype(jnp.float32)
         for x in jax.tree_util.tree_leaves(params)])


def pull_params(params) -> Dict:
    """Device→host copy of a param pytree as ONE flat transfer.

    A per-leaf ``np.asarray`` tree_map pays a full dispatch round-trip per
    leaf — measured 1.6-6.4s for a 6.8MB Nature-CNN tree on a
    relay-attached chip vs 0.76s flat (the transfer itself is the floor).
    Weight broadcast is on the learner's critical path in IMPALA, so this
    is the default pull everywhere weights move to rollout workers.

    The flat path concatenates in float32, which is only lossless when
    every leaf IS float32 — a mixed tree (int step counters, float64)
    would be silently rounded, so those trees take one
    ``jax.device_get`` of the whole tree instead (slower on a relay
    link, still a single batched host transfer)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not all(getattr(leaf, "dtype", None) == jnp.float32
               for leaf in leaves):
        return jax.device_get(params)
    flat = np.asarray(_flatten_tree(params))
    out, off = [], 0
    for leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        out.append(flat[off:off + n].reshape(leaf.shape))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def get_dist_class(action_space):
    if hasattr(action_space, "n"):
        return Categorical
    return DiagGaussian


def num_dist_inputs(action_space) -> int:
    if hasattr(action_space, "n"):
        return int(action_space.n)
    return 2 * int(np.prod(action_space.shape))


def flat_obs_dim(observation_space) -> int:
    return int(np.prod(observation_space.shape))
