"""Policy/value networks and action distributions — pure JAX.

Reference: ``rllib/models/`` catalog + ``ModelV2`` (SURVEY.md §2.5).  The
reference builds torch/tf modules; here networks are (init, apply) function
pairs over pytrees so the whole learner step jits into one XLA program —
the MXU sees a handful of batched matmuls per update, nothing else.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, jax.Array]


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    obs_dim: int
    num_outputs: int          # logits dim (discrete: n; gaussian: 2*act_dim)
    hiddens: Tuple[int, ...] = (256, 256)
    free_log_std: bool = False


def _init_linear(key, fan_in, fan_out, scale=np.sqrt(2)):
    """Orthogonal init — the standard PPO-stability choice."""
    w = jax.random.orthogonal(key, max(fan_in, fan_out))[:fan_in, :fan_out]
    return {"w": (w * scale).astype(jnp.float32),
            "b": jnp.zeros((fan_out,), jnp.float32)}


def init_actor_critic(key: jax.Array, cfg: ModelConfig) -> Params:
    """Separate policy and value towers (reference default: two MLPs)."""
    sizes = (cfg.obs_dim, *cfg.hiddens)
    keys = jax.random.split(key, 2 * len(cfg.hiddens) + 2)
    params: Params = {}
    for tower in ("pi", "vf"):
        off = 0 if tower == "pi" else len(cfg.hiddens) + 1
        for i, (fi, fo) in enumerate(zip(sizes[:-1], sizes[1:])):
            params[f"{tower}_{i}"] = _init_linear(keys[off + i], fi, fo)
    params["pi_out"] = _init_linear(keys[len(cfg.hiddens)],
                                    sizes[-1], cfg.num_outputs, scale=0.01)
    params["vf_out"] = _init_linear(keys[-1], sizes[-1], 1, scale=1.0)
    return params


def actor_critic_apply(params: Params, obs: jax.Array,
                       num_hidden: int) -> Tuple[jax.Array, jax.Array]:
    """Returns (dist_inputs [B, num_outputs], values [B])."""
    x = obs
    for i in range(num_hidden):
        p = params[f"pi_{i}"]
        x = jnp.tanh(x @ p["w"] + p["b"])
    logits = x @ params["pi_out"]["w"] + params["pi_out"]["b"]
    v = obs
    for i in range(num_hidden):
        p = params[f"vf_{i}"]
        v = jnp.tanh(v @ p["w"] + p["b"])
    values = (v @ params["vf_out"]["w"] + params["vf_out"]["b"])[:, 0]
    return logits, values


def init_q_net(key: jax.Array, cfg: ModelConfig) -> Params:
    sizes = (cfg.obs_dim, *cfg.hiddens, cfg.num_outputs)
    keys = jax.random.split(key, len(sizes) - 1)
    return {f"q_{i}": _init_linear(k, fi, fo)
            for i, (k, fi, fo) in enumerate(zip(keys, sizes[:-1], sizes[1:]))}


def q_net_apply(params: Params, obs: jax.Array, num_layers: int) -> jax.Array:
    x = obs
    for i in range(num_layers):
        p = params[f"q_{i}"]
        x = x @ p["w"] + p["b"]
        if i < num_layers - 1:
            x = jnp.tanh(x)
    return x


# ---------------------------------------------------------------- dists

class Categorical:
    """Discrete action distribution over logits."""

    @staticmethod
    def sample(logits: jax.Array, key: jax.Array) -> jax.Array:
        return jax.random.categorical(key, logits)

    @staticmethod
    def logp(logits: jax.Array, actions: jax.Array) -> jax.Array:
        logp_all = jax.nn.log_softmax(logits)
        return jnp.take_along_axis(
            logp_all, actions[:, None].astype(jnp.int32), axis=1)[:, 0]

    @staticmethod
    def entropy(logits: jax.Array) -> jax.Array:
        logp = jax.nn.log_softmax(logits)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

    @staticmethod
    def kl(logits_p: jax.Array, logits_q: jax.Array) -> jax.Array:
        lp, lq = jax.nn.log_softmax(logits_p), jax.nn.log_softmax(logits_q)
        return jnp.sum(jnp.exp(lp) * (lp - lq), axis=-1)

    @staticmethod
    def deterministic(logits: jax.Array) -> jax.Array:
        return jnp.argmax(logits, axis=-1)


class DiagGaussian:
    """Continuous actions; dist_inputs = concat(mean, log_std)."""

    @staticmethod
    def _split(inputs):
        mean, log_std = jnp.split(inputs, 2, axis=-1)
        return mean, jnp.clip(log_std, -20.0, 2.0)

    @staticmethod
    def sample(inputs: jax.Array, key: jax.Array) -> jax.Array:
        mean, log_std = DiagGaussian._split(inputs)
        return mean + jnp.exp(log_std) * jax.random.normal(key, mean.shape)

    @staticmethod
    def logp(inputs: jax.Array, actions: jax.Array) -> jax.Array:
        mean, log_std = DiagGaussian._split(inputs)
        z = (actions - mean) / jnp.exp(log_std)
        return jnp.sum(-0.5 * z**2 - log_std
                       - 0.5 * jnp.log(2 * jnp.pi), axis=-1)

    @staticmethod
    def entropy(inputs: jax.Array) -> jax.Array:
        _, log_std = DiagGaussian._split(inputs)
        return jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)

    @staticmethod
    def kl(inputs_p: jax.Array, inputs_q: jax.Array) -> jax.Array:
        mp, lp = DiagGaussian._split(inputs_p)
        mq, lq = DiagGaussian._split(inputs_q)
        return jnp.sum(lq - lp + (jnp.exp(2 * lp) + (mp - mq) ** 2)
                       / (2 * jnp.exp(2 * lq)) - 0.5, axis=-1)

    @staticmethod
    def deterministic(inputs: jax.Array) -> jax.Array:
        mean, _ = DiagGaussian._split(inputs)
        return mean


def get_dist_class(action_space):
    if hasattr(action_space, "n"):
        return Categorical
    return DiagGaussian


def num_dist_inputs(action_space) -> int:
    if hasattr(action_space, "n"):
        return int(action_space.n)
    return 2 * int(np.prod(action_space.shape))


def flat_obs_dim(observation_space) -> int:
    return int(np.prod(observation_space.shape))
