"""RolloutWorker + WorkerSet: the sampling side of every algorithm.

Reference: ``rllib/evaluation/rollout_worker.py`` + ``WorkerSet``
(SURVEY.md §2.5, §3.5) — each worker holds env(s) + a policy copy, steps the
vectorized env in its hot loop, and emits SampleBatches; the set is 1 local
worker + N remote actors.  Rebuilt: the policy inference inside the loop is
a single jitted call over the whole vector of envs.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib import env as env_lib
from ray_tpu.rllib.policy import Policy, compute_gae
from ray_tpu.rllib.sample_batch import (
    ACTIONS, EPS_ID, OBS, NEXT_OBS, REWARDS, SampleBatch, TERMINATEDS,
    TRUNCATEDS, concat_samples)


class RolloutWorker:
    """Holds ``num_envs_per_worker`` envs + a policy; ``sample()`` returns a
    postprocessed SampleBatch of ``rollout_fragment_length *
    num_envs_per_worker`` timesteps."""

    def __init__(self, config: Dict[str, Any], worker_index: int = 0):
        self.config = dict(config)
        self.worker_index = worker_index
        num_envs = int(config.get("num_envs_per_worker", 1))
        seed = config.get("seed")
        if seed is not None:
            seed = int(seed) + 1000 * worker_index
            np.random.seed(seed)
        creator = lambda: env_lib.create_env(  # noqa: E731
            config["env"], config.get("env_config"))
        self.vector_env = env_lib.VectorEnv(creator, num_envs, seed=seed)
        pol_config = dict(config)
        pol_config["seed"] = (seed or 0) + 17
        policy_cls = config.get("policy_class") or Policy
        self.policy = policy_cls(self.vector_env.observation_space,
                                 self.vector_env.action_space, pol_config)
        self.fragment_length = int(config.get("rollout_fragment_length", 200))
        self.gamma = float(config.get("gamma", 0.99))
        self.lam = float(config.get("lambda", 0.95))
        self._obs = self.vector_env.reset_all()
        self._eps_ids = np.arange(num_envs, dtype=np.int64) \
            + 1_000_000 * worker_index
        self._next_eps_id = num_envs
        self._ep_rewards = np.zeros(num_envs, np.float64)
        self._ep_lens = np.zeros(num_envs, np.int64)
        self._completed: collections.deque = collections.deque(maxlen=100)
        self._total_steps = 0

    def sample(self) -> SampleBatch:
        num_envs = self.vector_env.num_envs
        T = self.fragment_length
        cols: Dict[str, list] = collections.defaultdict(list)
        for _ in range(T):
            actions, extras = self.policy.compute_actions(self._obs)
            next_obs, final_obs, rewards, terms, truncs = \
                self.vector_env.step(actions)
            cols[OBS].append(self._obs)
            cols[ACTIONS].append(actions)
            cols[REWARDS].append(rewards)
            cols[NEXT_OBS].append(final_obs)
            cols[TERMINATEDS].append(terms)
            cols[TRUNCATEDS].append(truncs)
            cols[EPS_ID].append(self._eps_ids.copy())
            for k, v in extras.items():
                cols[k].append(v)
            self._ep_rewards += rewards
            self._ep_lens += 1
            done = terms | truncs
            for i in np.flatnonzero(done):
                self._completed.append(
                    (float(self._ep_rewards[i]), int(self._ep_lens[i])))
                self._ep_rewards[i] = 0.0
                self._ep_lens[i] = 0
                self._eps_ids[i] = (1_000_000 * self.worker_index
                                    + self._next_eps_id)
                self._next_eps_id += 1
            self._obs = next_obs
            self._total_steps += num_envs

        # [T, num_envs, ...] → per-env rows, then postprocess per episode.
        stacked = {k: np.stack(v) for k, v in cols.items()}
        per_env = []
        for i in range(num_envs):
            env_batch = SampleBatch({k: v[:, i] for k, v in stacked.items()})
            for ep in env_batch.split_by_episode():
                # Terminated → compute_gae bootstraps 0; truncated or
                # fragment-cut → bootstrap with V(true final obs).
                last_value = float(self.policy.value(ep[NEXT_OBS][-1:])[0])
                per_env.append(compute_gae(ep, last_value, self.gamma,
                                           self.lam))
        return concat_samples(per_env)

    def sample_with_weights(self, weights: Optional[dict]) -> SampleBatch:
        """One round trip: set weights then sample (IMPALA-style pipeline)."""
        if weights is not None:
            self.policy.set_weights(weights)
        return self.sample()

    def compute_gradients(self, weights: Optional[dict],
                          vf_loss_coeff: float = 0.5,
                          entropy_coeff: float = 0.01):
        """A3C worker step: sample a fragment, compute a2c gradients ON
        THE WORKER, return (numpy grad tree, steps, metrics) — the
        gradient-push execution pattern (reference: a3c async_optimizer).
        The jitted grad fn is built lazily from the policy's own
        apply_fn/dist and reused across calls."""
        if weights is not None:
            self.policy.set_weights(weights)
        batch = self.sample()
        grad_fn = getattr(self, "_a2c_grad_fn", None)
        if grad_fn is None:
            import jax
            import jax.numpy as jnp
            apply_fn = self.policy.apply_fn
            dist = self.policy.dist_class
            from ray_tpu.rllib.sample_batch import (ADVANTAGES,
                                                    VALUE_TARGETS)

            def loss(params, obs, actions, adv, targets, vf_c, ent_c):
                inputs, values = apply_fn(params, obs)
                logp = dist.logp(inputs, actions)
                entropy = dist.entropy(inputs).mean()
                pi_loss = -(logp * adv).mean()
                vf_loss = 0.5 * jnp.square(values - targets).mean()
                total = pi_loss + vf_c * vf_loss - ent_c * entropy
                return total, (pi_loss, vf_loss, entropy)

            grad_fn = jax.jit(jax.grad(loss, has_aux=True))
            self._a2c_grad_fn = grad_fn
            self._a2c_cols = (ADVANTAGES, VALUE_TARGETS)
        adv_k, tgt_k = self._a2c_cols
        adv = batch[adv_k]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        grads, (pi_l, vf_l, ent) = grad_fn(
            self.policy.params,
            np.asarray(batch[OBS], np.float32), batch[ACTIONS], adv,
            batch[tgt_k], vf_loss_coeff, entropy_coeff)
        import jax
        grads = jax.tree_util.tree_map(np.asarray, grads)
        return grads, batch.count, {
            "policy_loss": float(pi_l), "vf_loss": float(vf_l),
            "entropy": float(ent)}

    def get_weights(self) -> dict:
        return self.policy.get_weights()

    def set_weights(self, weights: dict) -> None:
        self.policy.set_weights(weights)

    def get_metrics(self) -> Dict[str, Any]:
        eps = list(self._completed)
        self._completed.clear()
        return {
            "episode_rewards": [r for r, _ in eps],
            "episode_lens": [l for _, l in eps],
            "num_env_steps": self._total_steps,
        }

    def get_spaces(self):
        return (self.vector_env.observation_space,
                self.vector_env.action_space)


class WorkerSet:
    """1 local worker (learner-side policy + spaces) + N remote actors."""

    def __init__(self, config: Dict[str, Any]):
        self.config = config
        worker_cls = RolloutWorker
        if config.get("multiagent"):
            from ray_tpu.rllib.multi_agent import MultiAgentRolloutWorker
            worker_cls = MultiAgentRolloutWorker
        self.local_worker = worker_cls(config, worker_index=0)
        num_workers = int(config.get("num_workers", 0))
        remote_cls = ray_tpu.remote(worker_cls).options(
            num_cpus=config.get("num_cpus_per_worker", 1))
        self.remote_workers: List = [
            remote_cls.remote(config, worker_index=i + 1)
            for i in range(num_workers)]

    def sync_weights(self) -> None:
        """Broadcast local weights to all remotes via one object-store put."""
        if not self.remote_workers:
            return
        ref = ray_tpu.put(self.local_worker.get_weights())
        ray_tpu.get([w.set_weights.remote(ref)
                     for w in self.remote_workers])

    def stop(self) -> None:
        for w in self.remote_workers:
            ray_tpu.kill(w)
        self.remote_workers = []


def synchronous_parallel_sample(worker_set: WorkerSet) -> SampleBatch:
    """Reference: ``rllib/execution/rollout_ops.py`` — one sample() round
    across the set (remote if any remotes, else local)."""
    if worker_set.remote_workers:
        batches = ray_tpu.get(
            [w.sample.remote() for w in worker_set.remote_workers])
    else:
        batches = [worker_set.local_worker.sample()]
    from ray_tpu.rllib.sample_batch import MultiAgentBatch
    if isinstance(batches[0], MultiAgentBatch):
        return MultiAgentBatch.concat_samples(batches)
    return concat_samples(batches)


def collect_metrics(worker_set: WorkerSet) -> Dict[str, Any]:
    if worker_set.remote_workers:
        metrics = ray_tpu.get([w.get_metrics.remote()
                               for w in worker_set.remote_workers])
    else:
        metrics = [worker_set.local_worker.get_metrics()]
    rewards: List[float] = []
    lens: List[int] = []
    steps = 0
    for m in metrics:
        rewards += m["episode_rewards"]
        lens += m["episode_lens"]
        steps += m["num_env_steps"]
    return {
        "episode_reward_mean": float(np.mean(rewards)) if rewards else
        float("nan"),
        "episode_reward_max": float(np.max(rewards)) if rewards else
        float("nan"),
        "episode_reward_min": float(np.min(rewards)) if rewards else
        float("nan"),
        "episode_len_mean": float(np.mean(lens)) if lens else float("nan"),
        "episodes_this_iter": len(rewards),
        "num_env_steps_sampled": steps,
    }
