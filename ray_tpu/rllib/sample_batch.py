"""SampleBatch: the unit of experience flowing rollout workers → learner.

Reference: ``rllib/policy/sample_batch.py`` (SURVEY.md §2.5) — a dict of
column-aligned arrays with concat / shuffle / minibatch utilities.  Rebuilt
numpy-first: columns are contiguous ``np.ndarray``s so a batch crosses the
object store zero-copy and lands in HBM with one ``jax.device_put``.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Sequence

import numpy as np

# Standard column names (reference: SampleBatch.OBS etc.).
OBS = "obs"
NEXT_OBS = "new_obs"
ACTIONS = "actions"
REWARDS = "rewards"
TERMINATEDS = "terminateds"
TRUNCATEDS = "truncateds"
INFOS = "infos"
EPS_ID = "eps_id"
ACTION_LOGP = "action_logp"
ACTION_DIST_INPUTS = "action_dist_inputs"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"


class SampleBatch(dict):
    """A column-oriented batch of experience.  Maps str → np.ndarray; all
    columns share leading dimension ``count``."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        for k, v in list(self.items()):
            if not isinstance(v, np.ndarray):
                self[k] = np.asarray(v)

    @property
    def count(self) -> int:
        for v in self.values():
            return int(v.shape[0])
        return 0

    def __len__(self) -> int:  # len(batch) == timesteps, not columns
        return self.count

    def copy(self) -> "SampleBatch":
        return SampleBatch({k: v.copy() for k, v in self.items()})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})

    def shuffle(self, rng: np.random.Generator | None = None) -> "SampleBatch":
        rng = rng or np.random.default_rng()
        perm = rng.permutation(self.count)
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def minibatches(self, minibatch_size: int,
                    drop_last: bool = True) -> Iterator["SampleBatch"]:
        n = self.count
        end = n - (n % minibatch_size) if drop_last else n
        for i in range(0, end, minibatch_size):
            yield self.slice(i, min(i + minibatch_size, n))

    def split_by_episode(self) -> List["SampleBatch"]:
        if EPS_ID not in self:
            return [self]
        ids = self[EPS_ID]
        # Episode boundaries = positions where eps_id changes.
        cuts = np.flatnonzero(ids[1:] != ids[:-1]) + 1
        bounds = [0, *cuts.tolist(), len(ids)]
        return [self.slice(a, b) for a, b in zip(bounds[:-1], bounds[1:])]

    @staticmethod
    def concat_samples(batches: Sequence["SampleBatch"]) -> "SampleBatch":
        batches = [b for b in batches if b.count > 0]
        if not batches:
            return SampleBatch()
        keys = set(batches[0])
        for b in batches[1:]:
            keys &= set(b)
        return SampleBatch(
            {k: np.concatenate([b[k] for b in batches]) for k in keys})

    def as_dict(self) -> Dict[str, np.ndarray]:
        return dict(self)

    def size_bytes(self) -> int:
        return sum(v.nbytes for v in self.values())

    def __repr__(self) -> str:
        cols = {k: tuple(v.shape) for k, v in self.items()}
        return f"SampleBatch({self.count}: {cols})"


def concat_samples(batches: Sequence[SampleBatch]) -> SampleBatch:
    return SampleBatch.concat_samples(batches)


class MultiAgentBatch:
    """Per-policy batches from one multi-agent sampling round (reference:
    ``rllib/policy/sample_batch.py::MultiAgentBatch``)."""

    def __init__(self, policy_batches: Dict[str, SampleBatch],
                 env_steps: int):
        self.policy_batches = dict(policy_batches)
        self._env_steps = int(env_steps)

    def env_steps(self) -> int:
        return self._env_steps

    @property
    def count(self) -> int:
        return self._env_steps

    def agent_steps(self) -> int:
        return sum(b.count for b in self.policy_batches.values())

    @staticmethod
    def concat_samples(batches: Sequence["MultiAgentBatch"]) -> "MultiAgentBatch":
        per_policy: Dict[str, List[SampleBatch]] = {}
        steps = 0
        for b in batches:
            steps += b.env_steps()
            for pid, sb in b.policy_batches.items():
                per_policy.setdefault(pid, []).append(sb)
        return MultiAgentBatch(
            {pid: SampleBatch.concat_samples(v)
             for pid, v in per_policy.items()}, steps)

    def __repr__(self) -> str:
        return (f"MultiAgentBatch(env_steps={self._env_steps}, "
                f"{ {p: b.count for p, b in self.policy_batches.items()} })")
