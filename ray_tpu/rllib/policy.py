"""Policy: network + action distribution + (algorithm-supplied) loss.

Reference: ``rllib/policy/policy.py`` / ``torch_policy.py`` (SURVEY.md §2.5)
— ``compute_actions`` drives sampling, ``learn_on_batch`` drives training,
weights move between learner and rollout workers as flat numpy dicts.
Rebuilt so ``compute_actions`` is one jitted XLA call per env-step batch and
all learner math lives in algorithm-owned jitted update fns.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib import models
from ray_tpu.rllib.sample_batch import (
    ACTION_DIST_INPUTS, ACTION_LOGP, REWARDS, SampleBatch, TERMINATEDS,
    VF_PREDS, ADVANTAGES, VALUE_TARGETS)


class Policy:
    """Actor-critic policy over a flat observation space."""

    def __init__(self, observation_space, action_space,
                 config: Optional[dict] = None):
        config = config or {}
        self.observation_space = observation_space
        self.action_space = action_space
        self.config = config
        self.dist_class = models.get_dist_class(action_space)
        self.model_config = models.make_model_config(
            observation_space, action_space, config)
        seed = config.get("seed", 0)
        # catalog: MLP towers for flat obs, shared Nature-CNN torso +
        # linear heads for rank-3 (pixel) obs
        self.params, self._apply = models.make_actor_critic(
            jax.random.key(seed), self.model_config)
        self._key = jax.random.key(seed + 1)
        dist = self.dist_class
        apply = self._apply

        @jax.jit
        def _act(params, obs, key):
            inputs, values = apply(params, obs)
            actions = dist.sample(inputs, key)
            logp = dist.logp(inputs, actions)
            return actions, logp, inputs, values

        @jax.jit
        def _act_det(params, obs):
            inputs, values = apply(params, obs)
            return dist.deterministic(inputs), inputs, values

        self._act, self._act_det = _act, _act_det

    def apply_fn(self, params, obs):
        """(dist_inputs, values) — used by algorithm loss fns."""
        return self._apply(params, obs)

    def compute_actions(self, obs: np.ndarray, explore: bool = True
                        ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        obs = jnp.asarray(obs, jnp.float32)
        if explore:
            self._key, sub = jax.random.split(self._key)
            actions, logp, inputs, values = self._act(self.params, obs, sub)
            extras = {ACTION_LOGP: np.asarray(logp),
                      ACTION_DIST_INPUTS: np.asarray(inputs),
                      VF_PREDS: np.asarray(values)}
        else:
            actions, inputs, values = self._act_det(self.params, obs)
            extras = {ACTION_DIST_INPUTS: np.asarray(inputs),
                      VF_PREDS: np.asarray(values)}
        return np.asarray(actions), extras

    def compute_single_action(self, obs: np.ndarray, explore: bool = True):
        a, extras = self.compute_actions(obs[None], explore)
        return a[0], {k: v[0] for k, v in extras.items()}

    def value(self, obs: np.ndarray) -> np.ndarray:
        _, _, values = self._act_det(self.params, jnp.asarray(obs,
                                                             jnp.float32))
        return np.asarray(values)

    def get_weights(self) -> Dict[str, Any]:
        return models.pull_params(self.params)

    def set_weights(self, weights: Dict[str, Any]) -> None:
        self.params = jax.tree_util.tree_map(jnp.asarray, weights)


def compute_gae(batch: SampleBatch, last_value: float, gamma: float,
                lam: float) -> SampleBatch:
    """GAE(λ) advantages + value targets for one episode fragment.

    Reference: ``rllib/evaluation/postprocessing.py::compute_advantages``.
    Runs in numpy on the rollout worker (tiny, latency-bound — not MXU work).
    ``last_value`` bootstraps truncated fragments; 0 for terminated episodes.
    """
    rewards = batch[REWARDS]
    vf = batch[VF_PREDS]
    terminated = bool(batch[TERMINATEDS][-1]) if len(batch) else False
    bootstrap = 0.0 if terminated else float(last_value)
    vf_next = np.append(vf[1:], bootstrap).astype(np.float32)
    deltas = rewards + gamma * vf_next - vf
    adv = np.zeros_like(rewards)
    acc = 0.0
    for t in range(len(rewards) - 1, -1, -1):
        acc = deltas[t] + gamma * lam * acc
        adv[t] = acc
    batch[ADVANTAGES] = adv.astype(np.float32)
    batch[VALUE_TARGETS] = (adv + vf).astype(np.float32)
    return batch
