"""Worker-side elastic train loop: quiesce → re-mesh → re-shard → resume.

One ``TrainWorkerActor.apply`` call runs this loop for the actor's whole
life across every mesh generation.  Per generation the worker:

1. waits for a plan (``gen``, rank-ordered member list, coordinator
   address) in the ``elastic`` KV namespace;
2. joins the ``jax.distributed`` domain (``parallel/multihost.py``) and
   builds the user program over the generation's global device set
   (``parallel/mesh.py`` machinery lives inside ``spec.build``);
3. restores state — survivors re-shard their IN-PROCESS gathered host
   state onto the new mesh via ``prog.restore_state`` (``put_global``
   semantics); fresh processes (a rejoining slice, or a restart) pull
   the last gathered checkpoint from the KV instead;
4. steps until done or signalled.  The control signal is read from the
   KV by rank 0 ONLY and broadcast in-band to every rank
   (``broadcast_one_to_all``) so all ranks take the same branch at the
   same step — a rank-divergent stop would strand peers inside a
   collective;
5. on quiesce: gathers state to host on every rank, rank 0 publishes it,
   then EVERY member of the old domain — including the ranks about to be
   preempted — leaves via a clean ``jax.distributed.shutdown()`` (the
   coordinated leave is exactly what the ``node_draining`` advance
   warning buys: an unwarned SIGKILL makes XLA's coordination service
   terminate the survivors, which is the restart fallback), clears the
   cached backends, and acks.  Survivors loop back to (1); drained
   members return.

The surviving processes NEVER restart: re-mesh costs one quiesce +
re-init + host→device re-shard, not an actor cold start.
"""

from __future__ import annotations

import os
import pickle
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private import rtlog

logger = rtlog.get("elastic")

KV_NAMESPACE = "elastic"

# control signals broadcast from rank 0 (0 = keep stepping)
_SIG_STOP = -1


@dataclass
class ElasticSpec:
    """What the elastic workers run.

    ``build()`` executes on every worker AFTER the generation's
    ``jax.distributed`` domain is up, and returns a program object with
    four methods::

        init_state() -> state                  # fresh start (gen 0)
        restore_state(host_state) -> state     # host pytree -> new mesh
        gather_state(state) -> host_state      # full host copy, every rank
        step(state, i) -> (state, metrics)     # one train step

    ``gather_state`` must return the SAME global value on every rank
    (the ``multihost.gather_to_host`` contract) — it is the gathered
    checkpoint a re-mesh re-shards from.  ``gather_every`` is the
    checkpoint cadence: steps since the last gather are recomputed after
    an unwarned loss (never after a warned re-mesh, which always gathers
    at the quiesce boundary).
    """

    build: Callable[[], Any]
    total_steps: int
    gather_every: int = 1
    local_device_count: Optional[int] = None
    cpu_collectives: str = "gloo"
    init_timeout_s: float = 120.0
    report_metrics: bool = True


# --------------------------------------------------------------------- KV
class ElasticKv:
    """The coordination keys one elastic group shares (namespace
    ``elastic``, prefix ``<group>/``): plan, quiesce intent, acks,
    gathered state, per-step reports, stop flag."""

    def __init__(self, group: str):
        self.group = group
        # newest object-plane state ref published from THIS process
        # (rank 0 keeps the blob alive until the next gather replaces
        # it; the manager holds its own borrow via peek_state_record).
        # _maybe_stale_ref: a "stateref" record may exist in the KV
        # (True at start — a restarted publisher cannot know); cleared
        # after the first inline-publish delete so the gather-every-
        # step hot path pays ONE delete RPC, not one per step
        self._state_ref: Optional[Any] = None
        self._maybe_stale_ref = True

    # -- raw ops (work from driver and worker processes alike)
    def _put(self, key: str, value: bytes) -> None:
        from ray_tpu.experimental import internal_kv as kv
        kv._internal_kv_put(f"{self.group}/{key}", value,
                            namespace=KV_NAMESPACE)

    def _get(self, key: str) -> Optional[bytes]:
        from ray_tpu.experimental import internal_kv as kv
        return kv._internal_kv_get(f"{self.group}/{key}",
                                   namespace=KV_NAMESPACE)

    def _del(self, key: str) -> None:
        from ray_tpu.experimental import internal_kv as kv
        kv._internal_kv_del(f"{self.group}/{key}", namespace=KV_NAMESPACE)

    def _list(self, prefix: str) -> List[str]:
        from ray_tpu.experimental import internal_kv as kv
        return kv._internal_kv_list(f"{self.group}/{prefix}",
                                    namespace=KV_NAMESPACE)

    # -- plan / quiesce / ack
    def put_plan(self, plan: dict) -> None:
        self._put("plan", pickle.dumps(plan, protocol=5))

    def get_plan(self) -> Optional[dict]:
        blob = self._get("plan")
        return pickle.loads(blob) if blob else None

    def put_quiesce(self, gen: int) -> None:
        self._put("quiesce", pickle.dumps({"gen": gen}))

    def clear_quiesce(self) -> None:
        """Retract a quiesce intent (a failed transition must not leave
        the stale key to ambush workers that haven't seen it yet)."""
        self._del("quiesce")

    def peek_quiesce(self) -> Optional[dict]:
        blob = self._get("quiesce")
        return pickle.loads(blob) if blob else None

    def ack(self, gen: int, worker_id: str) -> None:
        self._put(f"ack/{gen}/{worker_id}", b"1")

    def acked(self, gen: int) -> List[str]:
        prefix = f"{self.group}/ack/{gen}/"
        return [k[len(prefix):] for k in self._list(f"ack/{gen}/")]

    def put_stop(self) -> None:
        self._put("stop", b"1")

    def stopped(self) -> bool:
        return self._get("stop") is not None

    # -- gathered state (the checkpoint a re-mesh re-shards from).
    # Small states ride the KV inline (head-durable, one hop, crash-
    # safe); states above ``elastic_state_inline_max_bytes`` are
    # published to the object plane and pulled PEER-TO-PEER over the
    # §4e streaming data plane (range-striped bulk frames) — a multi-GB
    # gathered state never transits the head.  The KV then holds only a
    # small record with the ObjectRef; the publisher keeps the newest
    # ref alive in-process and the manager adopts a borrow
    # (``peek_state_record``) so the blob outlives the publishing
    # worker across restarts.  The durability trade (an unwarned loss
    # of BOTH the publisher's node and the manager loses the blob where
    # the inline path would have survived) is documented in §4n.
    def put_state(self, host_state: Any, step: int, gen: int) -> None:
        import cloudpickle
        from ray_tpu._private.config import GLOBAL_CONFIG
        blob = cloudpickle.dumps(
            {"step": step, "gen": gen, "state": host_state}, protocol=5)
        if len(blob) <= GLOBAL_CONFIG.elastic_state_inline_max_bytes:
            self._put("state", blob)
            if self._maybe_stale_ref:
                self._del("stateref")    # no object to adopt anymore
                self._maybe_stale_ref = False
            self._state_ref = None       # inline copy supersedes the ref
            return
        import ray_tpu
        ref = ray_tpu.put(blob)
        rec = pickle.dumps({"step": step, "gen": gen, "ref": ref},
                           protocol=5)
        self._put("state", rec)
        # duplicate SMALL record under its own key: the manager's
        # adoption poll reads only this (absent for inline states), so
        # it never ships a multi-MB inline checkpoint over the KV just
        # to discover there is nothing to adopt
        self._put("stateref", rec)
        self._maybe_stale_ref = True
        # hold the NEWEST ref until the next publish replaces it — a
        # ray_tpu.put refcount follows the local handle, and the KV
        # stores bytes, not a borrow
        self._state_ref = ref

    def peek_state_record(self) -> Optional[dict]:
        """The object-plane state record WITHOUT resolving the blob
        (None when the newest checkpoint is inline) — unpickling
        registers a borrow on the embedded ref, which is exactly why
        the manager calls this: holding the returned record keeps an
        object-plane checkpoint alive across worker restarts."""
        blob = self._get("stateref")
        return pickle.loads(blob) if blob else None

    def get_state(self) -> Optional[dict]:
        """The newest gathered checkpoint, or None.  An object-plane
        record whose blob is gone (owner node + every borrow lost —
        the documented durability trade) degrades to None with a loud
        log: the group restarts from scratch instead of wedging on an
        unfetchable ref."""
        blob = self._get("state")
        if blob is None:
            return None
        rec = pickle.loads(blob)
        if "ref" not in rec:
            return rec
        import ray_tpu
        try:
            data = ray_tpu.get(rec["ref"])   # streamed peer pull (§4e)
        except Exception:  # noqa: BLE001 - blob lost with its holders
            logger.error(
                "elastic[%s] gathered checkpoint (step %s) lost from "
                "the object plane — its holder died before the manager "
                "adopted a borrow; restarting from scratch",
                self.group, rec.get("step"), exc_info=True)
            return None
        return pickle.loads(data)

    # -- per-step reports (rank 0): the manager polls + deletes
    def report(self, step: int, gen: int, metrics: Dict[str, Any]) -> None:
        self._put(f"r/{step}", pickle.dumps(
            {"step": step, "gen": gen, "ts": time.time(),
             "metrics": metrics}))

    def poll_reports(self) -> List[dict]:
        prefix = f"{self.group}/r/"
        out = []
        for key in sorted(self._list("r/")):
            blob = self._get(key[len(f"{self.group}/"):])
            if blob is None:
                continue
            out.append(pickle.loads(blob))
            self._del(key[len(f"{self.group}/"):])
        return sorted(out, key=lambda r: r["step"])

    def clear(self) -> None:
        for key in self._list(""):
            self._del(key[len(f"{self.group}/"):])


# ----------------------------------------------------------------- helpers
def _clear_jax_backends() -> None:
    """Forget the cached XLA clients so the next ``jax.distributed
    .initialize`` is legal in this same process (the re-mesh enabling
    trick; jax >= 0.4.36 moved it under jax.extend)."""
    try:
        from jax.extend.backend import clear_backends
    except ImportError:  # pragma: no cover - older jax spelling
        from jax import clear_backends  # type: ignore[attr-defined]
    clear_backends()


def _broadcast_signal(sig: int, world: int) -> int:
    """All ranks agree on rank 0's control signal (in-band broadcast —
    a KV read can race differently per rank, and a divergent stop
    strands peers inside the next step's collectives)."""
    if world <= 1:
        return sig
    import numpy as np
    from jax.experimental import multihost_utils
    return int(multihost_utils.broadcast_one_to_all(np.int64(sig)))


# -------------------------------------------------------------- the loop
def elastic_worker_loop(group: str, worker_id: str, spec_blob: bytes,
                        min_gen: int = 0) -> dict:
    """Entry point run via ``TrainWorkerActor.apply`` — one call spans
    every generation this worker participates in.  ``min_gen`` is the
    first plan generation this worker may act on (0 for founders; the
    join/restart generation for workers spawned later, so they ignore
    the stale pre-join plan).  Returns the worker's participation
    record (the no-cold-start evidence the tests assert): pid, and
    per-generation {gen, rank, world, start/end step, cold}."""
    import cloudpickle

    spec: ElasticSpec = cloudpickle.loads(spec_blob)
    kv = ElasticKv(group)
    from ray_tpu.parallel import multihost

    pid = os.getpid()
    generations: List[dict] = []
    host_state: Optional[Any] = None   # survivor's in-RAM gathered state
    host_step = 0

    while True:
        plan = _wait_for_plan(kv, worker_id, min_gen, spec.init_timeout_s)
        if plan is None:           # excluded from the current plan
            return _result(worker_id, pid, generations, drained=True)
        gen, members = plan["gen"], plan["members"]
        rank, world = members.index(worker_id), len(members)
        if world > 1:
            multihost.initialize(
                plan["coordinator"], world, rank,
                local_device_count=spec.local_device_count,
                cpu_collectives=spec.cpu_collectives,
                init_timeout_s=spec.init_timeout_s)
        prog = spec.build()
        cold = not generations     # first generation in THIS process
        if host_state is None:
            blob = kv.get_state()
            if blob is not None:
                host_state, host_step = blob["state"], blob["step"]
        if host_state is None:
            state, step = prog.init_state(), 0
        else:
            state, step = prog.restore_state(host_state), host_step
        grec = {"gen": gen, "rank": rank, "world": world, "pid": pid,
                "start_step": step, "end_step": step, "cold": cold}
        generations.append(grec)
        logger.info("elastic[%s] %s gen=%d rank=%d/%d from step %d "
                    "(%s)", group, worker_id[:8], gen, rank, world, step,
                    "cold" if cold else "re-meshed")

        # per-rank step-time histogram: the §4k straggler detector reads
        # rtpu_train_step_seconds, so an elastic run is node-tagged and
        # autopilot-drainable exactly like a JaxTrainer session run.
        # The group tag cohorts the comparison — this job's ranks are
        # only ever measured against THIS job's median, never against
        # an unrelated (faster or slower) run sharing the cluster
        step_hist = None
        if spec.report_metrics:
            from ray_tpu._private.config import GLOBAL_CONFIG
            if GLOBAL_CONFIG.metrics_enabled:
                from ray_tpu.util import metrics_catalog as mcat
                step_hist = mcat.get("rtpu_train_step_seconds")

        target_gen = None
        while step < spec.total_steps:
            t_step = time.monotonic()
            state, metrics = prog.step(state, step)
            if step_hist is not None:
                step_hist.observe(time.monotonic() - t_step,
                                  tags={"rank": str(rank),
                                        "group": group})
            step += 1
            if step % spec.gather_every == 0 or step == spec.total_steps:
                host_state, host_step = prog.gather_state(state), step
                if rank == 0:
                    # the KV copy is what an UNWARNED loss restarts
                    # from — publish at the gather cadence, not just at
                    # quiesce, or a SIGKILL rolls back to the last
                    # planned transition instead of the last checkpoint
                    kv.put_state(host_state, host_step, gen)
            if rank == 0 and spec.report_metrics:
                kv.report(step - 1, gen, _plain_metrics(metrics))
            sig = 0
            if rank == 0:
                q = kv.peek_quiesce()
                if q and q["gen"] > gen:
                    sig = q["gen"]
                elif kv.stopped():
                    sig = _SIG_STOP
            sig = _broadcast_signal(sig, world)
            if sig:
                target_gen = sig
                break
        grec["end_step"] = step

        # quiesce: the state published here IS the checkpoint the next
        # generation re-shards from — gather at the boundary if the
        # cadence left it stale
        if host_step < step:
            host_state, host_step = prog.gather_state(state), step
        if rank == 0 and target_gen != _SIG_STOP:
            kv.put_state(host_state, host_step, gen)
        state = None   # drop device refs before the domain goes down
        if world > 1:
            multihost.shutdown()
        _clear_jax_backends()
        if target_gen is None or target_gen == _SIG_STOP:
            return _result(worker_id, pid, generations,
                           drained=target_gen == _SIG_STOP,
                           completed=target_gen is None)
        # clean leave done: tell the manager this member is out of the
        # old domain (it publishes the new plan once everyone acked)
        kv.ack(target_gen, worker_id)
        min_gen = target_gen


def _plain_metrics(metrics: Any) -> Dict[str, Any]:
    out = {}
    for k, v in (metrics or {}).items():
        try:
            out[k] = float(v)
        except (TypeError, ValueError):
            out[k] = repr(v)
    return out


def _wait_for_plan(kv: ElasticKv, worker_id: str, min_gen: int,
                   timeout_s: float) -> Optional[dict]:
    """Block until a plan with gen >= min_gen exists.  Returns None when
    that plan excludes this worker (drained), or raises on timeout (the
    manager sees the actor error and falls back to a restart)."""
    deadline = time.monotonic() + max(timeout_s, 1.0)
    while time.monotonic() < deadline:
        plan = kv.get_plan()
        if plan is not None and plan["gen"] >= min_gen:
            if worker_id in plan["members"]:
                return plan
            return None        # explicitly planned out -> drained
        time.sleep(0.05)
    raise TimeoutError(
        f"elastic worker {worker_id[:8]} saw no plan >= gen {min_gen} "
        f"in {timeout_s:.0f}s")


def _result(worker_id: str, pid: int, generations: List[dict], *,
            drained: bool = False, completed: bool = False) -> dict:
    return {"worker_id": worker_id, "pid": pid,
            "generations": generations, "drained": drained,
            "completed": completed}
