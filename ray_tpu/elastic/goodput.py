"""Goodput accounting: useful train steps per wall-second.

The chaos suite's contract upgrade (DESIGN.md §4j): surviving a
preemption is not enough — the metric is how many FIRST-TIME steps the
job completes per wall-second across the disruption.  A step re-run
after a restart-from-checkpoint (the work since the last gathered state
is recomputed) counts as waste, not progress; an elastic re-mesh avoids
the recompute entirely and pays only the quiesce → re-init pause.

The tracker is clock-agnostic (pass ``ts``) so the fleet simulator can
drive it on simulated time and the live manager on wall time.
"""

from __future__ import annotations

import time
from typing import Dict, Optional


class GoodputTracker:
    def __init__(self, t0: Optional[float] = None):
        self._t0 = time.monotonic() if t0 is None else t0
        self._last_ts = self._t0
        self._max_step = -1        # highest step index ever completed
        self.useful_steps = 0      # first-time completions
        self.wasted_steps = 0      # re-runs after a restart
        self.pauses = 0            # recovery pauses recorded
        self.paused_s = 0.0        # time attributed to recovery

    def record_step(self, step: int, ts: Optional[float] = None) -> bool:
        """Record one completed step; returns True when it was useful
        (first-time) progress, False for a post-restart re-run."""
        self._last_ts = time.monotonic() if ts is None else ts
        if step > self._max_step:
            self._max_step = step
            self.useful_steps += 1
            return True
        self.wasted_steps += 1
        return False

    def add_progress(self, useful: float = 0.0, wasted: float = 0.0,
                     ts: Optional[float] = None) -> None:
        """Bulk accounting for the fleet simulator: fractional step
        credit accrued over a tick (useful = first-time progress,
        wasted = recompute of checkpoint-lost work)."""
        self._last_ts = time.monotonic() if ts is None else ts
        self.useful_steps += useful
        self.wasted_steps += wasted

    def record_pause(self, seconds: float) -> None:
        """Attribute recovery downtime (quiesce->resume, or cold-start)."""
        self.pauses += 1
        self.paused_s += max(seconds, 0.0)

    def wall_s(self, now: Optional[float] = None) -> float:
        now = self._last_ts if now is None else now
        return max(now - self._t0, 1e-9)

    def goodput(self, now: Optional[float] = None) -> float:
        """Useful steps per wall-second, disruptions included."""
        return self.useful_steps / self.wall_s(now)

    def summary(self, now: Optional[float] = None) -> Dict[str, float]:
        return {
            "useful_steps": self.useful_steps,
            "wasted_steps": self.wasted_steps,
            "wall_s": round(self.wall_s(now), 6),
            "goodput_steps_per_s": round(self.goodput(now), 6),
            "pauses": self.pauses,
            "paused_s": round(self.paused_s, 6),
        }
