"""Fleet autopilot: the observability → actuation reflex arc (§4n).

PR 10 gave the head detectors (straggler skew, SLO burn) that *emit*
node-tagged fleet events; PR 9 gave it an elasticity manager and an
autoscaler that *react* to provider signals.  This module closes the
loop: a head-side supervision pass (ticked from the GCS monitor thread,
config-gated ``autopilot_enabled``) that turns fleet events and TSDB
history into **bounded** remediation actions:

- **straggler → drain**: a straggler fleet event (node-tagged by the
  detector) drains the offending host — the elasticity manager observes
  the ``node_draining`` event and quiesces → re-meshes the surviving
  domain without a restart; a replacement is pre-warmed through the
  attached autoscaler.  The node is returned to the pool (un-drained)
  once the signal clears; a relapse drains it permanently.
- **drain warning → pre-warm**: any ``node_draining`` warning (provider
  preemption included) pre-warms a replacement *during* the warning
  window via :meth:`StandardAutoscaler.prewarm_for_drain`; the
  replacement is reserved in ``_net_pending_capacity`` so the incoming
  loss is credited, never double-launched.
- **history → forecast**: a seasonal-naive forecast over the TSDB's 48h
  demand rungs feeds the autoscaler a lead-time demand floor
  (:meth:`StandardAutoscaler.set_forecast_demand`) so it scales ahead
  of the diurnal curve instead of behind it.
- **standby supervision**: keep one warm GCS standby attached (launch
  ``python -m ray_tpu._private.replication`` when none is, re-launch on
  standby death) and emit ``unprotected_head`` while the ledger is
  unreplicated.

Every reflex is **rate-limited and hysteresis-guarded** so a noisy
detector can never cause an actuation storm: at most
``max_drains_per_window`` drains per ``drain_window_s`` cluster-wide, a
per-node relapse window (``node_cooldown_s``: straggling again soon
after an undrain is drained permanently; later starts fresh), and
explicit vetoes (a node that is the sole host of a placement group,
the sole provider of a resource kind, or the last schedulable node, is
never drained).  Every action — applied, skipped, or errored — is
recorded in a bounded history, emitted as an ``autopilot_action`` fleet
event, and counted in ``rtpu_autopilot_actions_total{kind,outcome}``,
so the loop itself is observable and chaos-testable.

The policy core (:class:`Autopilot`) is clock-injectable and actuates
through a narrow duck-typed :class:`Actuator`; :class:`GcsActuator`
binds it to the live head, and the fleet simulator's ``SimActuator``
(``elastic/fleet_sim.py``) drives the identical policy over seeded
100-node traces — the storm bounds are asserted against the same code
that runs in production.

What the autopilot will NEVER do without an operator: terminate a
node, delete data, scale the fleet *down* (the forecast floor only adds
capacity; reclaim stays the autoscaler's idle-timeout policy), or
touch a node twice inside its cooldown.

Locking: one no-block leaf lock (``AUTOPILOT_LOCK_DAG`` in
lock_watchdog.py) guards everything ``autopilot_status`` readers see —
the bounded action history, the counters, and the two stats fields
(``_forecast_slots``, ``_unprotected_since``).  All other reflex state
(cooldowns, rate window, per-node ledger) is single-writer — only the
tick thread touches it — and actuator calls run with no autopilot lock
held.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ray_tpu._private import rtlog

logger = rtlog.get("autopilot")

# action kinds / outcomes (the rtpu_autopilot_actions_total tag values)
KIND_DRAIN = "drain"
KIND_UNDRAIN = "undrain"
KIND_PREWARM = "prewarm"
KIND_FORECAST = "forecast"
KIND_STANDBY = "standby_launch"
OUT_APPLIED = "applied"
OUT_SKIPPED = "skipped"
OUT_ERROR = "error"

_ACTION_HISTORY = 256          # bounded action ring (status surface)


@dataclass
class AutopilotConfig:
    """Reflex policy knobs — see the ``autopilot_*`` flags in
    ``_private/config.py`` for the operator-facing documentation."""

    interval_s: float = 1.0
    drain_window_s: float = 300.0
    max_drains_per_window: int = 1
    node_cooldown_s: float = 600.0
    undrain_after_s: float = 120.0
    prewarm: bool = True
    forecast: bool = True
    forecast_interval_s: float = 30.0
    forecast_horizon_s: float = 120.0
    forecast_period_s: float = 86400.0
    standby: bool = False
    standby_backoff_s: float = 5.0

    @classmethod
    def from_global_config(cls) -> "AutopilotConfig":
        from ray_tpu._private.config import GLOBAL_CONFIG as g
        return cls(
            interval_s=g.autopilot_interval_s,
            drain_window_s=g.autopilot_drain_window_s,
            max_drains_per_window=g.autopilot_max_drains_per_window,
            node_cooldown_s=g.autopilot_node_cooldown_s,
            undrain_after_s=g.autopilot_undrain_after_s,
            prewarm=g.autopilot_prewarm,
            forecast=g.autopilot_forecast,
            forecast_interval_s=g.autopilot_forecast_interval_s,
            forecast_horizon_s=g.autopilot_forecast_horizon_s,
            forecast_period_s=g.autopilot_forecast_period_s,
            standby=g.autopilot_standby and g.gcs_wal,
            standby_backoff_s=g.autopilot_standby_backoff_s)


class Actuator:
    """What the autopilot may do to the world — the narrow, duck-typed
    surface both the live head (:class:`GcsActuator`) and the fleet
    simulator implement.  Methods returning ``bool`` report whether the
    action took effect; ``False`` records a ``skipped`` outcome."""

    def drain(self, node_id: str, reason: str) -> bool:
        raise NotImplementedError

    def undrain(self, node_id: str) -> bool:
        raise NotImplementedError

    def veto(self, node_id: str) -> Optional[str]:
        """Reason this node must NOT be drained, or None."""
        return None

    def prewarm(self, node_id: str) -> bool:
        return False

    def demand_now(self) -> float:
        return 0.0

    def demand_forecast(self) -> Optional[float]:
        return None

    def forecast_demand(self, slots: int) -> bool:
        return False

    def emit(self, kind: str, node_id: Optional[str] = None,
             **fields) -> None:
        pass

    def incident(self, node_id: str, reason: str) -> Optional[str]:
        """Post-mortem bundle id for this node's episode (§4o) — the
        head captures one (or returns the id the detector's capture
        already minted inside the dedup window); None = unsupported."""
        return None

    # -- standby supervision (head-only; None = unsupported here)
    def standby_count(self) -> Optional[int]:
        return None

    def standby_alive(self) -> bool:
        return False

    def launch_standby(self) -> bool:
        return False

    def shutdown(self) -> None:
        pass


class Autopilot:
    """The reflex engine.  Feed fleet events with :meth:`observe`, run
    reflex passes with :meth:`tick` (the GCS monitor loop / the sim's
    tick loop); read the bounded action history with :meth:`actions`.

    Single-writer: ``observe``/``tick`` must be called from ONE thread
    (the GCS monitor thread live; the sim loop in the harness).  Only
    the action history crosses threads (status RPC) and is guarded by
    the one leaf lock."""

    def __init__(self, config: AutopilotConfig, actuator: Actuator,
                 clock=time.monotonic, metrics: bool = True):
        self.config = config
        self.actuator = actuator
        self._clock = clock
        self._metrics = metrics
        self._lock = threading.Lock()   # no-block leaf (AUTOPILOT_LOCK_DAG)
        self._actions: deque = deque(maxlen=_ACTION_HISTORY)
        # guarded by: _lock
        self._counts: Dict[str, int] = {}            # guarded by: _lock
        # -- tick-thread-only reflex state (single owner, never locked):
        self._pending: List[dict] = []           # observed, unprocessed
        self._drain_times: deque = deque()       # applied drains (rate win)
        self._nodes: Dict[str, dict] = {}        # per-node ledger
        self._prewarmed: set = set()
        self._skip_memo: Dict[tuple, float] = {}
        # the two tick-written fields stats() also reports cross-thread
        # ride the same leaf lock as the history (scalar writes, but
        # the single-writer contract stays lint-enforceable)
        self._forecast_slots = -1                # guarded by: _lock
        self._last_forecast = float("-inf")
        self._unprotected: Optional[float] = None  # guarded by: _lock
        self._last_unprotected_emit = float("-inf")
        self._last_standby_launch: Optional[float] = None

    # --------------------------------------------------------------- intake
    def observe(self, event: dict) -> None:
        """Feed one fleet event (straggler / node_draining /
        node_removed); processed on the next :meth:`tick`."""
        kind = event.get("kind")
        if kind in ("straggler", "node_draining", "node_removed"):
            self._pending.append(dict(event))

    # ----------------------------------------------------------------- tick
    def tick(self, now: Optional[float] = None) -> List[dict]:
        """One reflex pass; returns the actions recorded this pass."""
        now = self._clock() if now is None else now
        taken: List[dict] = []
        pending, self._pending = self._pending, []
        for ev in pending:
            kind = ev.get("kind")
            if kind == "straggler":
                taken += self._reflex_straggler(ev, now)
            elif kind == "node_draining":
                taken += self._reflex_prewarm(ev, now)
            elif kind == "node_removed":
                nid = ev.get("node_id")
                self._nodes.pop(nid, None)
                self._prewarmed.discard(nid)
        taken += self._reflex_undrain(now)
        # the forecast is a slow diurnal signal: two TSDB ladder scans
        # plus a demand scan per evaluation belong on their own cadence,
        # not on every monitor tick
        if self.config.forecast and \
                now - self._last_forecast >= self.config.forecast_interval_s:
            self._last_forecast = now
            taken += self._reflex_forecast(now)
        if self.config.standby:
            taken += self._reflex_standby(now)
        return taken

    # ----------------------------------------------------- reflex: straggler
    def _node(self, node_id: str) -> dict:
        return self._nodes.setdefault(node_id, {
            "drained_at": None, "undrained_at": None,
            "drains": 0, "permanent": False})

    def _drains_in_window(self, now: float) -> int:
        cutoff = now - self.config.drain_window_s
        while self._drain_times and self._drain_times[0] <= cutoff:
            self._drain_times.popleft()
        return len(self._drain_times)

    def _reflex_straggler(self, ev: dict, now: float) -> List[dict]:
        node_id = ev.get("node_id")
        if not node_id:
            return self._skip(KIND_DRAIN, None, "untagged", now)
        st = self._node(node_id)
        if st["permanent"] or st["drained_at"] is not None:
            # a refire against a node we already drained proves the
            # signal has NOT cleared: refresh the sick-timer so the
            # undrain quiet period restarts (the flag contract —
            # "returns after this long WITHOUT a fresh signal")
            if st["drained_at"] is not None:
                st["drained_at"] = now
            return self._skip(KIND_DRAIN, node_id, "already-draining", now)
        if self._drains_in_window(now) >= self.config.max_drains_per_window:
            return self._skip(KIND_DRAIN, node_id, "rate-limited", now)
        veto = self.actuator.veto(node_id)
        if veto:
            return self._skip(KIND_DRAIN, node_id, f"veto:{veto}", now)
        # per-node hysteresis: a straggler signal inside node_cooldown_s
        # of the node's undrain is a RELAPSE — the host is genuinely
        # sick, so it is drained again immediately and permanently
        # (replacement owns it); past the cooldown the node starts
        # fresh and a new drain is an ordinary, recoverable one
        relapse = st["undrained_at"] is not None and \
            now - st["undrained_at"] < self.config.node_cooldown_s
        out: List[dict] = []
        try:
            ok = self.actuator.drain(node_id, "straggler")
        except Exception:  # noqa: BLE001 - an actuator fault is an outcome
            logger.exception("autopilot drain of %s failed", node_id[:8])
            ok = None
        if ok:
            self._drain_times.append(now)
            st["drained_at"] = now
            st["drains"] += 1
            if relapse:
                st["permanent"] = True
            # link the post-mortem bundle: inside the dedup window this
            # returns the id the detector's capture already minted, so
            # the action history points at the evidence without a
            # second bundle ever being written
            try:
                iid = self.actuator.incident(node_id, "straggler")
            except Exception:  # noqa: BLE001 - evidence is best-effort
                iid = None
            out += self._record(KIND_DRAIN, OUT_APPLIED, node_id,
                                "straggler", now,
                                skew=ev.get("skew_ratio"),
                                rank=ev.get("rank"),
                                incident=iid)
            if self.config.prewarm:
                out += self._do_prewarm(node_id, now)
        else:
            outcome = OUT_SKIPPED if ok is False else OUT_ERROR
            out += self._record(KIND_DRAIN, outcome, node_id,
                                "actuator-declined" if ok is False
                                else "actuator-error", now)
        return out

    # ------------------------------------------------------- reflex: prewarm
    def _reflex_prewarm(self, ev: dict, now: float) -> List[dict]:
        node_id = ev.get("node_id")
        if not self.config.prewarm or not node_id:
            return []
        return self._do_prewarm(node_id, now)

    def _do_prewarm(self, node_id: str, now: float) -> List[dict]:
        if node_id in self._prewarmed:
            return []       # one replacement per drain, never a second
        try:
            ok = self.actuator.prewarm(node_id)
        except Exception:  # noqa: BLE001
            logger.exception("autopilot prewarm for %s failed",
                             node_id[:8])
            return self._record(KIND_PREWARM, OUT_ERROR, node_id,
                                "actuator-error", now)
        if ok:
            # only a SUCCESSFUL warm consumes the one-per-drain slot:
            # a decline (e.g. the autoscaler has not attached yet) must
            # stay retryable on the next detector refire
            self._prewarmed.add(node_id)
            return self._record(KIND_PREWARM, OUT_APPLIED, node_id,
                                "drain-warning", now)
        return self._skip(KIND_PREWARM, node_id, "actuator-declined", now)

    # ------------------------------------------------------- reflex: undrain
    def _reflex_undrain(self, now: float) -> List[dict]:
        out: List[dict] = []
        for node_id, st in list(self._nodes.items()):
            if st["drained_at"] is None or st["permanent"]:
                continue
            if now - st["drained_at"] < self.config.undrain_after_s:
                continue
            try:
                ok = self.actuator.undrain(node_id)
            except Exception:  # noqa: BLE001
                logger.exception("autopilot undrain of %s failed",
                                 node_id[:8])
                continue
            if ok:
                # NOT a "last_action" for hysteresis purposes: an
                # undrain must never delay the relapse drain it exists
                # to detect
                st["drained_at"] = None
                st["undrained_at"] = now
                out += self._record(KIND_UNDRAIN, OUT_APPLIED, node_id,
                                    "signal-cleared", now)
            else:
                # the drain is no longer ours to reverse (a provider
                # warning superseded it, or the node is gone): forget
                # the node entirely — it never got its recovery window,
                # so a future straggler there must read as FRESH, not
                # as a relapse-to-permanent
                self._nodes.pop(node_id, None)
                out += self._record(KIND_UNDRAIN, OUT_SKIPPED, node_id,
                                    "not-ours", now)
            self._prewarmed.discard(node_id)
        return out

    # ------------------------------------------------------ reflex: forecast
    def _reflex_forecast(self, now: float) -> List[dict]:
        try:
            pred = self.actuator.demand_forecast()
        except Exception:  # noqa: BLE001 - forecast is advisory
            logger.debug("demand forecast failed", exc_info=True)
            return []
        if pred is None:
            return []
        cur = self.actuator.demand_now()
        slots = max(int(math.ceil(pred - cur)), 0)
        with self._lock:
            unchanged = slots == self._forecast_slots
        if unchanged:
            return []       # hysteresis: hand over only on change
        if self.actuator.forecast_demand(slots):
            with self._lock:
                self._forecast_slots = slots
            return self._record(KIND_FORECAST, OUT_APPLIED, None,
                                f"slots={slots}", now)
        return self._skip(KIND_FORECAST, None, "actuator-declined", now)

    # ------------------------------------------------------- reflex: standby
    def _reflex_standby(self, now: float) -> List[dict]:
        count = self.actuator.standby_count()
        if count is None:
            return []       # no replication hub here
        if count > 0:
            with self._lock:
                self._unprotected = None
            return []
        with self._lock:
            if self._unprotected is None:
                self._unprotected = now
            since = self._unprotected
        # the head is unreplicated: say so (rate-limited), and make it
        # false — launch/relaunch the supervised standby
        if now - self._last_unprotected_emit >= self.config.drain_window_s:
            self._last_unprotected_emit = now
            self.actuator.emit("unprotected_head",
                               since_s=round(now - since, 3))
        if self.actuator.standby_alive():
            return []       # launched; repl_attach still in flight
        last = self._last_standby_launch
        if last is not None and now - last < self.config.standby_backoff_s:
            return []
        self._last_standby_launch = now
        try:
            ok = self.actuator.launch_standby()
        except Exception:  # noqa: BLE001
            logger.exception("standby launch failed")
            return self._record(KIND_STANDBY, OUT_ERROR, None,
                                "launch-error", now)
        return self._record(KIND_STANDBY,
                            OUT_APPLIED if ok else OUT_SKIPPED, None,
                            "unprotected-head", now)

    # ------------------------------------------------------------ recording
    def _skip(self, kind: str, node_id: Optional[str], reason: str,
              now: float) -> List[dict]:
        """Record a skipped action, deduped per (kind, node, reason)
        within the drain window — a detector refiring every tick must
        not flood the history with identical skips."""
        memo = (kind, node_id, reason)
        last = self._skip_memo.get(memo)
        if last is not None and now - last < self.config.drain_window_s:
            return []
        self._skip_memo[memo] = last = now
        if len(self._skip_memo) > 4 * _ACTION_HISTORY:
            cutoff = now - self.config.drain_window_s
            self._skip_memo = {k: t for k, t in self._skip_memo.items()
                               if t >= cutoff}
        return self._record(kind, OUT_SKIPPED, node_id, reason, now)

    def _record(self, kind: str, outcome: str, node_id: Optional[str],
                reason: str, now: float, **extra) -> List[dict]:
        rec = {"ts": now, "kind": kind, "outcome": outcome,
               "node_id": node_id, "reason": reason,
               **{k: v for k, v in extra.items() if v is not None}}
        with self._lock:
            self._actions.append(rec)
            key = f"{kind}/{outcome}"
            self._counts[key] = self._counts.get(key, 0) + 1
        logger.info("autopilot %s %s node=%s (%s)", kind, outcome,
                    (node_id or "-")[:8], reason)
        try:
            self.actuator.emit("autopilot_action", node_id=node_id,
                               action=kind, outcome=outcome, reason=reason)
        except Exception:  # noqa: BLE001 - the feed is best-effort
            logger.debug("autopilot_action emit failed", exc_info=True)
        if self._metrics:
            try:
                from ray_tpu._private.config import GLOBAL_CONFIG
                if GLOBAL_CONFIG.metrics_enabled:
                    from ray_tpu.util import metrics_catalog as mcat
                    mcat.get("rtpu_autopilot_actions_total").inc(
                        tags={"kind": kind, "outcome": outcome})
            except Exception:  # noqa: BLE001 - telemetry best-effort
                pass
        return [rec]

    # --------------------------------------------------------------- status
    def actions(self, limit: int = 50) -> List[dict]:
        with self._lock:
            out = list(self._actions)
        return out[-max(int(limit), 1):]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"counts": dict(self._counts),
                    "forecast_slots": max(self._forecast_slots, 0),
                    "unprotected": self._unprotected is not None}


# ---------------------------------------------------------------- live bind
class GcsActuator(Actuator):
    """Binds the reflex engine to the live head: node phases through the
    GCS ledger, capacity through an (optionally) attached autoscaler,
    forecasts through the head TSDB, standby supervision through a
    subprocess the head owns.  Runs on the GCS monitor thread with no
    GCS lock held; every method takes only the locks it documents."""

    def __init__(self, gcs):
        self.gcs = gcs
        self.autoscaler = None      # attached via AutoscalerLoop
        self._standby_proc = None
        self._closed = False

    # -- drains ride the same internal path as the node_draining RPC,
    # but never claim a node some other authority is already draining
    def drain(self, node_id: str, reason: str) -> bool:
        return self.gcs.drain_node_internal(node_id, deadline_s=0.0,
                                            reason=reason,
                                            only_if_running=True)

    def undrain(self, node_id: str) -> bool:
        # only reverse our OWN drains: a provider warning that arrived
        # (and overwrote the reason) while the node was drained must
        # not be cancelled by the recovery timer
        return self.gcs.undrain_node_internal(node_id,
                                              only_reason="straggler")

    def incident(self, node_id: str, reason: str) -> Optional[str]:
        # runs on the monitor thread like the detector pass, so the
        # head's per-node dedup ledger makes this the SAME bundle the
        # detector captured moments earlier (exactly-once per episode)
        return self.gcs._capture_incident(reason, node_id)

    def veto(self, node_id: str) -> Optional[str]:
        with self.gcs.lock:
            running = [n for n in self.gcs.nodes.values()
                       if n.alive and n.phase == "running"]
            if [n.node_id for n in running] == [node_id]:
                return "last-schedulable-node"
            node = self.gcs.nodes.get(node_id)
            if node is not None:
                # the sole provider of a resource kind (the last TPU
                # host, the only node with a custom accelerator) is
                # never drained: remediation must not take the fleet's
                # only capacity of a kind offline — operator territory
                for kind, total in node.resources_total.items():
                    if total <= 0 or kind.startswith("node:"):
                        continue
                    others = any(
                        n.resources_total.get(kind, 0.0) > 0
                        for n in running if n.node_id != node_id)
                    if not others:
                        return f"sole-resource-host:{kind}"
            for pg in self.gcs.pgs.values():
                hosts = {h for h in pg.assignment if h}
                if hosts == {node_id}:
                    # draining the sole host of a placement group would
                    # strand the whole group — operator territory
                    return "pg-sole-host"
        return None

    def prewarm(self, node_id: str) -> bool:
        if self.autoscaler is None:
            return False
        with self.gcs.lock:
            node = self.gcs.nodes.get(node_id)
            busy = node is not None and bool(node.workers)
            # the autoscaler's provider speaks ITS id namespace —
            # Kubernetes pod names, carried as the ray-pod label (the
            # same dual-keying _node_phases does); fall back to the
            # cluster id for providers whose ids coincide
            provider_id = node_id
            if node is not None:
                provider_id = node.labels.get("ray-pod") or node_id
        if not busy:
            return False        # idle node: a replacement buys nothing
        return self.autoscaler.prewarm_for_drain(provider_id)

    def demand_now(self) -> float:
        """The demand LEVEL (backlog + capacity already serving it) —
        the same quantity the forecast predicts, so the floor is their
        difference.  Forecasting residual backlog alone would
        self-extinguish: once scaling keeps up, yesterday's backlog is
        ~0 and the reflex would oscillate with the seasonal period."""
        d = self.gcs._h_resource_demand({})
        backlog = float(len(d["task_shapes"]) + len(d["pg_bundles"]))
        with self.gcs.lock:
            # exclude the head: the forecast side is built from
            # rtpu_autoscaler_nodes{phase="running"}, which counts
            # provider worker nodes only — now and predicted must be
            # the same unit or the floor is biased by the difference
            running = sum(1 for nid, n in self.gcs.nodes.items()
                          if n.alive and n.phase == "running"
                          and nid != self.gcs.head_node_id)
        return backlog + running

    def demand_forecast(self) -> Optional[float]:
        if self.autoscaler is None or self.gcs._tsdb is None:
            return None
        from ray_tpu._private.config import GLOBAL_CONFIG

        def fc(expr):
            rows = self.gcs._tsdb.forecast(
                expr, GLOBAL_CONFIG.autopilot_forecast_horizon_s,
                period_s=GLOBAL_CONFIG.autopilot_forecast_period_s)
            return sum(r["value"] for r in rows) if rows else None

        backlog = fc("rtpu_autoscaler_demand_backlog")
        running = fc('rtpu_autoscaler_nodes{phase="running"}')
        if backlog is None and running is None:
            return None
        return float(backlog or 0.0) + float(running or 0.0)

    def forecast_demand(self, slots: int) -> bool:
        if self.autoscaler is None:
            return False
        self.autoscaler.set_forecast_demand(slots)
        return True

    def emit(self, kind: str, node_id: Optional[str] = None,
             **fields) -> None:
        self.gcs._fleet_event(kind, node_id, **fields)

    # -- standby supervision (satellite of §4l: successor item b)
    def standby_count(self) -> Optional[int]:
        hub = self.gcs._repl_hub
        return None if hub is None else hub.standby_count()

    def standby_alive(self) -> bool:
        p = self._standby_proc
        return p is not None and p.poll() is None

    def launch_standby(self) -> bool:
        import os
        import subprocess
        import sys
        from ray_tpu._private.config import GLOBAL_CONFIG
        if self._closed or self.gcs._shutdown:
            return False    # a clean shutdown is in progress: no respawn
        session_dir = str(self.gcs.session.path)
        log_path = os.path.join(session_dir, "logs",
                                "autopilot_standby.log")
        logf = open(log_path, "ab")
        try:
            self._standby_proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.replication",
                 "--session", session_dir],
                stdout=logf, stderr=subprocess.STDOUT,
                start_new_session=True,
                env={**os.environ, **GLOBAL_CONFIG.to_env()})
        finally:
            logf.close()
        logger.info("autopilot launched GCS standby pid=%d (log: %s)",
                    self._standby_proc.pid, log_path)
        if self._closed:
            # raced a concurrent clean shutdown (the monitor thread was
            # mid-tick when it started): tear the fresh standby down —
            # an orphan would promote over a deliberately stopped head
            self.shutdown()
            return False
        return True

    def shutdown(self) -> None:
        """Clean head shutdown: the supervised standby must die with us
        (promoting over a deliberately stopped cluster would resurrect
        it).  A SIGKILLed head never runs this — exactly the case the
        standby exists to survive."""
        self._closed = True
        p, self._standby_proc = self._standby_proc, None
        if p is not None and p.poll() is None:
            p.terminate()
            try:
                p.wait(timeout=5)
            except Exception:  # noqa: BLE001 - stubborn child
                p.kill()
                p.wait(timeout=5)
