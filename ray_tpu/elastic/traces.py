"""Seeded fleet traces: scripted preemption + diurnal demand.

The same trace-replay idiom as ``benchmarks/llm_bench.py`` (seeded
``numpy`` RNG, diurnal modulation plus bursts) applied to fleet events:
a trace is data, generated once from a seed, and every consumer —
the fleet simulator, the churn test, ``fleet_bench.py`` — replays the
identical event list, so a 100-node simulation is reproducible from
``(seed, params)`` alone.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class PreemptionEvent:
    t: float              # sim seconds from trace start
    slice_index: int      # which fleet slot the preemption hits
    warning_s: float      # advance notice (0 = unwarned SIGKILL)


@dataclass
class StragglerEvent:
    """One node degrades (slow HBM, thermal throttle, noisy neighbor):
    from ``t`` the victim's compute runs at ``factor`` of nominal for
    ``duration_s`` — and a synchronous training domain runs at its
    slowest member's pace, so the WHOLE job drags until the node is
    drained (the autopilot's reflex) or the episode ends."""

    t: float
    slice_index: int
    factor: float
    duration_s: float


@dataclass
class PreemptionTrace:
    duration_s: float
    events: List[PreemptionEvent] = field(default_factory=list)
    # launch-outage windows: [start, end) during which the provider
    # cannot boot replacements (spot capacity crunch) — demand backlogs
    # and MUST fully drain once the window closes (the no-strand test)
    outages: List[tuple] = field(default_factory=list)
    # degradation episodes (closed-loop autopilot traces)
    stragglers: List[StragglerEvent] = field(default_factory=list)

    def in_outage(self, t: float) -> bool:
        return any(a <= t < b for a, b in self.outages)


def synthetic_preemption_trace(
        seed: int, duration_s: float, n_slices: int,
        mean_interval_s: float = 180.0,
        warning_s: float = 30.0,
        unwarned_fraction: float = 0.0,
        outage_every_s: Optional[float] = None,
        outage_len_s: float = 120.0,
        straggler_every_s: Optional[float] = None,
        straggler_factor: float = 0.4,
        straggler_len_s: float = 900.0) -> PreemptionTrace:
    """Poisson preemption arrivals over a fleet of ``n_slices`` slots.

    ``unwarned_fraction`` of events carry no advance notice (hard
    SIGKILL — the restart-only failure mode both recovery policies pay
    full price for); the rest give ``warning_s`` of drain window.

    ``straggler_every_s`` adds seeded degradation episodes (the
    autopilot's straggler-reflex input) from an INDEPENDENT rng stream
    (``seed + 2``), so a straggler-bearing trace replays the exact
    preemption/outage event list of its straggler-free sibling — the
    closed-loop A/B compares reflexes, not different weather.
    """
    import numpy as np
    rng = np.random.default_rng(seed)
    events: List[PreemptionEvent] = []
    t = 0.0
    while True:
        t += float(rng.exponential(mean_interval_s))
        if t >= duration_s:
            break
        warned = rng.random() >= unwarned_fraction
        events.append(PreemptionEvent(
            t=round(t, 3),
            slice_index=int(rng.integers(0, n_slices)),
            warning_s=warning_s if warned else 0.0))
    outages = []
    if outage_every_s:
        start = outage_every_s
        while start < duration_s:
            outages.append((start, min(start + outage_len_s, duration_s)))
            start += outage_every_s
    stragglers: List[StragglerEvent] = []
    if straggler_every_s:
        srng = np.random.default_rng(seed + 2)
        t = 0.0
        while True:
            t += float(srng.exponential(straggler_every_s))
            if t >= duration_s:
                break
            stragglers.append(StragglerEvent(
                t=round(t, 3),
                slice_index=int(srng.integers(0, n_slices)),
                factor=straggler_factor,
                duration_s=straggler_len_s))
    return PreemptionTrace(duration_s=duration_s, events=events,
                           outages=outages, stragglers=stragglers)


@dataclass
class DemandTrace:
    """Diurnal + burst demand curve: ``shapes_at(t)`` -> how many
    worker-shaped resource demands are outstanding at sim time t."""

    duration_s: float
    base: int
    amplitude: int
    period_s: float
    bursts: List[tuple]    # (t_start, extra, length_s)

    def shapes_at(self, t: float) -> int:
        level = self.base + self.amplitude * math.sin(
            2 * math.pi * t / self.period_s)
        for start, extra, length in self.bursts:
            if start <= t < start + length:
                level += extra
        return max(int(round(level)), 0)


def diurnal_demand_trace(seed: int, duration_s: float,
                         base: int = 8, amplitude: int = 4,
                         period_s: float = 3600.0,
                         burst_rate_per_hour: float = 2.0,
                         burst_extra: int = 6,
                         burst_len_s: float = 300.0) -> DemandTrace:
    import numpy as np
    rng = np.random.default_rng(seed + 1)
    bursts = []
    t = 0.0
    while True:
        t += float(rng.exponential(3600.0 / max(burst_rate_per_hour, 1e-9)))
        if t >= duration_s:
            break
        bursts.append((round(t, 3),
                       int(rng.integers(1, burst_extra + 1)),
                       burst_len_s))
    return DemandTrace(duration_s=duration_s, base=base,
                       amplitude=amplitude, period_s=period_s,
                       bursts=bursts)
