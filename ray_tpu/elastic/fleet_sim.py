"""Fleet simulator: O(100) simulated nodes vs the REAL autoscaler loop.

The harness ``cluster_utils.Cluster`` + the fake kube provider grew into
(DESIGN.md §4j): a simulated clock drives

- a :class:`SimNodeProvider` (instant CRUD, boot delays applied on sim
  time, launch outages from the trace),
- the real :class:`~ray_tpu.autoscaler.autoscaler.StandardAutoscaler`
  reconcile loop — ``update()`` runs verbatim with its inputs
  (demand / utilization / phases / clock) fed from sim state, so the
  bin-packing under test is ``resource_demand_scheduler
  .get_nodes_to_launch`` itself, not a reimplementation,
- a placement ledger asserting the two churn invariants: **no demand
  stranded** (every feasible shape eventually places once capacity
  allows) and **no double-placement** (node capacity never
  oversubscribed; one placement per demand slot),
- goodput accounting for one fleet-wide elastic training job under the
  two recovery policies (elastic re-mesh vs restart-from-checkpoint),
  replayed on the SAME node trajectory,
- optionally, the CLOSED LOOP (§4n): the REAL autopilot policy
  (``elastic/autopilot.py``) driven on sim time through a
  :class:`SimActuator` — straggler episodes from the trace become
  node-tagged detections, remediation drains cost the job real warned
  transitions, pre-warms and the forecast floor actuate through the
  real autoscaler hooks, and the rate-limit / veto bounds are asserted
  against the exact code production runs.

Everything is deterministic from ``(seed, params)``: traces are data
(``elastic/traces.py``), the sim never reads wall clocks (the autopilot
gets the sim clock injected), and ties break by sorted ids.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.autoscaler import AutoscalerConfig, StandardAutoscaler
from ray_tpu.autoscaler.node_provider import (
    NODE_KIND_WORKER, NodeProvider, TAG_NODE_KIND, TAG_NODE_TYPE)
from ray_tpu.elastic.autopilot import Actuator, Autopilot, AutopilotConfig
from ray_tpu.elastic.goodput import GoodputTracker
from ray_tpu.elastic.traces import DemandTrace, PreemptionTrace


# ------------------------------------------------------------------ provider
class SimNode:
    __slots__ = ("node_id", "node_type", "resources", "phase",
                 "ready_at", "drain_deadline", "placements")

    def __init__(self, node_id: str, node_type: str,
                 resources: Dict[str, float], ready_at: float):
        self.node_id = node_id
        self.node_type = node_type
        self.resources = dict(resources)
        self.phase = "pending"        # pending -> running -> draining
        self.ready_at = ready_at
        self.drain_deadline: Optional[float] = None
        self.placements: List[Dict[str, float]] = []

    def available(self) -> Dict[str, float]:
        out = dict(self.resources)
        for shape in self.placements:
            for k, v in shape.items():
                out[k] = out.get(k, 0.0) - v
        return out

    def fits(self, shape: Dict[str, float]) -> bool:
        avail = self.available()
        return all(avail.get(k, 0.0) >= v for k, v in shape.items()
                   if v > 0)


class SimNodeProvider(NodeProvider):
    """Deterministic in-memory provider on sim time.  ``create_node``
    during a trace outage window raises (spot capacity crunch) — the
    autoscaler's reconcile loop must tolerate that and retry."""

    def __init__(self, boot_delay_s: float = 30.0):
        super().__init__({}, "sim")
        self.boot_delay_s = boot_delay_s
        self.nodes: Dict[str, SimNode] = {}
        self.now = 0.0
        self.outage = False
        self._seq = 0
        self.launch_failures = 0

    # -- NodeProvider interface
    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        want_type = tag_filters.get(TAG_NODE_TYPE)
        return sorted(nid for nid, n in self.nodes.items()
                      if want_type is None or n.node_type == want_type)

    def node_tags(self, node_id: str) -> Dict[str, str]:
        n = self.nodes.get(node_id)
        if n is None:
            return {}
        return {TAG_NODE_KIND: NODE_KIND_WORKER,
                TAG_NODE_TYPE: n.node_type}

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> List[str]:
        if self.outage:
            self.launch_failures += count
            raise RuntimeError("sim provider: launch capacity outage")
        out = []
        for _ in range(count):
            self._seq += 1
            nid = f"sim-{self._seq:05d}"
            self.nodes[nid] = SimNode(
                nid, tags.get(TAG_NODE_TYPE, ""),
                dict(node_config.get("resources", {})),
                ready_at=self.now + self.boot_delay_s)
            out.append(nid)
        return out

    def terminate_node(self, node_id: str) -> None:
        self.nodes.pop(node_id, None)

    def drain_node(self, node_id: str, deadline_s: float = 0.0,
                   reason: str = "preemption") -> None:
        n = self.nodes.get(node_id)
        if n is not None and n.phase != "pending":
            n.phase = "draining"
            n.drain_deadline = self.now + deadline_s

    # -- sim hooks
    def tick(self, now: float, outage: bool) -> None:
        self.now = now
        self.outage = outage
        for n in self.nodes.values():
            if n.phase == "pending" and now >= n.ready_at:
                n.phase = "running"

    def running(self) -> List[SimNode]:
        return sorted((n for n in self.nodes.values()
                       if n.phase == "running"),
                      key=lambda n: n.node_id)


class SimAutoscaler(StandardAutoscaler):
    """The real reconcile loop with sim-fed inputs: demand comes from
    the harness's unplaced ledger, utilization/phases from sim nodes,
    and the clock from the sim."""

    def __init__(self, config: AutoscalerConfig, provider: SimNodeProvider,
                 harness: "FleetSimulator"):
        super().__init__(config, provider)
        self._harness = harness
        self._clock = lambda: provider.now

    def _demand(self) -> List[Dict[str, float]]:
        return self._harness.unfulfilled_demand()

    def _node_utilization(self) -> Dict[str, bool]:
        return {nid: not n.placements
                for nid, n in self._harness.provider.nodes.items()}

    def _node_phases(self) -> Dict[str, str]:
        return {nid: n.phase
                for nid, n in self._harness.provider.nodes.items()}


# ----------------------------------------------------------------- actuator
class SimActuator(Actuator):
    """Binds the REAL autopilot policy (``elastic/autopilot.py``) to the
    simulated fleet: drains go through the sim provider (and cost the
    job a warned transition, exactly like a provider preemption),
    pre-warms and the forecast floor go through the REAL autoscaler's
    new hooks, and every emitted event lands in the sim's action log.
    The storm bounds asserted here are the storm bounds production
    runs."""

    def __init__(self, sim: "FleetSimulator"):
        self.sim = sim
        self.veto_fn = None          # test hook: node_id -> reason|None

    def drain(self, node_id: str, reason: str) -> bool:
        return self.sim._autopilot_drain(node_id, reason)

    def undrain(self, node_id: str) -> bool:
        n = self.sim.provider.nodes.get(node_id)
        if n is None or n.phase != "draining":
            return False
        n.phase = "running"
        return True

    def veto(self, node_id):
        return self.veto_fn(node_id) if self.veto_fn else None

    def prewarm(self, node_id: str) -> bool:
        n = self.sim.provider.nodes.get(node_id)
        if n is None or not n.placements:
            return False        # idle node: a replacement buys nothing
        return self.sim.autoscaler.prewarm_for_drain(node_id)

    def demand_now(self) -> float:
        return float(self.sim._demand_level)

    def demand_forecast(self):
        return self.sim._seasonal_forecast()

    def forecast_demand(self, slots: int) -> bool:
        self.sim.autoscaler.set_forecast_demand(slots)
        return True

    def emit(self, kind, node_id=None, **fields):
        self.sim.emitted.append({"kind": kind, "node_id": node_id,
                                 "t": self.sim.provider.now, **fields})


# ------------------------------------------------------------------ job model
@dataclass
class TrainJobModel:
    """One fleet-wide elastic training job for the goodput A/B.

    Throughput is ``steps_per_s_per_slice × active_slices`` (per-slice
    batch, weak scaling).  Transition costs are the policy knobs:

    - ``remesh_s`` — elastic quiesce → re-init → re-shard pause (the
      live path measures ~0.2s on the CPU rig toy; 15s is a
      conservative multi-host figure covering ICI re-init + compile).
    - ``coldstart_s`` — full-group restart: processes respawn, jax
      re-imports, program recompiles, state restores from the persisted
      checkpoint.
    - ``checkpoint_every_s`` — the restart policy additionally re-runs
      work since the last checkpoint; the elastic path gathers at the
      quiesce boundary so a WARNED preemption never loses steps.
    """

    slices_target: int = 16
    steps_per_s_per_slice: float = 1.0
    remesh_s: float = 15.0
    coldstart_s: float = 120.0
    checkpoint_every_s: float = 300.0


class _PolicyState:
    def __init__(self, policy: str, job: TrainJobModel, t0: float):
        self.policy = policy
        self.job = job
        self.tracker = GoodputTracker(t0=t0)
        self.active = 0              # live slices
        self.formed = False          # reached full strength once
        self.paused_until = 0.0
        self.pending_recompute_s = 0.0
        self.last_checkpoint_t = 0.0
        self.transitions = 0

    def lose_slice(self, t: float, warned: bool) -> None:
        if self.active <= 0:
            return
        self.active -= 1
        self.transitions += 1
        if self.policy == "elastic" and warned:
            self._pause(t, self.job.remesh_s)
        else:
            # unwarned loss (both policies) or restart policy: full
            # cold start + recompute back to the last checkpoint
            lost = min(t - self.last_checkpoint_t,
                       self.job.checkpoint_every_s)
            self.pending_recompute_s = max(lost, 0.0)
            self._pause(t, self.job.coldstart_s)

    def gain_slice(self, t: float) -> None:
        if self.active >= self.job.slices_target:
            return
        self.active += 1
        if not self.formed:
            # initial formation is free for BOTH policies: the A/B
            # measures recovery economics, not first bring-up
            if self.active >= self.job.slices_target:
                self.formed = True
                self.last_checkpoint_t = t
            return
        self.transitions += 1
        if self.policy == "elastic":
            self._pause(t, self.job.remesh_s)
        else:
            lost = min(t - self.last_checkpoint_t,
                       self.job.checkpoint_every_s)
            self.pending_recompute_s = max(lost, 0.0)
            self._pause(t, self.job.coldstart_s)

    def _pause(self, t: float, dur: float) -> None:
        # overlapping pauses extend, not stack: account only the wall
        # time this transition actually adds
        new_until = max(self.paused_until, t + dur)
        self.tracker.record_pause(new_until - max(self.paused_until, t))
        self.paused_until = new_until

    def advance(self, t: float, dt: float,
                rate_scale: float = 1.0) -> None:
        """Accrue progress over [t, t+dt).  ``rate_scale`` < 1 models a
        degraded (straggling) member gating the synchronous domain —
        the whole job runs at the slowest rank's pace until the node is
        drained out (the autopilot reflex) or recovers."""
        run_s = dt
        if t < self.paused_until:
            run_s = max(0.0, (t + dt) - self.paused_until)
        if run_s <= 0 or self.active <= 0:
            self.tracker.add_progress(ts=t + dt)
            return
        rate = self.job.steps_per_s_per_slice * self.active * rate_scale
        # recompute debt burns run time producing WASTED steps first
        waste_s = min(self.pending_recompute_s, run_s)
        self.pending_recompute_s -= waste_s
        useful_s = run_s - waste_s
        self.tracker.add_progress(useful=rate * useful_s,
                                  wasted=rate * waste_s, ts=t + dt)
        if t + dt - self.last_checkpoint_t >= self.job.checkpoint_every_s:
            self.last_checkpoint_t = t + dt


# ------------------------------------------------------------------ simulator
@dataclass
class FleetReport:
    duration_s: float
    ticks: int
    launched: int
    preempted: int
    stranded_demand: int
    max_unfulfilled: int
    double_placements: int
    policies: Dict[str, dict] = field(default_factory=dict)
    unfulfilled_integral: float = 0.0      # shape-seconds of demand lag
    autopilot: Optional[dict] = None       # closed-loop action summary

    @property
    def goodput_ratio(self) -> float:
        e = self.policies.get("elastic", {}).get("goodput_steps_per_s", 0.0)
        r = self.policies.get("restart", {}).get("goodput_steps_per_s", 0.0)
        return e / r if r else float("inf")

    def to_dict(self) -> dict:
        return {"duration_s": self.duration_s, "ticks": self.ticks,
                "launched": self.launched, "preempted": self.preempted,
                "stranded_demand": self.stranded_demand,
                "max_unfulfilled": self.max_unfulfilled,
                "double_placements": self.double_placements,
                "unfulfilled_integral": round(self.unfulfilled_integral, 3),
                "autopilot": self.autopilot,
                "goodput_ratio": (round(self.goodput_ratio, 4)
                                  if self.goodput_ratio != float("inf")
                                  else None),
                "policies": self.policies}


class FleetSimulator:
    def __init__(self, *, node_types: Dict[str, dict],
                 demand_shape: Dict[str, float],
                 preemption: PreemptionTrace,
                 demand: Optional[DemandTrace] = None,
                 job: Optional[TrainJobModel] = None,
                 tick_s: float = 5.0,
                 boot_delay_s: float = 30.0,
                 max_workers: int = 200,
                 autoscale_every_s: float = 10.0,
                 autopilot: bool = False,
                 autopilot_config: Optional[AutopilotConfig] = None,
                 detector_delay_s: float = 20.0,
                 drain_grace_s: float = 20.0,
                 forecast_horizon_s: float = 90.0,
                 forecast_period_s: Optional[float] = None):
        self.preemption = preemption
        self.demand_trace = demand
        self.demand_shape = dict(demand_shape)
        self.tick_s = tick_s
        self.provider = SimNodeProvider(boot_delay_s=boot_delay_s)
        self.autoscaler = SimAutoscaler(
            AutoscalerConfig(node_types=node_types,
                             max_workers=max_workers,
                             idle_timeout_s=120.0),
            self.provider, self)
        self.autoscale_every_s = autoscale_every_s
        self.job = job
        self._demand_level = 0
        self._placed = 0          # placements currently held
        self._double_placements = 0
        # --- closed loop (§4n): the REAL autopilot policy on sim time
        self.actuator = SimActuator(self)
        self.autopilot: Optional[Autopilot] = None
        if autopilot:
            self.autopilot = Autopilot(
                autopilot_config or AutopilotConfig(),
                self.actuator, clock=lambda: self.provider.now,
                metrics=False)
        self.detector_delay_s = detector_delay_s
        self.drain_grace_s = drain_grace_s
        self.forecast_horizon_s = forecast_horizon_s
        self.forecast_period_s = forecast_period_s or (
            demand.period_s if demand is not None else 3600.0)
        self.emitted: List[dict] = []            # autopilot fleet events
        self.unfulfilled_integral = 0.0          # shape-seconds of lag
        self._policies: Dict[str, _PolicyState] = {}
        self._death_row: List[tuple] = []        # (kill_at, node_id)
        self._stragglers: Dict[str, tuple] = {}  # node -> (factor, until)
        self._strag_reported: Dict[str, float] = {}
        self._demand_history: List[tuple] = []   # (t, level)

    # -- harness inputs to the real autoscaler
    def unfulfilled_demand(self) -> List[Dict[str, float]]:
        missing = max(self._demand_level - self._placed, 0)
        return [dict(self.demand_shape) for _ in range(missing)]

    # -- placement ledger
    def _place_pending(self) -> None:
        missing = max(self._demand_level - self._placed, 0)
        if missing <= 0:
            return
        for node in self.provider.running():
            while missing > 0 and node.fits(self.demand_shape):
                node.placements.append(dict(self.demand_shape))
                avail = node.available()
                if any(v < -1e-9 for v in avail.values()):
                    self._double_placements += 1
                self._placed += 1
                missing -= 1
            if missing <= 0:
                break

    def _release_over_demand(self) -> None:
        """Diurnal down-curve: drop the most recent placements first
        (live systems cancel the newest queued work)."""
        excess = self._placed - self._demand_level
        for node in reversed(self.provider.running()):
            while excess > 0 and node.placements:
                node.placements.pop()
                self._placed -= 1
                excess -= 1

    # -- closed-loop hooks (§4n)
    def _seasonal_forecast(self) -> Optional[float]:
        """Demand level one season back at (now + horizon) — the sim's
        stand-in for the head TSDB's 48h rungs (same seasonal-naive
        baseline as ``TSDB.forecast``)."""
        anchor = self.provider.now + self.forecast_horizon_s \
            - self.forecast_period_s
        if anchor < 0:
            return None     # cold start: less than one period of history
        best = None
        for ts, level in self._demand_history:
            if ts <= anchor:
                best = level
            else:
                break
        return None if best is None else float(best)

    def _autopilot_drain(self, node_id: str, reason: str) -> bool:
        """The autopilot's remediation drain, sim-side: mark the node
        draining (it stops straggling the domain — the quiesce excludes
        it), schedule its hand-off death after ``drain_grace_s``, and
        charge every policy the WARNED transition it causes."""
        node = self.provider.nodes.get(node_id)
        if node is None or node.phase != "running":
            return False
        t = self.provider.now
        self.provider.drain_node(node_id, deadline_s=self.drain_grace_s)
        self._death_row.append((t + self.drain_grace_s, node_id))
        self._stragglers.pop(node_id, None)
        if node.placements:
            for ps in self._policies.values():
                ps.lose_slice(t, warned=True)
        return True

    def _rate_scale(self, t: float) -> float:
        """The synchronous domain runs at its slowest member's pace: the
        min factor over currently-degraded nodes still holding
        placements and still in the domain (phase running)."""
        scale = 1.0
        for nid in list(self._stragglers):
            factor, until = self._stragglers[nid]
            node = self.provider.nodes.get(nid)
            if until <= t or node is None:
                self._stragglers.pop(nid)
                self._strag_reported.pop(nid, None)
                continue
            if node.phase == "running" and node.placements:
                scale = min(scale, factor)
        return scale

    def _feed_autopilot(self, t: float) -> None:
        """Synthesize the detector/fleet-event feed for the reflex
        engine: a degradation episode older than ``detector_delay_s``
        (the sim's stand-in for the straggler detector's window) fires a
        node-tagged straggler event, re-fired each detector interval
        while it persists — the flapping input the rate limits must
        bound."""
        ap = self.autopilot
        if ap is None:
            return
        for nid, (factor, until) in self._stragglers.items():
            node = self.provider.nodes.get(nid)
            if node is None or node.phase != "running" \
                    or not node.placements:
                continue
            onset = self._strag_onset.get(nid, t)
            last = self._strag_reported.get(nid)
            if t - onset < self.detector_delay_s:
                continue
            if last is not None and t - last < self.detector_delay_s:
                continue
            self._strag_reported[nid] = t
            ap.observe({"kind": "straggler", "node_id": nid,
                        "skew_ratio": round(1.0 / max(factor, 1e-9), 3)})
        ap.tick(now=t)

    # -- run
    def run(self) -> FleetReport:
        trace = self.preemption
        events = sorted(trace.events, key=lambda e: (e.t, e.slice_index))
        stragglers = sorted(trace.stragglers,
                            key=lambda e: (e.t, e.slice_index))
        ev_i = sv_i = 0
        t = 0.0
        ticks = 0
        launched_total = 0
        preempted_total = 0
        max_unfulfilled = 0
        next_autoscale = 0.0
        self._death_row = []
        self._strag_onset: Dict[str, float] = {}
        policies = {}
        if self.job is not None:
            policies = {p: _PolicyState(p, self.job, t0=0.0)
                        for p in ("elastic", "restart")}
        self._policies = policies

        while t < trace.duration_s:
            outage = trace.in_outage(t)
            self.provider.tick(t, outage)
            # demand level from the trace (constant when none)
            if self.demand_trace is not None:
                self._demand_level = self.demand_trace.shapes_at(t)
            elif self.job is not None:
                self._demand_level = self.job.slices_target
            self._demand_history.append((t, self._demand_level))
            # job slices come up as placements land on booted nodes
            before = self._placed
            self._place_pending()
            self._release_over_demand()
            gained = self._placed - before
            for ps in policies.values():
                for _ in range(max(gained, 0)):
                    ps.gain_slice(t)

            # degradation episodes due this tick hit a PLACED node (an
            # idle node straggling drags nobody)
            while sv_i < len(stragglers) and \
                    stragglers[sv_i].t < t + self.tick_s:
                sv = stragglers[sv_i]
                sv_i += 1
                placed = [n for n in self.provider.running()
                          if n.placements]
                if not placed:
                    continue
                victim = placed[sv.slice_index % len(placed)]
                self._stragglers[victim.node_id] = (
                    sv.factor, sv.t + sv.duration_s)
                self._strag_onset[victim.node_id] = sv.t

            # preemption events due this tick
            while ev_i < len(events) and events[ev_i].t < t + self.tick_s:
                ev = events[ev_i]
                ev_i += 1
                running = self.provider.running()
                if not running:
                    continue
                victim = running[ev.slice_index % len(running)]
                preempted_total += 1
                warned = ev.warning_s > 0
                if warned:
                    self.provider.drain_node(victim.node_id,
                                             deadline_s=ev.warning_s)
                    self._death_row.append(
                        (ev.t + ev.warning_s, victim.node_id))
                    if self.autopilot is not None:
                        self.autopilot.observe(
                            {"kind": "node_draining",
                             "node_id": victim.node_id})
                else:
                    self._kill_node(victim.node_id)
                if victim.placements:
                    for ps in policies.values():
                        ps.lose_slice(ev.t, warned)
            # warned preemptions whose deadline passed die now
            due = [nid for kill_at, nid in self._death_row
                   if kill_at <= t]
            self._death_row = [(k, n) for k, n in self._death_row
                               if k > t]
            for nid in due:
                self._kill_node(nid)

            # the reflex pass: detector feed + autopilot tick (§4n)
            self._feed_autopilot(t)

            # the REAL autoscaler reconcile, on its own cadence
            if t >= next_autoscale:
                next_autoscale = t + self.autoscale_every_s
                try:
                    report = self.autoscaler.update()
                    launched_total += sum(
                        len(ids) for ids in report["launched"].values())
                except RuntimeError:
                    pass        # outage window: launches rejected
            backlog = len(self.unfulfilled_demand())
            max_unfulfilled = max(max_unfulfilled, backlog)
            self.unfulfilled_integral += backlog * self.tick_s
            rate_scale = self._rate_scale(t)
            for ps in policies.values():
                ps.advance(t, self.tick_s, rate_scale)
            t += self.tick_s
            ticks += 1

        # drain phase: a backlog at trace end is only STRANDED if it
        # survives quiet time too (no events, no outage) — an in-flight
        # boot or a just-closed outage window resolves here.  Goodput
        # accounting stays frozen at duration_s.
        drain_deadline = t + 600.0
        while t < drain_deadline and self.unfulfilled_demand():
            self.provider.tick(t, False)
            self._place_pending()
            if t >= next_autoscale:
                next_autoscale = t + self.autoscale_every_s
                try:
                    self.autoscaler.update()
                except RuntimeError:
                    pass
            t += self.tick_s

        ap_summary = None
        if self.autopilot is not None:
            stats = self.autopilot.stats()
            ap_summary = {"counts": stats["counts"],
                          "forecast_slots": stats["forecast_slots"],
                          "events": len(self.emitted)}
        report = FleetReport(
            duration_s=trace.duration_s, ticks=ticks,
            launched=launched_total, preempted=preempted_total,
            stranded_demand=len(self.unfulfilled_demand()),
            max_unfulfilled=max_unfulfilled,
            double_placements=self._double_placements,
            unfulfilled_integral=self.unfulfilled_integral,
            autopilot=ap_summary,
            policies={p: {**ps.tracker.summary(now=trace.duration_s),
                          "active_slices": ps.active,
                          "transitions": ps.transitions}
                      for p, ps in policies.items()})
        return report

    def _kill_node(self, node_id: str) -> None:
        node = self.provider.nodes.get(node_id)
        if node is None:
            return
        self._placed -= len(node.placements)
        self.provider.terminate_node(node_id)
        self._stragglers.pop(node_id, None)
        self._strag_reported.pop(node_id, None)
        if self.autopilot is not None:
            self.autopilot.observe({"kind": "node_removed",
                                    "node_id": node_id})
