"""``ray_tpu.elastic`` — slice-granular fleet elasticity (DESIGN.md §4j).

The subsystem ROADMAP open item 5 named, in three coupled pieces:

- **Elasticity manager** (``manager.py``): a head-side controller layered
  on the autoscaler's demand view and the raylet liveness path.  It
  subscribes to the GCS fleet-event feed (``node_draining`` preemption
  warnings, node add/remove), and drives *re-mesh-without-restart*: on a
  warned preemption the surviving ``jax.distributed`` domain quiesces at
  a step boundary, every rank leaves the old domain cleanly, survivors
  re-initialize at the new world size and re-shard optimizer/model state
  from the last gathered checkpoint — the surviving Python processes
  never die.  On scale-up a rejoining slice attaches to the running
  group the same way.  Unwarned (SIGKILL) losses fall back to a
  full-group restart from the same gathered state.

- **Fleet simulator** (``fleet_sim.py`` + ``traces.py``): an
  O(100)-simulated-node harness replaying scripted preemption and
  diurnal-demand traces (seeded, deterministic) against the REAL
  autoscaler bin-packing loop, with goodput accounting for the elastic
  vs restart-from-checkpoint recovery policies
  (``benchmarks/fleet_bench.py`` commits the A/B artifact).

- **Goodput accounting** (``goodput.py``): useful (first-time) train
  steps per wall-second — the chaos suite asserts goodput, not mere
  survival.

- **Autopilot** (``autopilot.py``, DESIGN.md §4n): the reflex arc
  closing the observability → actuation loop — straggler events drain
  the offending host, drain warnings pre-warm replacements, the TSDB's
  diurnal history feeds the autoscaler a lead-time demand signal, and
  the head keeps a warm GCS standby attached.  Rate-limited,
  hysteresis-guarded, vetoed — and every action is itself a fleet
  event + metric.
"""

from ray_tpu.elastic.autopilot import (Autopilot, AutopilotConfig,
                                       GcsActuator)
from ray_tpu.elastic.events import (FleetEventSubscriber, drain_node,
                                    fleet_events, fleet_state)
from ray_tpu.elastic.goodput import GoodputTracker
from ray_tpu.elastic.manager import (ElasticConfig, ElasticResult,
                                     ElasticityManager)
from ray_tpu.elastic.worker_loop import ElasticSpec

__all__ = [
    "Autopilot", "AutopilotConfig", "ElasticConfig", "ElasticResult",
    "ElasticSpec", "ElasticityManager", "FleetEventSubscriber",
    "GcsActuator", "GoodputTracker", "drain_node", "fleet_events",
    "fleet_state",
]
