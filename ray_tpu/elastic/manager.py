"""Elasticity manager: the head-side controller of DESIGN.md §4j.

Owns one elastic train group end to end: spawns the worker actors
(one per schedulable node — the slice is the elasticity unit), publishes
mesh-generation plans, watches the GCS fleet-event feed, and drives the
three transitions:

- **re-mesh** (warned preemption / ``node_draining``): quiesce at a step
  boundary → every old-domain rank leaves cleanly → survivors
  re-initialize at the new world size and re-shard from the gathered
  state.  Surviving processes stay alive — no cold start.
- **join** (scale-up / a preempted slice restored): same quiesce cycle
  with the new worker included in the next plan; only the joiner pays a
  cold start.
- **restart** (unwarned SIGKILL): XLA's coordination service terminates
  the whole domain; the manager force-kills the remains, respawns a
  fresh group, and resumes from the last gathered state in the KV —
  the restart-from-checkpoint baseline behavior, kept as the fallback.

Progress is accounted by :class:`~ray_tpu.elastic.goodput.GoodputTracker`
(useful steps per wall-second, re-runs excluded) and every transition is
reported to the GCS (``elastic_event``) so ``ray_tpu status`` shows the
last re-mesh cluster-wide.
"""

from __future__ import annotations

import socket
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu._private import rtlog
from ray_tpu._private import worker as _worker_mod
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.elastic import events as fleet
from ray_tpu.elastic.goodput import GoodputTracker
from ray_tpu.elastic.worker_loop import (ElasticKv, ElasticSpec,
                                         elastic_worker_loop)
from ray_tpu.util import metrics_catalog as mcat

logger = rtlog.get("elastic")


@dataclass
class ElasticConfig:
    """Manager knobs.

    num_workers: TARGET world size — the group runs degraded below it
        after preemptions and re-grows on scale-up.
    min_workers: below this the manager stops re-meshing smaller and
        waits for capacity (a restart can still re-form at >= min).
    cpus_per_worker: actor resource request.
    spread: place at most one worker per node (the slice failure-domain
        model; requires enough schedulable nodes) — node affinity rides
        the ``node:<id>`` resource.
    auto_rejoin: scale back up automatically when capacity appears.
    poll_s: manager reconcile period.
    """

    num_workers: int = 2
    min_workers: int = 1
    cpus_per_worker: float = 1.0
    # extra per-worker resource claims (TPU chips, custom resources) —
    # scheduled alongside cpus_per_worker so elastic workers account
    # for devices exactly like BackendExecutor workers do
    resources_per_worker: Optional[Dict[str, float]] = None
    spread: bool = True
    auto_rejoin: bool = True
    poll_s: float = 0.1
    group: Optional[str] = None
    quiesce_timeout_s: float = 60.0
    max_restarts: int = 4


@dataclass
class ElasticResult:
    history: List[dict] = field(default_factory=list)
    worker_results: List[dict] = field(default_factory=list)
    transitions: List[dict] = field(default_factory=list)
    goodput: Dict[str, Any] = field(default_factory=dict)
    error: Optional[BaseException] = None

    @property
    def generations(self) -> int:
        return max((t["generation"] for t in self.transitions), default=0)


class _Member:
    """One live worker actor of the group."""

    def __init__(self, worker_id: str, actor: Any, node_id: str):
        self.worker_id = worker_id
        self.actor = actor
        self.node_id = node_id
        self.ref: Any = None           # the running loop's result ref

    def __repr__(self) -> str:
        return f"_Member({self.worker_id[:8]}@{self.node_id[:8]})"


class ElasticityManager:
    def __init__(self, spec: ElasticSpec, config: ElasticConfig):
        import cloudpickle
        self.spec = spec
        self.config = config
        self.group = config.group or f"eg_{uuid.uuid4().hex[:8]}"
        self._spec_blob = cloudpickle.dumps(spec)
        self.kv = ElasticKv(self.group)
        self.goodput = GoodputTracker()
        self._gen = -1
        self._members: List[_Member] = []
        self._leavers: List[_Member] = []
        self._completing = False
        self._force_restart = False
        self._drained_nodes: set = set()
        self._transitions: List[dict] = []
        self._history: List[dict] = []
        self._worker_results: List[dict] = []
        self._restarts = 0
        # adopted object-plane state record (§4n): unpickling the KV
        # record borrows the embedded ObjectRef, so holding it here
        # keeps a large gathered checkpoint alive across worker
        # restarts (the publishing rank's own ref dies with it).  The
        # raw bytes are cached so an unchanged record is not
        # re-borrowed every poll.
        self._state_rec: Optional[dict] = None
        self._state_raw: Optional[bytes] = None
        self._events = fleet.FleetEventSubscriber(
            self._on_fleet_event,
            kinds=("node_draining", "node_added", "node_removed",
                   "node_undrained"))

    # ------------------------------------------------------------ lifecycle
    def fit(self, timeout_s: float = 600.0) -> ElasticResult:
        """Run the group to completion (or failure-budget exhaustion)."""
        deadline = time.monotonic() + timeout_s
        error: Optional[BaseException] = None
        self.kv.clear()
        try:
            self._start_group(cold=True)
            # the subscriber is polled INLINE from this loop (no thread):
            # transitions mutate manager state, and one writer beats a
            # lock discipline
            while time.monotonic() < deadline:
                self._collect_reports()
                self._events.poll_once()
                done = self._reap_members()
                if done is None and self._force_restart:
                    # a transition failed in a way that may have split
                    # the domain (some members quiesced, some not):
                    # recover deterministically instead of waiting for
                    # worker timeouts to surface as actor errors
                    done = False
                if done is not None:
                    self._force_restart = False
                    if done:            # completed cleanly
                        break
                    # hard failure -> restart fallback
                    self._restarts += 1
                    if self._restarts > self.config.max_restarts:
                        error = RuntimeError(
                            f"elastic group {self.group}: restart budget "
                            f"({self.config.max_restarts}) exhausted")
                        break
                    self._restart_group()
                time.sleep(self.config.poll_s)
            else:
                error = TimeoutError(
                    f"elastic group {self.group} did not finish in "
                    f"{timeout_s:.0f}s")
        except BaseException as e:  # noqa: BLE001 - surface in the result
            error = e
        finally:
            # the head may be the thing that died: the final sweep and
            # teardown must not raise out of fit() past the actor kills
            try:
                self._collect_reports()
            except Exception:  # noqa: BLE001
                logger.debug("final report sweep failed", exc_info=True)
            self._teardown()
        return ElasticResult(
            history=self._history, worker_results=self._worker_results,
            transitions=list(self._transitions),
            goodput=self.goodput.summary(now=time.monotonic()),
            error=error)

    # ------------------------------------------------------------- spawning
    def _pick_nodes(self, count: int, exclude: set) -> List[dict]:
        from ray_tpu.util import state
        need = dict(self.config.resources_per_worker or {})
        need.pop("CPU", None)
        nodes = [n for n in state.list_nodes()
                 if n["alive"] and n["phase"] == "running"
                 and n["node_id"] not in exclude
                 and all(n["resources_available"].get(k, 0.0) >= v
                         for k, v in need.items())]
        nodes.sort(key=lambda n: -n["resources_available"].get("CPU", 0.0))
        if self.config.spread:
            return nodes[:count]
        return [nodes[i % len(nodes)] for i in range(count)] if nodes else []

    def _spawn_member(self, node: dict) -> _Member:
        from ray_tpu.train._internal.worker_group import TrainWorkerActor
        worker_id = f"ew_{uuid.uuid4().hex[:8]}"
        res = dict(self.config.resources_per_worker or {})
        res.pop("CPU", None)   # CPU rides cpus_per_worker
        if self.config.spread:
            # node-affinity via the node-id resource: the worker IS the
            # slice's representative, so it must live on that node
            res[f"node:{node['node_id']}"] = 0.001
        actor = TrainWorkerActor.options(
            num_cpus=self.config.cpus_per_worker,
            resources=res or None).remote(0)
        member = _Member(worker_id, actor, node["node_id"])
        return member

    def _launch_loops(self, members: List[_Member], min_gen: int) -> None:
        for m in members:
            if m.ref is None:
                m.ref = m.actor.apply.remote(
                    elastic_worker_loop, self.group, m.worker_id,
                    self._spec_blob, min_gen)

    def _start_group(self, cold: bool) -> None:
        want = self.config.num_workers
        nodes = self._pick_nodes(want, exclude=self._drained_nodes)
        if len(nodes) < self.config.min_workers:
            raise RuntimeError(
                f"elastic group {self.group}: only {len(nodes)} "
                f"schedulable node(s) for min_workers="
                f"{self.config.min_workers}")
        self._members = [self._spawn_member(n) for n in nodes[:want]]
        self._gen += 1
        self._launch_loops(self._members, self._gen)
        self._publish_plan()
        self._record_transition("start" if cold else "restart")

    def _publish_plan(self) -> None:
        plan = {"gen": self._gen,
                "members": [m.worker_id for m in self._members],
                "coordinator": f"{_host_ip()}:{_free_port()}"}
        self.kv.put_plan(plan)
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_elastic_generation").set(
                float(self._gen), tags={"group": self.group})

    # ----------------------------------------------------------- transitions
    def _remesh(self, action: str,
                exclude_workers: Optional[set] = None,
                joiners: Optional[List[_Member]] = None) -> bool:
        """One quiesce → re-plan cycle.  Returns False when the quiesce
        acks did not all arrive (a member died mid-drain) — the caller
        falls back to the restart path."""
        t0 = time.monotonic()
        old = list(self._members)
        survivors = [m for m in old
                     if m.worker_id not in (exclude_workers or set())]
        new_members = survivors + list(joiners or [])
        if not survivors:
            return False               # nothing survives -> restart path
        target = self._gen + 1
        self.kv.put_quiesce(target)
        deadline = time.monotonic() + self.config.quiesce_timeout_s
        need = {m.worker_id for m in old}
        while time.monotonic() < deadline:
            if need.issubset(set(self.kv.acked(target))):
                break
            # a member dying mid-quiesce dooms the clean leave
            if self._any_member_failed(old):
                return self._abandon_quiesce()
            time.sleep(0.02)
        else:
            return self._abandon_quiesce()
        self._gen = target
        self._leavers.extend(m for m in old if m not in new_members)
        self._members = new_members
        self._launch_loops(self._members, self._gen)
        self._publish_plan()
        # leavers observe the new plan, return "drained", and are reaped
        # by _reap_leavers; their actors die with them
        dur = time.monotonic() - t0
        self.goodput.record_pause(dur)
        self._record_transition(action, duration_s=dur)
        return True

    def _abandon_quiesce(self) -> bool:
        """A transition could not complete: retract the quiesce intent
        (workers that haven't seen it must not walk into a plan that
        will never come) and schedule a deterministic restart — members
        that DID ack are already out of the old domain, so the group
        state is split and only a restart reconciles it."""
        try:
            self.kv.clear_quiesce()
        except Exception:  # noqa: BLE001 - head trouble; restart anyway
            pass
        self._force_restart = True
        return False

    def _restart_group(self) -> None:
        """Unwarned loss: kill what remains, respawn fresh, resume from
        the last gathered state (the KV checkpoint)."""
        t0 = time.monotonic()
        for m in self._members + self._leavers:
            try:
                ray_tpu.kill(m.actor)
            except Exception:  # noqa: BLE001 - already dead
                pass
        self._members = []
        self._leavers = []
        # stale quiesce intent must not immediately re-trigger on the
        # fresh group: the new plan's gen supersedes it
        self._start_group(cold=False)
        self.goodput.record_pause(time.monotonic() - t0)

    def _record_transition(self, action: str, **extra) -> None:
        rec = {"action": action, "generation": self._gen,
               "world_size": len(self._members),
               "ts": time.time(), **extra}
        self._transitions.append(rec)
        logger.info("elastic[%s] %s -> gen=%d world=%d", self.group,
                    action, self._gen, len(self._members))
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_elastic_remesh_total").inc(
                tags={"action": action})
            if "duration_s" in extra:
                mcat.get("rtpu_elastic_remesh_seconds").observe(
                    extra["duration_s"], tags={"action": action})
        try:
            _worker_mod.global_worker().rpc(
                "elastic_event", group=self.group, action=action,
                generation=self._gen,
                world_size=len(self._members),
                detail={k: v for k, v in extra.items()})
        except Exception:  # noqa: BLE001 - status surface is best-effort
            logger.debug("elastic_event report failed", exc_info=True)

    # ------------------------------------------------------------- reconcile
    def _on_fleet_event(self, ev: dict) -> None:
        kind, node_id = ev.get("kind"), ev.get("node_id")
        if self._completing:
            return     # the group is finishing; no more transitions
        if kind == "node_draining":
            victims = {m.worker_id for m in self._members
                       if m.node_id == node_id}
            if not victims:
                return
            self._drained_nodes.add(node_id)
            survivors = len(self._members) - len(victims)
            logger.info("elastic[%s] node %s draining (%d member(s) "
                        "affected)", self.group, node_id[:8], len(victims))
            if survivors >= self.config.min_workers:
                if not self._remesh("remesh", exclude_workers=victims):
                    # quiesce failed (member died under us): the reap
                    # pass will notice the errors and restart
                    logger.warning("elastic[%s] quiesce failed; falling "
                                   "back to restart", self.group)
        elif kind == "node_removed":
            self._drained_nodes.discard(node_id)
        elif kind == "node_undrained":
            # the autopilot returned a drained node to the pool (§4n):
            # it is schedulable again, so a degraded group may re-grow
            # onto it exactly like a fresh node
            self._drained_nodes.discard(node_id)
            if self.config.auto_rejoin:
                self._maybe_scale_up()
        elif kind == "node_added" and self.config.auto_rejoin:
            self._maybe_scale_up()

    def _maybe_scale_up(self) -> None:
        want = self.config.num_workers - len(self._members)
        if want <= 0:
            return
        taken = {m.node_id for m in self._members}
        nodes = self._pick_nodes(want, exclude=taken | self._drained_nodes)
        if not nodes:
            return
        joiners = [self._spawn_member(n) for n in nodes[:want]]
        # joiners only act on the NEXT generation's plan
        self._launch_loops(joiners, self._gen + 1)
        if not self._remesh("join", joiners=joiners):
            for j in joiners:
                try:
                    ray_tpu.kill(j.actor)
                except Exception:  # noqa: BLE001
                    pass

    def _any_member_failed(self, members: List[_Member]) -> bool:
        """True when a member died hard — OR when every loop already
        returned (the group completed while the quiesce was in flight);
        either way the caller must abandon the transition."""
        refs = [m.ref for m in members if m.ref is not None]
        done, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
        for d in done:
            try:
                ray_tpu.get(d)
            except (exc.RayActorError, exc.RayTaskError,
                    exc.ObjectLostError):
                return True
        return bool(refs) and len(done) == len(refs)

    def _reap_leavers(self) -> None:
        """Drained members return their record once they observe the
        plan that excludes them; collect it and drop the actor."""
        still: List[_Member] = []
        for m in self._leavers:
            done, _ = ray_tpu.wait([m.ref], num_returns=1, timeout=0)
            if not done:
                still.append(m)
                continue
            try:
                self._worker_results.append(ray_tpu.get(m.ref))
            except (exc.RayActorError, exc.RayTaskError,
                    exc.ObjectLostError):
                pass               # died on the way out; nothing to keep
            try:
                ray_tpu.kill(m.actor)
            except Exception:  # noqa: BLE001
                pass
        self._leavers = still

    def _reap_members(self) -> Optional[bool]:
        """Harvest finished loops.  Returns True when the whole group
        completed, False when a member failed hard (restart needed),
        None while still running."""
        self._reap_leavers()
        failed = False
        for m in self._members:
            if m.ref is None:
                continue           # already reported completion
            done, _ = ray_tpu.wait([m.ref], num_returns=1, timeout=0)
            if not done:
                continue
            try:
                res = ray_tpu.get(m.ref)
            except (exc.RayActorError, exc.RayTaskError,
                    exc.ObjectLostError):
                failed = True
                continue
            self._worker_results.append(res)
            # a clean return mid-run can only be "completed" (drained
            # members moved to _leavers before their plan excluded them)
            m.ref = None
            self._completing = True
        if failed:
            return False
        if self._members and all(m.ref is None for m in self._members):
            return True
        return None

    def _collect_reports(self) -> None:
        for rec in self.kv.poll_reports():
            useful = self.goodput.record_step(rec["step"])
            rec["useful"] = useful
            self._history.append(rec)
        # adopt the object-plane checkpoint record every pass: the
        # ``stateref`` key is tiny (absent for inline states), and
        # adopting at poll cadence keeps the publisher-died-before-
        # adoption window at ~poll_s.  Only a CHANGED record is
        # unpickled (and thereby borrowed) — the old borrow is dropped
        # when _state_rec is replaced.
        try:
            import pickle
            raw = self.kv._get("stateref")
            if raw is None:
                # the checkpoint reverted to inline (or was cleared):
                # release the superseded blob's borrow — the adopted
                # ref must not pin a replaced multi-GB object
                self._state_rec = None
                self._state_raw = None
            elif raw != self._state_raw:
                self._state_raw = raw
                self._state_rec = pickle.loads(raw)
        except Exception:  # noqa: BLE001 - adoption is best-effort
            logger.debug("state-record adoption failed", exc_info=True)
        if GLOBAL_CONFIG.metrics_enabled and self._history:
            mcat.get("rtpu_elastic_goodput_steps_per_s").set(
                self.goodput.goodput(now=time.monotonic()),
                tags={"group": self.group})

    def _teardown(self) -> None:
        try:
            self.kv.put_stop()
        except Exception:  # noqa: BLE001 - head gone; kills still matter
            pass
        for m in self._members + self._leavers:
            try:
                ray_tpu.kill(m.actor)
            except Exception:  # noqa: BLE001
                pass
        self._members = []
        self._leavers = []
        self._state_rec = None   # release the adopted checkpoint borrow
        try:
            # every worker is gone: drop the group's coordination keys
            # (plan/state/reports) so runs don't accrete in the GCS KV
            self.kv.clear()
        except Exception:  # noqa: BLE001 - head may be shutting down
            pass


# the coordinator-port allocation is shared with the train backend so a
# fix there (e.g. around the pick-then-rebind race) applies here too
from ray_tpu.train.backend import _free_port  # noqa: E402


def _host_ip() -> str:
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"
