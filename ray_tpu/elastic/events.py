"""Fleet lifecycle event feed: the subscription side of DESIGN.md §4j.

The GCS keeps a bounded ring of node add / drain / remove + re-mesh
events (``gcs._fleet_events``) behind two RPCs:

- ``fleet_events(since)`` — cursor read of the ring; a lagging reader
  may miss events (bounded ring) and should reconcile against
  ``list_nodes``.
- ``fleet_state()`` — one-call rollup: nodes by lifecycle phase, the
  demand backlog, the last elastic re-mesh.

``FleetEventSubscriber`` is the polling client the elasticity manager
and the Train backend (``JaxConfig(drain_handler=...)``) share: a daemon
thread delivering new events to a callback in feed order.  Polling, not
push — matching the autoscaler's reconcile idiom; the warning window of
a real preemption (30s+ on GCE) dwarfs the poll period.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu._private import rtlog
from ray_tpu._private import worker as _worker_mod

logger = rtlog.get("elastic")


def _rpc(kind: str, **kw) -> dict:
    return _worker_mod.global_worker().rpc(kind, **kw)


def fleet_events(since: int = 0) -> Tuple[List[dict], int]:
    """Events with seq > ``since`` plus the feed's current cursor."""
    resp = _rpc("fleet_events", since=since)
    return resp["events"], resp["seq"]


# one wrapper for the fleet_state RPC lives in the state API; re-export
# so elastic callers don't grow a drifting duplicate
from ray_tpu.util.state import fleet_state  # noqa: E402,F401


def drain_node(node_id: Optional[str] = None,
               label: Optional[Dict[str, str]] = None,
               deadline_s: float = 0.0,
               reason: str = "preemption") -> Optional[str]:
    """Signal a provider-initiated preemption warning for one node
    (by id, or by label match — e.g. ``{"ray-pod": pod_name}`` from the
    Kubernetes provider).  Returns the drained node's id, or None when
    no live node matched."""
    resp = _rpc("node_draining", node_id=node_id, label=label,
                deadline_s=deadline_s, reason=reason)
    return resp["node_id"] if resp.get("ok") else None


class FleetEventSubscriber:
    """Deliver fleet events to ``callback(event_dict)`` in feed order.

    ``kinds`` filters delivery (e.g. ``("node_draining",)``); the cursor
    still advances over filtered-out events.  Callback exceptions are
    logged and swallowed — a broken handler must not stop the feed.
    """

    def __init__(self, callback: Callable[[dict], None],
                 poll_s: float = 0.2,
                 kinds: Optional[Tuple[str, ...]] = None):
        self._callback = callback
        self._poll_s = max(poll_s, 0.02)
        self._kinds = tuple(kinds) if kinds else None
        # feed cursor, shared between the polling thread and inline
        # poll_once callers (ELASTIC_LOCK_DAG in lock_watchdog.py)
        self._cursor_lock = threading.Lock()
        self._since = 0                    # guarded by: _cursor_lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self, from_now: bool = True) -> "FleetEventSubscriber":
        if from_now:
            # skip history: only events after subscription fire
            try:
                _, seq = fleet_events(since=1 << 62)
            except Exception:  # noqa: BLE001 - feed not up yet
                seq = 0
            with self._cursor_lock:
                self._since = seq
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-event-subscriber")
        self._thread.start()
        return self

    def poll_once(self) -> List[dict]:
        """One synchronous poll (the manager's inline mode): returns the
        newly delivered events after invoking the callback on each.
        The RPC and the callbacks run OUTSIDE the cursor lock (blocking
        under a leaf lock is forbidden; §4d)."""
        with self._cursor_lock:
            since = self._since
        events, seq = fleet_events(since=since)
        with self._cursor_lock:
            if seq > self._since:
                self._since = seq
        delivered = []
        for ev in events:
            if self._kinds and ev.get("kind") not in self._kinds:
                continue
            delivered.append(ev)
            try:
                self._callback(ev)
            except Exception:  # noqa: BLE001 - keep the feed alive
                logger.exception("fleet event callback failed: %r", ev)
        return delivered

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - transient RPC failures
                if self._stop.is_set():
                    return
                logger.debug("fleet event poll failed", exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
