"""``ray_tpu.util.collective`` — collective communication among actors.

Reference: ``python/ray/util/collective/`` (SURVEY.md §2.4, §5.8).
"""

from ray_tpu.util.collective.collective import (  # noqa: F401
    allgather, allreduce, alltoall, barrier, broadcast,
    create_collective_group, destroy_collective_group,
    get_collective_group_size, get_rank, init_collective_group,
    is_group_initialized, recv, reduce, reducescatter, send,
)
from ray_tpu.util.collective.types import Backend, ReduceOp  # noqa: F401


def xla_group(devices=None, group_name: str = "default"):
    """Create an in-mesh device collective group (compiled ICI collectives).

    Imported lazily so the shm backend never pays the JAX import.
    """
    from ray_tpu.util.collective.collective_group.xla_group import (
        XlaCollectiveGroup)
    return XlaCollectiveGroup(devices, group_name)
