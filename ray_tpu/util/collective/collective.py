"""Public collective API.

Reference: ``python/ray/util/collective/collective.py`` (SURVEY.md §2.4) —
``init_collective_group`` / ``create_collective_group`` / ``allreduce`` /
``allgather`` / ``reducescatter`` / ``broadcast`` / ``reduce`` / ``barrier``
/ ``send`` / ``recv`` / ``destroy_collective_group`` / ``get_rank`` /
``get_collective_group_size``.

Two backends (types.Backend): ``shm`` — object-plane collectives among
arbitrary actors/processes (GLOO analog); ``xla`` — compiled shard_map
collectives over a local device set (NCCL analog; see xla_group.py for why
that group does not follow the per-rank calling convention).

Rendezvous is through the GCS KV (namespace "collective"): each rank
registers ``<group>/meta/<rank>`` and init blocks until all ranks are
present, mirroring the reference's named-actor NCCL-uid rendezvous.
"""

from __future__ import annotations

import pickle
import time
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu.util.collective.collective_group.shm_group import (
    ShmCollectiveGroup, _POLL_MAX, _POLL_MIN,
)
from ray_tpu.util.collective.types import Backend, ReduceOp

_groups: Dict[str, ShmCollectiveGroup] = {}


def _register_alias(alias: str, group_name: str) -> None:
    """Process-local alias → existing group (used by Train so user code can
    say "train_default" while the KV keys use a per-run unique name)."""
    _groups[alias] = _groups[group_name]


def is_group_initialized(group_name: str = "default") -> bool:
    return group_name in _groups


def get_rank(group_name: str = "default") -> int:
    g = _groups.get(group_name)
    return g.rank if g else -1


def get_collective_group_size(group_name: str = "default") -> int:
    g = _groups.get(group_name)
    return g.world_size if g else -1


def init_collective_group(world_size: int, rank: int,
                          backend: str = "shm",
                          group_name: str = "default") -> None:
    """Register this process as ``rank`` of ``group_name`` and block until
    all ``world_size`` ranks have registered."""
    if group_name in _groups:
        raise RuntimeError(f"collective group {group_name!r} already "
                           "initialized in this process")
    b = Backend.coerce(backend)
    if b != Backend.SHM:
        raise ValueError(
            "per-rank groups use the 'shm' backend; the 'xla' backend is a "
            "single-process device group (util.collective.xla_group)")
    g = ShmCollectiveGroup(world_size, rank, group_name)
    meta = pickle.dumps({"world_size": world_size, "backend": b.value})
    g._kv_put(f"{group_name}/meta/{rank}", meta)
    # Block until the whole group is present (reference init semantics).
    deadline = time.monotonic() + 120.0
    poll = _POLL_MIN
    while True:
        keys = g._kv_count(f"{group_name}/meta/")
        if len(keys) >= world_size:
            break
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"group {group_name}: only {len(keys)}/{world_size} ranks "
                "registered")
        time.sleep(poll)
        poll = min(poll * 2, _POLL_MAX)
    _groups[group_name] = g


def _init_in_actor(_instance, world_size: int, rank: int, backend: str,
                   group_name: str) -> None:
    init_collective_group(world_size, rank, backend, group_name)


def create_collective_group(actors: Sequence[Any], world_size: Optional[int] = None,
                            ranks: Optional[Sequence[int]] = None,
                            backend: str = "shm",
                            group_name: str = "default") -> None:
    """Driver-side: install a collective group across ``actors``.

    Each actor becomes one rank (``ranks`` defaults to positional order).
    Reference: ``create_collective_group`` declared the group and the NCCL
    communicator was lazily built; here init runs eagerly in every actor via
    ``__ray_apply__`` and this call blocks until rendezvous completes.
    """
    import ray_tpu
    world_size = world_size or len(actors)
    ranks = list(ranks) if ranks is not None else list(range(len(actors)))
    if len(actors) != len(ranks):
        raise ValueError("actors and ranks length mismatch")
    refs = [a.__ray_apply__.remote(_init_in_actor, world_size, r, backend,
                                   group_name)
            for a, r in zip(actors, ranks)]
    ray_tpu.get(refs)


def destroy_collective_group(group_name: str = "default") -> None:
    """Tear down THIS rank's state only (reference semantics) — deleting
    other ranks' keys would break peers mid-collective."""
    g = _groups.pop(group_name, None)
    if g is None:
        return
    # drop aliases pointing at the same group
    for k, v in list(_groups.items()):
        if v is g:
            del _groups[k]
    import re
    # exactly this rank's phase keys (<group>/<seq>/<phase>/<rank>), its
    # meta key, and p2p keys it SENT (<group>/p2p/<rank>-<dst>/<seq>) —
    # never keys whose trailing seq number merely equals the rank
    pat = re.compile(
        rf"^{re.escape(g.group_name)}/(\d+/[a-z]+/{g.rank}"
        rf"|meta/{g.rank}|p2p/{g.rank}-\d+/\d+)$")
    for k in g._kv_count(f"{g.group_name}/"):
        if pat.match(k):
            g._kv_del(k)
    g.destroy()


def _group(group_name: str) -> ShmCollectiveGroup:
    g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} is not initialized in this "
            "process (call init_collective_group / create_collective_group)")
    return g


# ------------------------------------------------------------------ ops API
def allreduce(tensor: Any, group_name: str = "default",
              op: ReduceOp = ReduceOp.SUM) -> Any:
    return _group(group_name).allreduce(tensor, ReduceOp.coerce(op))


def reduce(tensor: Any, dst_rank: int = 0, group_name: str = "default",
           op: ReduceOp = ReduceOp.SUM) -> Any:
    return _group(group_name).reduce(tensor, dst_rank, ReduceOp.coerce(op))


def broadcast(tensor: Any, src_rank: int = 0,
              group_name: str = "default") -> Any:
    return _group(group_name).broadcast(tensor, src_rank)


def allgather(tensor: Any, group_name: str = "default") -> List[Any]:
    return _group(group_name).allgather(tensor)


def reducescatter(tensor_list: Sequence[Any], group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM) -> Any:
    return _group(group_name).reducescatter(tensor_list, ReduceOp.coerce(op))


def alltoall(tensor_list: Sequence[Any],
             group_name: str = "default") -> List[Any]:
    return _group(group_name).alltoall(tensor_list)


def barrier(group_name: str = "default") -> None:
    _group(group_name).barrier()


def send(tensor: Any, dst_rank: int, group_name: str = "default") -> None:
    _group(group_name).send(tensor, dst_rank)


def recv(src_rank: int, group_name: str = "default") -> Any:
    return _group(group_name).recv(src_rank)
