"""Object-plane collective backend (the GLOO analog).

Reference: ``python/ray/util/collective/collective_group/gloo_collective_group.py``
— CPU collectives among arbitrary actors/processes.  Here tensors move
through the shared-memory object store (zero-copy segments) and rendezvous
rides the GCS KV (reference rendezvous: a named actor storing NCCL unique
ids; SURVEY.md §2.4 says replace that with GCS KV).

Synchronization model: every rank calls the same sequence of collectives in
the same order (the standard NCCL/GLOO contract).  Each call gets a
monotonically increasing sequence number; rank r publishes its contribution
under ``<group>/<seq>/<phase>/<r>`` and polls for the others.  Keys and
tensor objects from seq s-2 are reclaimed on entering seq s — safe because
entering seq s requires every rank to have *published* at s-1, which
requires every rank to have fully *read* s-2.

Small payloads (≤ ``INLINE_LIMIT``) are inlined into KV values; large
tensors go through the object store and only the object id travels via KV.
"""

from __future__ import annotations

import pickle
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ray_tpu._private import worker as _worker_mod
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu.experimental import internal_kv
from ray_tpu.util.collective.types import ReduceOp

NAMESPACE = "collective"
INLINE_LIMIT = 64 * 1024
_POLL_MIN, _POLL_MAX = 0.0002, 0.005


def _reduce_arrays(arrays: List[np.ndarray], op: ReduceOp) -> np.ndarray:
    out = np.array(arrays[0], copy=True)
    for a in arrays[1:]:
        if op == ReduceOp.SUM:
            out += a
        elif op == ReduceOp.PRODUCT:
            out *= a
        elif op == ReduceOp.MIN:
            np.minimum(out, a, out=out)
        else:
            np.maximum(out, a, out=out)
    return out


def _to_numpy(tensor: Any) -> np.ndarray:
    return np.asarray(tensor)


def _like(result: np.ndarray, template: Any) -> Any:
    """Return ``result`` in the array namespace of ``template``."""
    if type(template).__module__.startswith("jax"):
        import jax.numpy as jnp
        return jnp.asarray(result)
    return result


class ShmCollectiveGroup:
    def __init__(self, world_size: int, rank: int, group_name: str):
        if not (0 <= rank < world_size):
            raise ValueError(f"rank {rank} outside world of {world_size}")
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self._seq = 0
        self._p2p_send: Dict[int, int] = {}
        self._p2p_recv: Dict[int, int] = {}
        # refs published at seq s, released at s+2 (see module docstring)
        self._pinned: Dict[int, List[ObjectRef]] = {}
        # p2p refs can't use the epoch rule (recv timing is unknown); a
        # send's ref stays pinned until the matching recv deletes its key.
        self._p2p_pinned: List[tuple] = []  # (key, ref)

    # ------------------------------------------------------------------ kv
    @property
    def _w(self):
        return _worker_mod.global_worker()

    def _key(self, seq: int, phase: str, rank: int) -> str:
        return f"{self.group_name}/{seq}/{phase}/{rank}"

    def _kv_put(self, key: str, value: bytes) -> None:
        internal_kv._internal_kv_put(key, value, namespace=NAMESPACE)

    def _kv_get(self, key: str) -> Optional[bytes]:
        return internal_kv._internal_kv_get(key, namespace=NAMESPACE)

    def _kv_del(self, key: str) -> None:
        internal_kv._internal_kv_del(key, namespace=NAMESPACE)

    def _kv_count(self, prefix: str) -> List[str]:
        return internal_kv._internal_kv_list(prefix, namespace=NAMESPACE)

    # -------------------------------------------------------------- framing
    def _publish(self, seq: int, phase: str, tensor: Any) -> None:
        payload = pickle.dumps(tensor, protocol=5)
        if len(payload) <= INLINE_LIMIT:
            blob = b"I" + payload
        else:
            ref = self._w.put(tensor)
            self._pinned.setdefault(seq, []).append(ref)
            blob = b"R" + ref.hex().encode()
        self._kv_put(self._key(seq, phase, self.rank), blob)

    def _fetch(self, blob: bytes) -> Any:
        if blob[:1] == b"I":
            return pickle.loads(blob[1:])
        ref = ObjectRef(blob[1:].decode(), self._w, skip_release=True)
        return self._w.get_one(ref)

    def _await_keys(self, seq: int, phase: str, ranks: Sequence[int],
                    timeout: float) -> Dict[int, bytes]:
        want = {self._key(seq, phase, r): r for r in ranks}
        prefix = f"{self.group_name}/{seq}/{phase}/"
        deadline = time.monotonic() + timeout
        poll = _POLL_MIN
        while True:
            have = set(self._kv_count(prefix))
            if all(k in have for k in want):
                return {r: self._kv_get(k) for k, r in want.items()}
            if time.monotonic() > deadline:
                missing = [r for k, r in want.items() if k not in have]
                raise TimeoutError(
                    f"collective {self.group_name} seq={seq} phase={phase}: "
                    f"rank {self.rank} timed out waiting for ranks {missing}")
            time.sleep(poll)
            poll = min(poll * 2, _POLL_MAX)

    def _collect(self, seq: int, phase: str, ranks: Sequence[int],
                 timeout: float) -> Dict[int, Any]:
        blobs = self._await_keys(seq, phase, ranks, timeout)
        return {r: self._fetch(b) for r, b in blobs.items()}

    def _next_seq(self) -> int:
        self._seq += 1
        stale = self._seq - 2
        if stale in self._pinned:
            del self._pinned[stale]
        if stale >= 0:
            for phase in ("t", "b"):
                self._kv_del(self._key(stale, phase, self.rank))
        return self._seq

    # ---------------------------------------------------------------- ops
    def _ranks(self) -> List[int]:
        return list(range(self.world_size))

    def barrier(self, timeout: float = 60.0) -> None:
        seq = self._next_seq()
        self._kv_put(self._key(seq, "b", self.rank), b"")
        self._await_keys(seq, "b", self._ranks(), timeout)

    # Above this size the ring algorithm wins: the naive all-gather moves
    # N·S bytes per rank (every rank reads every contribution) while the
    # ring moves 2·S·(N-1)/N ≈ 2·S — the NCCL bus-bandwidth shape
    # (reference: nccl_collective_group ring semantics, SURVEY.md §2.4).
    # Below it, the 2(N-1) sequential KV hops cost more than the traffic.
    RING_THRESHOLD = 4 * 1024 * 1024

    def allreduce(self, tensor: Any, op: ReduceOp = ReduceOp.SUM,
                  timeout: float = 60.0) -> Any:
        arr = _to_numpy(tensor)
        if arr.nbytes >= self.RING_THRESHOLD and self.world_size > 2:
            return _like(self._allreduce_ring(arr, op, timeout), tensor)
        seq = self._next_seq()
        self._publish(seq, "t", arr)
        parts = self._collect(seq, "t", self._ranks(), timeout)
        out = _reduce_arrays([parts[r] for r in self._ranks()], op)
        return _like(out, tensor)

    def _allreduce_ring(self, arr: np.ndarray, op: ReduceOp,
                        timeout: float) -> np.ndarray:
        """Chunked ring allreduce: reduce-scatter then all-gather, each
        N-1 p2p hops of S/N-byte chunks through the object plane (chunks
        ride the slab/shm segments; only ids travel via KV).  Per-rank
        traffic is ~2·S instead of the naive N·S, so bus bandwidth holds
        flat as S grows instead of collapsing (VERDICT r2 missing #3)."""
        N, r = self.world_size, self.rank
        flat = np.ascontiguousarray(arr).reshape(-1)
        chunks = np.array_split(flat, N)
        acc: List[np.ndarray] = [np.array(c, copy=True) for c in chunks]
        right = (r + 1) % N
        left = (r - 1) % N
        # reduce-scatter: after N-1 hops, rank r holds the full reduction
        # of chunk (r+1) % N
        idx = r
        for _ in range(N - 1):
            self.send(acc[idx], right, timeout)
            idx = (idx - 1) % N
            incoming = self.recv(left, timeout)
            if op == ReduceOp.SUM:
                acc[idx] += incoming
            elif op == ReduceOp.PRODUCT:
                acc[idx] *= incoming
            elif op == ReduceOp.MIN:
                np.minimum(acc[idx], incoming, out=acc[idx])
            else:
                np.maximum(acc[idx], incoming, out=acc[idx])
        # all-gather: circulate the reduced chunks N-1 hops
        idx = (r + 1) % N
        for _ in range(N - 1):
            self.send(acc[idx], right, timeout)
            idx = (idx - 1) % N
            acc[idx] = self.recv(left, timeout)
        out = np.concatenate(acc)
        return out.reshape(arr.shape).astype(arr.dtype, copy=False)

    def _ack_barrier(self, seq: int, timeout: float) -> None:
        """Full all-rank ack: entering seq s+2 (which reclaims seq-s keys)
        then provably implies every rank finished seq s.  Required for ops
        where the main phase does not already collect from all ranks
        (broadcast, reduce) — see module docstring invariant."""
        self._kv_put(self._key(seq, "b", self.rank), b"")
        self._await_keys(seq, "b", self._ranks(), timeout)

    def reduce(self, tensor: Any, dst_rank: int = 0,
               op: ReduceOp = ReduceOp.SUM, timeout: float = 60.0) -> Any:
        seq = self._next_seq()
        self._publish(seq, "t", _to_numpy(tensor))
        out = tensor
        if self.rank == dst_rank:
            parts = self._collect(seq, "t", self._ranks(), timeout)
            out = _like(_reduce_arrays([parts[r] for r in self._ranks()], op),
                        tensor)
        self._ack_barrier(seq, timeout)
        return out

    def broadcast(self, tensor: Any, src_rank: int = 0,
                  timeout: float = 60.0) -> Any:
        seq = self._next_seq()
        if self.rank == src_rank:
            self._publish(seq, "t", _to_numpy(tensor))
            out = tensor
        else:
            parts = self._collect(seq, "t", [src_rank], timeout)
            out = _like(parts[src_rank], tensor)
        self._ack_barrier(seq, timeout)
        return out

    def allgather(self, tensor: Any, timeout: float = 60.0) -> List[Any]:
        seq = self._next_seq()
        self._publish(seq, "t", _to_numpy(tensor))
        parts = self._collect(seq, "t", self._ranks(), timeout)
        return [_like(parts[r], tensor) for r in self._ranks()]

    def reducescatter(self, tensor_list: Sequence[Any],
                      op: ReduceOp = ReduceOp.SUM,
                      timeout: float = 60.0) -> Any:
        if len(tensor_list) != self.world_size:
            raise ValueError("reducescatter needs world_size input tensors")
        seq = self._next_seq()
        self._publish(seq, "t", [_to_numpy(t) for t in tensor_list])
        parts = self._collect(seq, "t", self._ranks(), timeout)
        mine = [parts[r][self.rank] for r in self._ranks()]
        return _like(_reduce_arrays(mine, op), tensor_list[self.rank])

    def alltoall(self, tensor_list: Sequence[Any],
                 timeout: float = 60.0) -> List[Any]:
        """Rank r receives tensor_list[r] from every rank (Ulysses building
        block over the object plane; the in-mesh path is compiled)."""
        if len(tensor_list) != self.world_size:
            raise ValueError("alltoall needs world_size input tensors")
        seq = self._next_seq()
        self._publish(seq, "t", [_to_numpy(t) for t in tensor_list])
        parts = self._collect(seq, "t", self._ranks(), timeout)
        return [_like(parts[r][self.rank], tensor_list[0])
                for r in self._ranks()]

    def send(self, tensor: Any, dst_rank: int, timeout: float = 60.0) -> None:
        seq = self._p2p_send.get(dst_rank, 0) + 1
        self._p2p_send[dst_rank] = seq
        key = f"{self.group_name}/p2p/{self.rank}-{dst_rank}/{seq}"
        payload = pickle.dumps(_to_numpy(tensor), protocol=5)
        if len(payload) <= INLINE_LIMIT:
            self._kv_put(key, b"I" + payload)
        else:
            ref = self._w.put(_to_numpy(tensor))
            # lazily unpin completed sends (recv deletes the key on read)
            self._p2p_pinned = [
                (k, r) for k, r in self._p2p_pinned
                if self._kv_get(k) is not None]
            self._p2p_pinned.append((key, ref))
            self._kv_put(key, b"R" + ref.hex().encode())

    def recv(self, src_rank: int, timeout: float = 60.0) -> Any:
        seq = self._p2p_recv.get(src_rank, 0) + 1
        self._p2p_recv[src_rank] = seq
        key = f"{self.group_name}/p2p/{src_rank}-{self.rank}/{seq}"
        deadline = time.monotonic() + timeout
        poll = _POLL_MIN
        while True:
            blob = self._kv_get(key)
            if blob is not None:
                self._kv_del(key)
                return self._fetch(blob)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"recv from rank {src_rank} timed out ({key})")
            time.sleep(poll)
            poll = min(poll * 2, _POLL_MAX)

    def destroy(self) -> None:
        self._pinned.clear()
        self._p2p_pinned.clear()
