"""In-mesh collective backend: compiled XLA collectives over ICI.

Reference: ``python/ray/util/collective/collective_group/nccl_collective_group.py``
— but per SURVEY.md §5.8 the TPU-native inversion is that intra-slice
collectives are *compiled into the program*, not runtime library calls.
This group therefore lives inside ONE process that owns N local devices
(a TPU host owns its chips under single-controller JAX); each op is a
jitted ``shard_map`` collective over a 1-D mesh of those devices, executed
over ICI.  This is the path the ``allreduce bus bandwidth`` baseline
(BASELINE.md #6) measures.

Data layout convention: ops accept either
- an array whose leading axis is the device axis (shape ``(n_dev, ...)``),
  sharded or not — it is sharded over the mesh on entry; or
- a list of ``n_dev`` per-device arrays (stacked for you).
Results come back with the same leading device axis.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.util.collective.types import ReduceOp

AXIS = "col"

_REDUCERS = {
    ReduceOp.SUM: jax.lax.psum,
    ReduceOp.MAX: jax.lax.pmax,
    ReduceOp.MIN: jax.lax.pmin,
}


class XlaCollectiveGroup:
    """A device-set collective group with compiled ops (cached per shape)."""

    def __init__(self, devices: Optional[Sequence[Any]] = None,
                 group_name: str = "default"):
        devs = list(devices if devices is not None else jax.devices())
        self.group_name = group_name
        self.mesh = Mesh(np.asarray(devs), (AXIS,))
        self.world_size = len(devs)

    # ------------------------------------------------------------- helpers
    def _stack(self, tensor: Any) -> jax.Array:
        if isinstance(tensor, (list, tuple)):
            tensor = jnp.stack([jnp.asarray(t) for t in tensor])
        tensor = jnp.asarray(tensor)
        if tensor.shape[0] != self.world_size:
            raise ValueError(
                f"leading axis {tensor.shape[0]} != group size {self.world_size}")
        sharding = NamedSharding(self.mesh, P(AXIS))
        return jax.device_put(tensor, sharding)

    @functools.lru_cache(maxsize=64)
    def _compiled(self, kind: str, op: ReduceOp, shape: tuple, dtype: Any):
        mesh = self.mesh
        spec = P(AXIS)

        # Per-device block always has leading axis 1 (global leading axis is
        # the device axis, sharded over the mesh); bodies return leading
        # axis 1 so out_specs=P(AXIS) reassembles the device axis.
        if kind == "allreduce":
            def body(x):
                return _REDUCERS[op](x, AXIS)
        elif kind == "allgather":
            def body(x):  # x: (1, ...) → (1, world, ...)
                return jax.lax.all_gather(x[0], AXIS, tiled=False)[None]
        elif kind == "reducescatter":
            def body(x):  # x: (1, world, ...) → (1, ...)
                return jax.lax.psum_scatter(x[0], AXIS, scatter_dimension=0,
                                            tiled=False)[None]
        elif kind == "alltoall":
            def body(x):  # x: (1, world, ...) → (1, world, ...) transposed
                return jax.lax.all_to_all(x[0], AXIS, split_axis=0,
                                          concat_axis=0, tiled=False)[None]
        else:
            raise ValueError(kind)

        from ray_tpu._private.jax_compat import shard_map
        fn = shard_map(body, mesh=mesh, in_specs=(spec,), out_specs=spec)
        return jax.jit(fn)

    # ----------------------------------------------------------------- ops
    def allreduce(self, tensor: Any, op: ReduceOp = ReduceOp.SUM) -> jax.Array:
        """All-reduce over the device axis; result replicated per device
        (leading axis preserved: out[i] == reduce(in[:, ...]) for all i)."""
        op = ReduceOp.coerce(op)
        if op == ReduceOp.PRODUCT:
            raise NotImplementedError(
                "PRODUCT allreduce is not compiled; use SUM/MIN/MAX "
                "(reference NCCL supports prod; add on demand)")
        x = self._stack(tensor)
        fn = self._compiled("allreduce", op, x.shape, x.dtype)
        return fn(x)

    def allgather(self, tensor: Any) -> jax.Array:
        """Per-device rows gathered: out shape (world, world, ...)."""
        x = self._stack(tensor)
        fn = self._compiled("allgather", ReduceOp.SUM, x.shape, x.dtype)
        return fn(x)

    def reducescatter(self, tensor: Any, op: ReduceOp = ReduceOp.SUM) -> jax.Array:
        """In: (world, world, ...) — row i is device i's contribution list.
        Out: (world, ...) — device i holds sum_j in[j, i]."""
        x = self._stack(tensor)
        fn = self._compiled("reducescatter", ReduceOp.coerce(op), x.shape,
                            x.dtype)
        return fn(x)

    def alltoall(self, tensor: Any) -> jax.Array:
        """In: (world, world, ...); out[i, j] = in[j, i] (transpose over
        devices — the EP/Ulysses dispatch primitive)."""
        x = self._stack(tensor)
        fn = self._compiled("alltoall", ReduceOp.SUM, x.shape, x.dtype)
        return fn(x)

    def barrier(self) -> None:
        # A collective that must complete on all devices.
        jax.block_until_ready(
            self.allreduce(jnp.zeros((self.world_size, 1), jnp.int32)))

    def destroy(self) -> None:
        self._compiled.cache_clear()


# `functools.lru_cache` on a method holds self; acceptable here (groups are
# long-lived and destroy() clears), but make hashing identity-based:
XlaCollectiveGroup.__hash__ = object.__hash__
XlaCollectiveGroup.__eq__ = object.__eq__
