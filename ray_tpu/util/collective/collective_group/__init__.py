"""Collective backends (reference: ``python/ray/util/collective/collective_group/``)."""
