"""Collective op/backend types.

Reference: ``python/ray/util/collective/types.py`` — ``ReduceOp`` and
backend identifiers (the reference's backends are NCCL and GLOO; ours are
the object-plane ``shm`` backend and the in-mesh ``xla`` backend,
SURVEY.md §2.4/§5.8).
"""

from __future__ import annotations

import enum


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"

    @staticmethod
    def coerce(op: "ReduceOp | str") -> "ReduceOp":
        return op if isinstance(op, ReduceOp) else ReduceOp(str(op).lower())


class Backend(str, enum.Enum):
    """Collective transport.

    SHM — object-plane backend: tensors move through the shared-memory
    object store, rendezvous via GCS KV.  Works for any set of actors or
    processes (the GLOO analog).
    XLA — in-mesh backend: the group is a set of local devices and ops are
    compiled ``shard_map`` collectives over ICI (the NCCL analog — except
    collectives are *compiled into the program*, not runtime library calls).
    """

    SHM = "shm"
    XLA = "xla"
    # Reference-compatible aliases accepted by init_collective_group.
    GLOO = "gloo"
    NCCL = "nccl"

    @staticmethod
    def coerce(b: "Backend | str") -> "Backend":
        b = Backend(str(b).lower()) if not isinstance(b, Backend) else b
        if b == Backend.GLOO:
            return Backend.SHM
        if b == Backend.NCCL:
            return Backend.XLA
        return b
