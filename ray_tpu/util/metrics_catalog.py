"""Catalog of every built-in ``rtpu_*`` metric.

One declaration per built-in series (name, kind, tags, buckets, emitting
process) so the worker, GCS, Serve, and Train layers share definitions
instead of re-declaring strings — the same role ``ray_config_def.h``
plays for flags.  Layers obtain instances through :func:`get`, which is a
registry hit on the warm path (thanks to ``Metric`` merge-on-reregister)
and re-creates the instance after a test registry reset.

``tools/check_metrics_catalog.py`` (wired into ``make lint``) statically
verifies that every ``Counter(``/``Gauge(``/``Histogram(`` instantiation
of an ``rtpu_*`` name in the tree — and every ``mcat.get(...)`` call —
names an entry declared here, so the catalog stays honest as layers grow.

One documented exception: the ``rtpu_native_store_*`` gauge family is
synthesized at collect time from whatever stats the C++ slab store's
shared header exposes (``SlabStore.stats()`` keys — hits/misses/allocs/
fails/used/...), so its exact member names live in native code, not
here, and the static check cannot cover them.

README.md § Observability renders this catalog for operators.
"""

from __future__ import annotations

from typing import Dict

from ray_tpu.util import metrics as _metrics

# Latency buckets biased toward the sub-second range where task dispatch
# and serve requests live, with a long tail for slow train steps.
LATENCY_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# Microsecond-scale buckets for control-plane handler CPU (a hot-kind
# handler at its floor runs in tens of µs; the ms range is the
# contention tail we watch for).
HOT_HANDLER_BUCKETS = (0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                       0.005, 0.01, 0.05, 0.25, 1.0)

# name -> {kind, description, tag_keys, buckets?, emitted_by}
# ``emitted_by`` is documentation: which process's registry carries the
# series (collect_cluster adds the disambiguating ``worker`` tag).
CATALOG: Dict[str, dict] = {
    # --- core task lifecycle ------------------------------------------------
    "rtpu_task_queue_seconds": dict(
        kind="histogram", tag_keys=("name",), buckets=LATENCY_BUCKETS,
        description="Time a task spec waited in the scheduler queue "
                    "(submit/retry enqueue -> dispatch to a worker)",
        emitted_by="head (GCS)"),
    "rtpu_task_exec_seconds": dict(
        kind="histogram", tag_keys=("name",), buckets=LATENCY_BUCKETS,
        description="Task / actor-method body execution time on the worker "
                    "(arg unpack through result store)",
        emitted_by="worker"),
    "rtpu_tasks_total": dict(
        kind="counter", tag_keys=("state",),
        description="Tasks reaching a terminal state "
                    "(ok | app_error | sys_error | dep_error | cancelled)",
        emitted_by="head (GCS)"),
    "rtpu_object_store_put_bytes": dict(
        kind="counter", tag_keys=(),
        description="Serialized bytes written to the object store by "
                    "ray_tpu.put() in this process",
        emitted_by="every worker/driver"),
    "rtpu_object_store_get_bytes": dict(
        kind="counter", tag_keys=(),
        description="Serialized bytes materialized from the object store "
                    "by ray_tpu.get() in this process",
        emitted_by="every worker/driver"),
    "rtpu_actor_restarts_total": dict(
        kind="counter", tag_keys=("class",),
        description="Actor restarts triggered by worker death "
                    "(max_restarts budget consumed)",
        emitted_by="head (GCS)"),
    # --- control-plane fast path (GCS hot kinds) ----------------------------
    "rtpu_gcs_hot_handler_seconds": dict(
        kind="histogram", tag_keys=("kind",), buckets=HOT_HANDLER_BUCKETS,
        description="GCS hot-kind handler time (get_meta_fast = lock-free "
                    "sealed read; get_meta_scan = slow-path scan; "
                    "submit_batch / task_done / actor_result / put_object "
                    "= apply under the global lock; ref_drain = one "
                    "coalesced refcount batch)",
        emitted_by="head (GCS)"),
    "rtpu_gcs_lock_wait_seconds": dict(
        kind="gauge", tag_keys=("lock",),
        description="Last observed wait to acquire a GCS lock on an "
                    "instrumented hot path (contention probe, not a "
                    "cumulative meter)",
        emitted_by="head (GCS)"),
    "rtpu_gcs_ref_ops_total": dict(
        kind="counter", tag_keys=("path",),
        description="Refcount-plane ops applied, by path: 'coalesced' = "
                    "batched per-connection drain (one lock acquisition "
                    "per batch), 'inline' = per-call handler (in-process "
                    "short circuit / direct RPC)",
        emitted_by="head (GCS)"),
    # --- raylet lease plane (raylet.py / gcs.py, DESIGN.md §4i) -------------
    "rtpu_raylet_leases_total": dict(
        kind="counter", tag_keys=("event",),
        description="Worker-lease ledger events: 'granted' (specs shipped "
                    "to a raylet in lease_grant blocks), 'done' (settled "
                    "by raylet_done_batch), 'handoff' (lease inherited by "
                    "a queued same-shape task with no head round-trip), "
                    "'returned' (unstarted leases handed back), "
                    "'reclaimed' (raylet death/detach reclaim)",
        emitted_by="head (GCS)"),
    "rtpu_raylet_ref_ops_total": dict(
        kind="counter", tag_keys=("path",),
        description="Owner-local refcount releases applied through raylet "
                    "reconciliation ('reconciled' = netted worker releases "
                    "shipped in raylet_ref_batch frames)",
        emitted_by="head (GCS)"),
    "rtpu_raylet_queue_depth": dict(
        kind="gauge", tag_keys=("node",),
        description="Local scheduler queue depth per raylet node "
                    "(granted-but-undispatched leases; from "
                    "raylet_heartbeat)",
        emitted_by="head (GCS)"),
    "rtpu_raylet_reconcile_age_seconds": dict(
        kind="gauge", tag_keys=("node",),
        description="Seconds since a raylet last reconciled its netted "
                    "refcount deltas to the GCS ledger (from "
                    "raylet_heartbeat)",
        emitted_by="head (GCS)"),
    # --- P2P object plane (data_plane.py) -----------------------------------
    "rtpu_data_pull_seconds": dict(
        kind="histogram", tag_keys=("path",), buckets=LATENCY_BUCKETS,
        description="End-to-end peer-object pull time: 'direct' = "
                    "streamed/chunked pull from the holder's data plane "
                    "(pooled conns), 'relay' = head pull-through fallback "
                    "for unreachable holders",
        emitted_by="every puller (worker/driver/head)"),
    "rtpu_data_bytes_total": dict(
        kind="counter", tag_keys=("dir",),
        description="Data-plane bulk bytes moved by this process: "
                    "'in' = pulled from peers, 'out' = served from the "
                    "local spool",
        emitted_by="pullers ('in') and data-plane servers ('out')"),
    "rtpu_data_pool_conns": dict(
        kind="gauge", tag_keys=(),
        description="Open data-plane connections held by this process's "
                    "connection pool (idle + checked out)",
        emitted_by="every process with a DataPlanePool"),
    # --- serve data plane ---------------------------------------------------
    # ``group`` label convention: cross-layer series that belong to one
    # logical workload stamp its name as ``group`` — train series use the
    # elastic training-group name, serve/LLM series use the deployment
    # key (stamped at the proxy/handle call sites and, for the engine's
    # rtpu_llm_* family, via per-replica-process ``set_default_tags``).
    # One selector ({group="X"}) then follows a workload across every
    # layer, and group-aware detectors (straggler cohorts) never mix
    # concurrent workloads.
    "rtpu_serve_requests_total": dict(
        kind="counter", tag_keys=("deployment", "code", "group"),
        description="HTTP requests completed by the Serve proxy, by "
                    "deployment key and status code",
        emitted_by="serve proxy"),
    "rtpu_serve_errors_total": dict(
        kind="counter", tag_keys=("deployment", "group"),
        description="Serve requests that ended in a 5xx response",
        emitted_by="serve proxy"),
    "rtpu_serve_request_latency_seconds": dict(
        kind="histogram", tag_keys=("deployment", "group"),
        buckets=LATENCY_BUCKETS,
        description="End-to-end Serve request latency at the proxy "
                    "(replica assignment + execution; time-to-first-byte "
                    "for streaming responses)",
        emitted_by="serve proxy"),
    "rtpu_serve_replica_queue_depth": dict(
        kind="gauge", tag_keys=("deployment", "group"),
        description="Requests held in a router's assign() waiting for a "
                    "free replica (max_ongoing_requests backpressure)",
        emitted_by="every process with a router (proxy/driver)"),
    "rtpu_serve_ongoing_requests": dict(
        kind="gauge", tag_keys=("deployment", "replica", "group"),
        description="Requests currently executing inside a replica",
        emitted_by="serve replica"),
    "rtpu_serve_autoscaler_desired_replicas": dict(
        kind="gauge", tag_keys=("deployment", "group"),
        description="Autoscaler target replica count after the current "
                    "decision tick (equals num_replicas when autoscaling "
                    "is off)",
        emitted_by="serve controller"),
    # --- serve.llm continuous-batching engine -------------------------------
    "rtpu_llm_sequences": dict(
        kind="gauge", tag_keys=("model", "state", "group"),
        description="Sequences inside an LLM engine by state "
                    "(running = in the decode batch, waiting = queued "
                    "for prefill admission, incl. preempted)",
        emitted_by="llm replica"),
    "rtpu_llm_kv_blocks": dict(
        kind="gauge", tag_keys=("model", "state", "group"),
        description="Paged KV cache blocks by state (used | free) in "
                    "an engine's shm block pool",
        emitted_by="llm replica"),
    "rtpu_llm_batch_occupancy": dict(
        kind="gauge", tag_keys=("model", "group"),
        description="Decode batch occupancy: running sequences / "
                    "max_num_seqs after the last scheduler iteration",
        emitted_by="llm replica"),
    "rtpu_llm_preemptions_total": dict(
        kind="counter", tag_keys=("model", "group"),
        description="Sequences evicted under KV cache pressure "
                    "(blocks freed, re-prefilled later)",
        emitted_by="llm replica"),
    "rtpu_llm_ttft_seconds": dict(
        kind="histogram", tag_keys=("model", "group"), buckets=LATENCY_BUCKETS,
        description="Time to first token: request submission to the "
                    "first sampled token (queueing + prefill)",
        emitted_by="llm replica"),
    "rtpu_llm_tpot_seconds": dict(
        kind="histogram", tag_keys=("model", "group"), buckets=LATENCY_BUCKETS,
        description="Time per output token after the first (decode "
                    "cadence), observed once per finished sequence",
        emitted_by="llm replica"),
    "rtpu_llm_tokens_total": dict(
        kind="counter", tag_keys=("model", "phase", "group"),
        description="Tokens processed by an LLM engine: 'prefill' = "
                    "prompt tokens prefilled, 'decode' = tokens "
                    "generated by decode iterations",
        emitted_by="llm replica"),
    # --- head TSDB / anomaly detection (DESIGN.md §4k) ----------------------
    "rtpu_tsdb_series": dict(
        kind="gauge", tag_keys=(),
        description="Time series held by the head-resident metrics "
                    "TSDB (bounded by tsdb_max_series)",
        emitted_by="head (GCS)"),
    "rtpu_tsdb_samples_total": dict(
        kind="counter", tag_keys=(),
        description="Samples ingested into the head TSDB from "
                    "__metrics__/ snapshot receipts",
        emitted_by="head (GCS)"),
    # --- continuous profiling / incident capture (DESIGN.md §4o) ------------
    "rtpu_profile_samples_total": dict(
        kind="counter", tag_keys=(),
        description="Stack samples taken by this process's always-on "
                    "sampling profiler and shipped to the head in "
                    "__profile__/ deltas",
        emitted_by="every non-client process (profiler_enabled)"),
    "rtpu_profile_stacks": dict(
        kind="gauge", tag_keys=(),
        description="Distinct folded stacks in the last published "
                    "profile delta (bounded by profiler_max_stacks; an "
                    "'(overflow)' bucket absorbs the tail)",
        emitted_by="every non-client process (profiler_enabled)"),
    "rtpu_profile_publish_seconds": dict(
        kind="histogram", tag_keys=(), buckets=HOT_HANDLER_BUCKETS,
        description="Wall time to fold + serialize + ship one profile "
                    "delta on the metrics-publisher cadence (the "
                    "profiler's own overhead meter)",
        emitted_by="every non-client process (profiler_enabled)"),
    "rtpu_incidents_total": dict(
        kind="counter", tag_keys=("kind",),
        description="Post-mortem incident bundles captured by the head "
                    "on anomaly events (straggler | slo_burn), after "
                    "incident_dedup_s dedup — each bundle lands in "
                    "<session>/incidents/<id>/",
        emitted_by="head (GCS)"),
    # --- GCS replication / head fault tolerance (DESIGN.md §4l) -------------
    "rtpu_gcs_wal_records_total": dict(
        kind="counter", tag_keys=(),
        description="Durable ledger mutations appended to the GCS "
                    "write-ahead log (fsynced in drain batches, "
                    "streamed to attached warm standbys)",
        emitted_by="head (GCS)"),
    "rtpu_gcs_repl_standbys": dict(
        kind="gauge", tag_keys=(),
        description="Warm standby heads currently attached to the "
                    "replication stream (0 = a head failure falls back "
                    "to snapshot+WAL restart over the session dir)",
        emitted_by="head (GCS)"),
    "rtpu_anomaly_events_total": dict(
        kind="counter", tag_keys=("kind",),
        description="Anomalies emitted into the fleet-event feed by the "
                    "always-on detectors ('straggler' = per-rank train "
                    "step-time skew vs the group median; 'slo_burn' = "
                    "multi-window SLO error-budget burn)",
        emitted_by="head (GCS)"),
    # --- request tracing / flight recorder ----------------------------------
    "rtpu_trace_spans_total": dict(
        kind="counter", tag_keys=("cat",),
        description="Timeline span events emitted by this process, by "
                    "category (span | task | actor_task | sched | data | "
                    "llm | serve | device)",
        emitted_by="every traced process"),
    "rtpu_trace_sampled_total": dict(
        kind="counter", tag_keys=("decision",),
        description="Head-based sampling decisions at auto-rooted "
                    "request traces (sampled | dropped) — explicit "
                    "tracing.trace() roots are always sampled and not "
                    "counted here",
        emitted_by="request-root processes (serve proxy)"),
    "rtpu_trace_flight_records_total": dict(
        kind="counter", tag_keys=(),
        description="Flight-recorder ring records written by this "
                    "process (amortized count; the ring itself is "
                    "fixed-size and overwrites in place)",
        emitted_by="every process with a flight recorder"),
    # --- train --------------------------------------------------------------
    # --- fleet elasticity (DESIGN.md §4j) -----------------------------------
    "rtpu_elastic_node_draining_total": dict(
        kind="counter", tag_keys=("reason",),
        description="Provider-initiated preemption warnings received "
                    "(node_draining events marking a node unschedulable)",
        emitted_by="head (GCS)"),
    "rtpu_elastic_remesh_total": dict(
        kind="counter", tag_keys=("action",),
        description="Elastic train-group transitions driven by the "
                    "elasticity manager (remesh = survivors re-form "
                    "without a cold start; restart = full-group cold "
                    "start from the last gathered state; join = a "
                    "restored slice attached to the running group)",
        emitted_by="driver (elasticity manager)"),
    "rtpu_elastic_remesh_seconds": dict(
        kind="histogram", tag_keys=("action",), buckets=LATENCY_BUCKETS,
        description="Quiesce -> resume wall time of one elastic "
                    "transition (training paused, processes alive)",
        emitted_by="driver (elasticity manager)"),
    "rtpu_elastic_generation": dict(
        kind="gauge", tag_keys=("group",),
        description="Current mesh generation of an elastic train group "
                    "(bumps on every re-mesh/restart/join)",
        emitted_by="driver (elasticity manager)"),
    "rtpu_elastic_goodput_steps_per_s": dict(
        kind="gauge", tag_keys=("group",),
        description="Useful (first-time) train steps per wall-second "
                    "across the run so far, re-runs excluded",
        emitted_by="driver (elasticity manager)"),
    "rtpu_autoscaler_demand_backlog": dict(
        kind="gauge", tag_keys=(),
        description="Unfulfilled resource shapes (tasks + PG bundles) "
                    "seen by the last autoscaler reconcile pass",
        emitted_by="driver (autoscaler)"),
    "rtpu_autoscaler_nodes": dict(
        kind="gauge", tag_keys=("phase",),
        description="Provider nodes by lifecycle phase (pending / "
                    "running / draining) at the last reconcile pass",
        emitted_by="driver (autoscaler)"),
    "rtpu_autoscaler_decisions_total": dict(
        kind="counter", tag_keys=("action",),
        description="Autoscaler reconcile decisions (launch | terminate)",
        emitted_by="driver (autoscaler)"),
    "rtpu_autoscaler_forecast_slots": dict(
        kind="gauge", tag_keys=(),
        description="Lead-time demand floor the autopilot's diurnal "
                    "forecast is currently feeding the autoscaler "
                    "(extra shapes packed ahead of the measured "
                    "backlog; DESIGN.md §4n)",
        emitted_by="driver (autoscaler)"),
    "rtpu_autopilot_actions_total": dict(
        kind="counter", tag_keys=("kind", "outcome"),
        description="Autopilot remediation actions (kind: drain | "
                    "undrain | prewarm | forecast | standby_launch; "
                    "outcome: applied | skipped | error) — every "
                    "reflex firing, including the ones the rate "
                    "limits and vetoes suppressed (DESIGN.md §4n)",
        emitted_by="head (GCS)"),
    "rtpu_train_step_seconds": dict(
        kind="histogram", tag_keys=("rank", "group"),
        buckets=LATENCY_BUCKETS,
        description="Wall time between consecutive train.report() calls "
                    "on a training worker (one reported step).  Elastic "
                    "worker loops additionally stamp their training "
                    "group — the straggler detector cohorts its median "
                    "by this tag so concurrent jobs never read each "
                    "other as sick",
        emitted_by="train worker"),
    "rtpu_train_throughput_steps_per_s": dict(
        kind="gauge", tag_keys=("rank",),
        description="Instantaneous training throughput (1 / last step "
                    "duration) per worker rank",
        emitted_by="train worker"),
    "rtpu_train_mfu": dict(
        kind="gauge", tag_keys=("rank",),
        description="Model-FLOPs utilization reported by the training "
                    "loop (train.report key 'mfu'): model FLOP/s over "
                    "the chip's peak — the overlap-scheduled step's "
                    "headline number, fleet-visible via ray_tpu top",
        emitted_by="train worker"),
    "rtpu_train_overlap_exposed_ms": dict(
        kind="gauge", tag_keys=("rank",),
        description="Exposed (compute-unhidden) collective ms per train "
                    "step reported by the training loop (train.report "
                    "key 'overlap_exposed_ms', from bench-style device-"
                    "trace accounting) — the number the decomposed "
                    "collective matmuls drive toward zero",
        emitted_by="train worker"),
    # --- synthesized at collect time (documented here; no instantiation) ----
    "rtpu_device_hbm_bytes_in_use": dict(
        kind="gauge", tag_keys=("device", "kind"),
        description="HBM bytes currently allocated (PJRT memory_stats)",
        emitted_by="driver collect (device_memory_gauges)"),
    "rtpu_device_hbm_peak_bytes": dict(
        kind="gauge", tag_keys=("device", "kind"),
        description="Peak HBM bytes allocated (PJRT memory_stats)",
        emitted_by="driver collect (device_memory_gauges)"),
    "rtpu_device_hbm_bytes_limit": dict(
        kind="gauge", tag_keys=("device", "kind"),
        description="HBM allocator capacity (PJRT memory_stats)",
        emitted_by="driver collect (device_memory_gauges)"),
}


# --------------------------------------------------------------- SLO rules
# Burn-rate alerting rules over the latency histograms above, consumed
# by ``tsdb.SloBurnAlerter`` (always-on, ticked by the GCS monitor
# loop).  Declared HERE — next to the series they reference — so the
# rtlint metrics pass (``metric-slo-rule``) can statically prove every
# rule names a live cataloged histogram whose bucket ladder covers the
# threshold; a rule over a dead or re-bucketed series fails the build,
# not the 3am page.
#
# Shape: windows = ((long_s, short_s, burn_factor), ...) — an alert
# fires when the error-budget burn rate (fraction of observations
# slower than threshold_s, divided by 1 - objective) exceeds
# burn_factor on BOTH windows (long filters blips, short proves the
# burn is still live).  Factors follow the SRE-workbook ladder: 14.4x
# on the fast page window (budget gone in ~2h at that rate).
SLO_RULES: tuple = (
    dict(name="llm_ttft", series="rtpu_llm_ttft_seconds",
         threshold_s=2.5, objective=0.99,
         windows=((3600.0, 300.0, 14.4), (21600.0, 1800.0, 6.0))),
    dict(name="llm_tpot", series="rtpu_llm_tpot_seconds",
         threshold_s=0.25, objective=0.99,
         windows=((3600.0, 300.0, 14.4), (21600.0, 1800.0, 6.0))),
    dict(name="serve_latency", series="rtpu_serve_request_latency_seconds",
         threshold_s=1.0, objective=0.999,
         windows=((3600.0, 300.0, 14.4),)),
)


# resolved-instance cache: get() runs on hot paths (inside the GCS
# scheduler lock, per Serve request) — the warm path must be two dict
# lookups, not a _REGISTRY_LOCK acquisition.  Invalidated by registry
# generation (bumped in metrics._reset_for_tests); races are benign
# (worst case one redundant rebuild that merges into the same instance).
_CACHE: Dict[str, "_metrics.Metric"] = {}
_CACHE_GEN = [-1]


def get(name: str) -> "_metrics.Metric":
    """The shared instance of a cataloged built-in metric.

    Warm path = a local cache hit (no shared lock); after
    ``_reset_for_tests()`` the generation bump drops the cache and the
    next call re-registers a fresh instance from the catalog spec."""
    gen = _metrics._REGISTRY_GEN[0]
    if gen != _CACHE_GEN[0]:
        _CACHE.clear()
        _CACHE_GEN[0] = gen
    inst = _CACHE.get(name)
    if inst is not None:
        return inst
    try:
        spec = CATALOG[name]
    except KeyError:
        raise KeyError(
            f"{name!r} is not a cataloged built-in metric — declare it in "
            f"ray_tpu/util/metrics_catalog.py") from None
    kind = spec["kind"]
    if kind == "counter":
        inst = _metrics.Counter(name, spec["description"],
                                spec.get("tag_keys", ()))
    elif kind == "gauge":
        inst = _metrics.Gauge(name, spec["description"],
                              spec.get("tag_keys", ()))
    else:
        inst = _metrics.Histogram(
            name, spec["description"],
            spec.get("buckets", _metrics.DEFAULT_BUCKETS),
            spec.get("tag_keys", ()))
    _CACHE[name] = inst
    return inst
