"""Cross-process trace assembly: timeline events → one causal tree.

``ray_tpu.timeline()`` returns every process's Chrome-trace events in one
flat list; sampled spans carry ``args.{trace_id, span_id, parent_id}``
(util/tracing.py).  This module filters one trace out of the dump,
re-links the spans into a tree — driver root → GCS dispatch → worker
exec → data-plane pulls → Serve/LLM engine iterations — and renders it
as text or as a Chrome/Perfetto-loadable trace (device rows captured
under the same trace, ``profile_device``, ride along: they share the
span's ids).

CLI: ``ray_tpu trace <trace_id> [-o out.json]``.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def trace_events(events: List[dict], trace_id: str) -> List[dict]:
    """Events belonging to one trace (spans + device rows tagged with
    the trace's span args), ts-ordered.  Metadata (``ph:"M"``) events
    for rows that appear in the trace are kept so named thread rows
    survive the filter."""
    rows = set()
    out = []
    for e in events:
        args = e.get("args") or {}
        if args.get("trace_id") == trace_id:
            out.append(e)
            rows.add((e.get("pid"), e.get("tid")))
    meta = [e for e in events if e.get("ph") == "M"
            and (e.get("pid"), e.get("tid")) in rows]
    out.sort(key=lambda e: e.get("ts") or 0)
    return meta + out


class SpanNode:
    __slots__ = ("span_id", "events", "children")

    def __init__(self, span_id: str):
        self.span_id = span_id
        self.events: List[dict] = []
        self.children: List["SpanNode"] = []

    @property
    def primary(self) -> dict:
        """The span's own completed event (device rows tagged with the
        same ids are secondaries)."""
        for e in self.events:
            if e.get("cat") != "device":
                return e
        return self.events[0] if self.events else {}

    @property
    def name(self) -> str:
        return self.primary.get("name", "?")

    @property
    def parent_id(self) -> Optional[str]:
        return (self.primary.get("args") or {}).get("parent_id")


def build_tree(events: List[dict], trace_id: str) -> List[SpanNode]:
    """Assemble one trace's span tree; returns the root nodes (a
    well-formed trace has exactly one).  Spans whose parent never
    surfaced (e.g. sampled-out half, lost process) become roots — the
    tree degrades instead of dropping them."""
    nodes: Dict[str, SpanNode] = {}
    for e in trace_events(events, trace_id):
        if e.get("ph") == "M":
            continue
        sid = (e.get("args") or {}).get("span_id")
        if not sid:
            continue
        nodes.setdefault(sid, SpanNode(sid)).events.append(e)
    roots: List[SpanNode] = []
    for node in nodes.values():
        pid = node.parent_id
        if pid and pid in nodes:
            nodes[pid].children.append(node)
        else:
            roots.append(node)
    for node in nodes.values():
        node.children.sort(key=lambda n: n.primary.get("ts") or 0)
    roots.sort(key=lambda n: n.primary.get("ts") or 0)
    return roots


def render_tree(roots: List[SpanNode]) -> str:
    """Indented text rendering of an assembled trace tree."""
    lines: List[str] = []

    def walk(node: SpanNode, depth: int) -> None:
        ev = node.primary
        dur = ev.get("dur")
        extra = ""
        args = ev.get("args") or {}
        for k in ("bytes", "tokens", "batch", "path", "task_id"):
            if k in args:
                extra += f" {k}={args[k]}"
        dev = sum(1 for e in node.events if e.get("cat") == "device")
        if dev:
            extra += f" device_events={dev}"
        lines.append(
            f"{'  ' * depth}{node.name}  "
            f"[{ev.get('cat', '?')}@{ev.get('pid', '?')}]"
            f"{f'  {dur / 1e3:.2f}ms' if dur is not None else ''}{extra}")
        for c in node.children:
            walk(c, depth + 1)

    for r in roots:
        walk(r, 0)
    return "\n".join(lines)


def to_chrome(events: List[dict], trace_id: str) -> dict:
    """Chrome/Perfetto ``traceEvents`` document for one trace (host
    spans + device rows merged — load in chrome://tracing / ui.perfetto
    directly)."""
    return {"traceEvents": trace_events(events, trace_id),
            "displayTimeUnit": "ms",
            "metadata": {"trace_id": trace_id}}


def trace_ids(events: List[dict]) -> List[str]:
    """Distinct trace ids present in a timeline dump, most recent
    activity first — `ray_tpu trace` with no id lists these."""
    last: Dict[str, float] = {}
    for e in events:
        tid = (e.get("args") or {}).get("trace_id")
        if tid:
            last[tid] = max(last.get(tid, 0.0), e.get("ts") or 0.0)
    return [t for t, _ in sorted(last.items(), key=lambda kv: -kv[1])]
