"""Placement groups — public API.

Reference: ``python/ray/util/placement_group.py`` + GCS PG manager
(SURVEY.md §2.1, §2.4).  TPU extension: a bundle may be written as
``{"TPU": 4}`` (chips on one host) or via :func:`tpu_slice_bundles` which
expands a pod-slice topology (e.g. ``"v4-32"``) into per-host bundles plus
the STRICT_PACK-over-ICI-domain constraint the scheduler understands.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private import worker as _worker
from ray_tpu._private.ids import PlacementGroupID


class PlacementGroup:
    def __init__(self, pg_id: str, bundles: List[Dict[str, float]], strategy: str):
        self.id = pg_id
        self.bundle_specs = bundles
        self.strategy = strategy

    def ready(self):
        """Returns an ObjectRef-like waitable; get() blocks until scheduled."""
        return _PgReady(self)

    def wait(self, timeout_seconds: Optional[float] = None) -> bool:
        w = _worker.global_worker()
        resp = w.rpc("pg_wait", pg_id=self.id, timeout=timeout_seconds)
        return resp["ready"]

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundle_specs, self.strategy))


class _PgReady:
    """Duck-typed ref so ``ray_tpu.get(pg.ready())`` works like the reference."""

    def __init__(self, pg: PlacementGroup):
        self.pg = pg

    def __ray_get__(self, timeout: Optional[float] = None) -> PlacementGroup:
        if not self.pg.wait(timeout_seconds=timeout):
            from ray_tpu.exceptions import GetTimeoutError
            raise GetTimeoutError(f"placement group {self.pg.id} not ready")
        return self.pg


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None) -> PlacementGroup:
    if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        raise ValueError(f"unknown strategy {strategy!r}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be non-empty dicts")
    w = _worker.global_worker()
    pg_id = PlacementGroupID.new()
    w.rpc("pg_create", pg_id=pg_id, bundles=[dict(b) for b in bundles],
          strategy=strategy, name=name)
    return PlacementGroup(pg_id, bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    _worker.global_worker().rpc("pg_remove", pg_id=pg.id)


def placement_group_table() -> dict:
    return _worker.global_worker().rpc("pg_table")["pgs"]


def tpu_slice_bundles(topology: str) -> List[Dict[str, float]]:
    """Expand a TPU pod-slice topology into per-host bundles.

    ``v4-32`` → 4 hosts × 4 chips, etc.  Use with STRICT_PACK so all hosts
    land in one ICI domain (multi-host slice atomicity, SURVEY.md §2.4).
    """
    from ray_tpu.parallel.topology import slice_spec
    spec = slice_spec(topology)
    return [{"TPU": float(spec.chips_per_host)} for _ in range(spec.num_hosts)]
