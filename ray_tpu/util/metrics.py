"""Application-level metrics: Counter / Gauge / Histogram with tags.

Reference: ``ray.util.metrics`` (``python/ray/util/metrics.py``; SURVEY.md
§5.5) — user code registers metrics that flow to each node's metrics agent
and out a Prometheus endpoint.  Here the registry lives in-process and
publishes snapshots into the GCS KV (``__metrics__/<worker>``) so the driver
— or the dashboard-lite HTTP endpoint — can aggregate cluster-wide without a
sidecar agent; ``prometheus_text()`` renders the standard exposition format.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

_REGISTRY_LOCK = threading.Lock()
_REGISTRY: Dict[str, "Metric"] = {}
# bumped on registry reset so caches of resolved instances (the catalog's
# warm path) know to drop stale references
_REGISTRY_GEN = [0]

# The GCS KV prefix under which every process's publisher writes its
# snapshot.  One spelling, shared by the publisher, the collector, and
# the GCS's persistence/sweep exemptions (gcs.py).
METRICS_KV_PREFIX = "__metrics__/"


def is_metrics_key(key) -> bool:
    """Is this KV key an ephemeral metrics snapshot?  (keys may be str
    or bytes depending on the caller)"""
    if isinstance(key, bytes):
        return key.startswith(METRICS_KV_PREFIX.encode())
    return isinstance(key, str) and key.startswith(METRICS_KV_PREFIX)

DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   50.0, 100.0)

# Per-metric series-cardinality cap.  Tag values can be user-controlled
# (task names via .options(name=...), deployment keys): without a bound,
# a driver submitting uniquely-named tasks grows the registry — and the
# publisher's per-cycle kv_put payload — forever.  The tagset that would
# exceed the cap folds into one {"overflow": "true"} series so totals
# stay correct even when labels saturate.
MAX_SERIES_PER_METRIC = 1000
_OVERFLOW_KEY = (("overflow", "true"),)


def _tag_key(tags: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((tags or {}).items()))


class Metric:
    """Base: named metric with default tags and per-tagset series.

    Same-name same-kind construction returns THE registered instance
    (series merge) instead of silently replacing the registry entry —
    two modules declaring the same counter share one series, and the
    catalog accessor (``metrics_catalog.get``) is a cheap registry hit
    on the warm path.  Same name with a different kind still raises."""

    kind = "untyped"
    # class-level fallbacks: a registered-but-not-yet-__init__'d instance
    # (another thread won the __new__ race a moment ago) must already be
    # safe to snapshot/update
    description = ""
    tag_keys: Tuple[str, ...] = ()

    def __new__(cls, name: str, *args: Any, **kwargs: Any) -> "Metric":
        if not name or not name.replace("_", "a").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        with _REGISTRY_LOCK:
            existing = _REGISTRY.get(name)
            if existing is not None:
                if existing.kind != cls.kind:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}")
                return existing
            inst = super().__new__(cls)
            # essential state under the registry lock: the instance is
            # visible to other threads the moment it lands in _REGISTRY
            inst.name = name
            inst._default_tags = {}
            inst._lock = threading.Lock()
            inst._series = {}
            _REGISTRY[name] = inst
        return inst

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        if getattr(self, "_initialized", False):
            return  # merged into the already-registered instance
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._initialized = True

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _resolve_tags(self, tags: Optional[Dict[str, str]]):
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return _tag_key(merged)

    def _admit_key(self, k):
        """Lock held.  Cardinality gate: an unseen tagset beyond the cap
        folds into the shared overflow series instead of growing the
        registry (and every publish payload) without bound."""
        if k in self._series or len(self._series) < MAX_SERIES_PER_METRIC:
            return k
        return _OVERFLOW_KEY

    def remove_series(self, tags: Optional[Dict[str, str]] = None) -> bool:
        """Drop one tagset's series — called when the tagged entity (a
        deployment, a replica) is deleted, so a long-lived process stops
        republishing its last value forever.  Returns True if present."""
        with self._lock:
            return self._series.pop(self._resolve_tags(tags), None) is not None

    # -- snapshot / exposition ----------------------------------------------
    def snapshot(self) -> List[dict]:
        with self._lock:
            return [{"tags": dict(k), "value": self._render(v)}
                    for k, v in self._series.items()]

    def _render(self, v):
        return v


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only go up")
        k = self._resolve_tags(tags)
        with self._lock:
            k = self._admit_key(k)
            self._series[k] = self._series.get(k, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        k = self._resolve_tags(tags)
        with self._lock:
            self._series[self._admit_key(k)] = float(value)


class Histogram(Metric):
    kind = "histogram"
    boundaries = tuple(DEFAULT_BUCKETS)  # pre-__init__ visibility (see base)

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = DEFAULT_BUCKETS,
                 tag_keys: Sequence[str] = ()):
        if getattr(self, "_initialized", False):
            return  # merged: the first registration's boundaries stand
        self.boundaries = tuple(sorted(boundaries))
        super().__init__(name, description, tag_keys)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        k = self._resolve_tags(tags)
        with self._lock:
            k = self._admit_key(k)
            series = self._series.get(k)
            if series is None:
                series = {"counts": [0] * (len(self.boundaries) + 1),
                          "sum": 0.0, "count": 0}
                self._series[k] = series
            idx = bisect.bisect_left(self.boundaries, value)
            series["counts"][idx] += 1
            series["sum"] += value
            series["count"] += 1

    def _render(self, v):
        return {"buckets": dict(zip([str(b) for b in self.boundaries]
                                    + ["+Inf"], v["counts"])),
                "sum": v["sum"], "count": v["count"]}


# ---------------------------------------------------------------- exposition
def registry_snapshot() -> Dict[str, dict]:
    with _REGISTRY_LOCK:
        metrics = list(_REGISTRY.values())
    return {m.name: {"kind": m.kind, "description": m.description,
                     "series": m.snapshot()} for m in metrics}


def _esc_label(v: Any) -> str:
    """Escape a label value per the Prometheus text-format spec:
    backslash, double quote, and line feed would otherwise emit invalid
    exposition text (unparseable by any strict scraper)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _esc_help(v: str) -> str:
    """HELP text escaping: backslash and line feed (spec)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_tags(tags: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_esc_label(v)}"' for k, v in sorted(tags.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(snapshot: Optional[Dict[str, dict]] = None) -> str:
    """Render a snapshot in the Prometheus exposition format."""
    snap = snapshot if snapshot is not None else registry_snapshot()
    out: List[str] = []
    for name, m in sorted(snap.items()):
        if m["description"]:
            out.append(f"# HELP {name} {_esc_help(m['description'])}")
        out.append(f"# TYPE {name} {m['kind']}")
        for s in m["series"]:
            tags, v = s["tags"], s["value"]
            if m["kind"] == "histogram":
                acc = 0
                for b, c in v["buckets"].items():
                    acc += c
                    le = 'le="%s"' % b
                    out.append(f"{name}_bucket{_fmt_tags(tags, le)} {acc}")
                out.append(f"{name}_sum{_fmt_tags(tags)} {v['sum']}")
                out.append(f"{name}_count{_fmt_tags(tags)} {v['count']}")
            else:
                out.append(f"{name}{_fmt_tags(tags)} {v}")
    return "\n".join(out) + "\n"


# ------------------------------------------------------------- cluster push
def publish(worker=None) -> None:
    """Publish this process's metrics snapshot to the GCS KV."""
    import json

    from ray_tpu._private import worker as worker_mod
    w = worker or worker_mod.try_global_worker()
    if w is None:
        return
    # _reconnect=False: publishing is periodic best-effort — during a head
    # restart it must fail fast and let the owning threads heal the pool,
    # not fight them for it (the next cycle publishes to the healed head)
    w.rpc("kv_put", _reconnect=False,
          key=METRICS_KV_PREFIX + w.worker_id,
          value=json.dumps({"ts": time.time(),
                            "snapshot": registry_snapshot()}).encode())


# How long a DEAD publisher's final snapshot stays visible before the
# collector reaps it.  Short-lived processes (a train worker that ran a
# quick loop, a task worker that exited) flush once on clean shutdown —
# without a grace window their series would vanish the instant the worker
# died, i.e. exactly when an operator wants to read them.
DEAD_SNAPSHOT_GRACE_S = 120.0


def collect_cluster() -> Dict[str, dict]:
    """Merge every live process's published snapshot (driver-side).

    Each series gains a ``worker`` tag so identical name+tags from two
    processes stay distinct samples (duplicate labels are invalid
    Prometheus); dead workers' snapshots stay visible for
    ``DEAD_SNAPSHOT_GRACE_S`` after their last publish (the shutdown
    flush), then are reaped.  (Reader-side aging uses the payload's
    publisher wall clock — adequate for the common single-host driver;
    the GCS's own sweep ages by head receipt time and is the
    authoritative skew-proof bound.)

    One ``kv_mget`` round trip fetches every publisher's snapshot —
    scrape cost does not grow a head RPC per worker.
    """
    import json

    from ray_tpu._private import worker as worker_mod
    w = worker_mod.global_worker()
    live = {wk["worker_id"] for wk in w.rpc("list_workers")["workers"]
            if wk["state"] != "dead"}
    entries = w.rpc("kv_mget", prefix=METRICS_KV_PREFIX)["entries"]
    merged: Dict[str, dict] = {}
    now = time.time()
    for key, raw in sorted(entries.items()):
        wid = key.split("/", 1)[1]
        if not raw:
            if wid not in live:
                w.rpc("kv_del", key=key)  # dead publisher, empty payload
            continue
        try:
            payload = json.loads(raw)
            payload["snapshot"]
        except Exception:  # noqa: BLE001 - one corrupt payload must not
            # take down the whole cluster scrape; reap it (a live
            # publisher rewrites its key next cycle anyway)
            w.rpc("kv_del", key=key)
            continue
        if wid not in live and \
                now - payload.get("ts", 0) > DEAD_SNAPSHOT_GRACE_S:
            w.rpc("kv_del", key=key)  # reap dead publishers' stale snapshots
            continue
        snap = payload["snapshot"]
        for name, m in snap.items():
            dst = merged.setdefault(name, {"kind": m["kind"],
                                           "description": m["description"],
                                           "series": []})
            for s in m["series"]:
                dst["series"].append(
                    {"tags": {**s["tags"], "worker": wid},
                     "value": s["value"]})
    # native slab-store counters (reference: src/ray/stats/ metrics in the
    # plasma/raylet process — SURVEY.md §2.1 Stats row): the C++ store
    # keeps hits/misses/allocs/fails in its shared header; surface them as
    # first-class gauges so `ray_tpu metrics` / Prometheus see the native
    # data plane, not just Python-side registries.
    # (the slab is per-HOST shared state — one series tagged with the
    # collecting node, not one per worker; remote agent hosts use spools,
    # not slabs, so this meters the head-host store)
    slab = w.slab
    if slab is not None:
        try:
            for name, val in slab.stats().items():
                merged[f"rtpu_native_store_{name}"] = {
                    "kind": "gauge",
                    "description": f"native slab store {name} (head host)",
                    "series": [{"tags": {"node": str(w.node_id)[:8]},
                                "value": float(val)}]}
        except Exception:  # noqa: BLE001 - store detached mid-collect
            pass
    merged.update(device_memory_gauges())
    return merged


def device_memory_gauges() -> Dict[str, dict]:
    """Per-chip HBM gauges from PJRT ``device.memory_stats()`` (SURVEY.md
    §5.5 rebuild note: per-chip HBM/duty-cycle on the dashboard).

    Best-effort by design: only reads devices when jax is ALREADY imported
    in this process (collecting metrics must never pay a backend init), and
    only platforms whose PJRT client implements memory_stats report.
    Documented platform gaps rather than silent ones:

    - the relay-attached ``axon`` platform returns ``None`` from
      memory_stats (no allocator stats over the relay), so on this rig the
      gauges appear only for locally-attached chips;
    - duty-cycle/TensorCore-utilization needs libtpu's gRPC metrics
      service (what ``tpu-info`` reads), which PJRT does not expose — no
      gauge is synthesized for it.
    """
    import sys as _sys
    jax_mod = _sys.modules.get("jax")
    if jax_mod is None:
        return {}
    try:
        # merely having jax imported is not enough: local_devices() on an
        # UNinitialized process triggers full PJRT backend init (seconds,
        # and on TPU a second-process libtpu init can hang or contend for
        # the trainer's chip).  Only read devices from a backend some
        # other code already paid for.
        if not jax_mod._src.xla_bridge._backends:
            return {}
    except AttributeError:  # internal layout moved: skip, never init
        return {}
    names = (("bytes_in_use", "rtpu_device_hbm_bytes_in_use",
              "HBM bytes currently allocated (PJRT memory_stats)"),
             ("peak_bytes_in_use", "rtpu_device_hbm_peak_bytes",
              "peak HBM bytes allocated (PJRT memory_stats)"),
             ("bytes_limit", "rtpu_device_hbm_bytes_limit",
              "HBM allocator capacity (PJRT memory_stats)"))
    out: Dict[str, dict] = {}
    try:
        for d in jax_mod.local_devices():
            if d.platform == "cpu":
                continue
            stats = d.memory_stats() or {}
            for key, mname, desc in names:
                if key not in stats:
                    continue
                dst = out.setdefault(mname, {"kind": "gauge",
                                             "description": desc,
                                             "series": []})
                dst["series"].append(
                    {"tags": {"device": str(getattr(d, "id", 0)),
                              "kind": getattr(d, "device_kind", d.platform)},
                     "value": float(stats[key])})
    except Exception:  # noqa: BLE001 - backend half-initialized/detached
        return out
    return out


def _reset_for_tests() -> None:
    with _REGISTRY_LOCK:
        _REGISTRY.clear()
        _REGISTRY_GEN[0] += 1
