"""Application-level metrics: Counter / Gauge / Histogram with tags.

Reference: ``ray.util.metrics`` (``python/ray/util/metrics.py``; SURVEY.md
§5.5) — user code registers metrics that flow to each node's metrics agent
and out a Prometheus endpoint.  Here the registry lives in-process and
publishes snapshots into the GCS KV (``__metrics__/<worker>``) so the driver
— or the dashboard-lite HTTP endpoint — can aggregate cluster-wide without a
sidecar agent; ``prometheus_text()`` renders the standard exposition format.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

_REGISTRY_LOCK = threading.Lock()
_REGISTRY: Dict[str, "Metric"] = {}

DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   50.0, 100.0)


def _tag_key(tags: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((tags or {}).items()))


class Metric:
    """Base: named metric with default tags and per-tagset series."""

    kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        if not name or not name.replace("_", "a").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._series: Dict[Tuple[Tuple[str, str], ...], Any] = {}
        with _REGISTRY_LOCK:
            existing = _REGISTRY.get(name)
            if existing is not None and existing.kind != self.kind:
                raise ValueError(
                    f"metric {name!r} already registered as {existing.kind}")
            _REGISTRY[name] = self

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _resolve_tags(self, tags: Optional[Dict[str, str]]):
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return _tag_key(merged)

    # -- snapshot / exposition ----------------------------------------------
    def snapshot(self) -> List[dict]:
        with self._lock:
            return [{"tags": dict(k), "value": self._render(v)}
                    for k, v in self._series.items()]

    def _render(self, v):
        return v


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only go up")
        k = self._resolve_tags(tags)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._series[self._resolve_tags(tags)] = float(value)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = DEFAULT_BUCKETS,
                 tag_keys: Sequence[str] = ()):
        self.boundaries = tuple(sorted(boundaries))
        super().__init__(name, description, tag_keys)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        k = self._resolve_tags(tags)
        with self._lock:
            series = self._series.get(k)
            if series is None:
                series = {"counts": [0] * (len(self.boundaries) + 1),
                          "sum": 0.0, "count": 0}
                self._series[k] = series
            idx = bisect.bisect_left(self.boundaries, value)
            series["counts"][idx] += 1
            series["sum"] += value
            series["count"] += 1

    def _render(self, v):
        return {"buckets": dict(zip([str(b) for b in self.boundaries]
                                    + ["+Inf"], v["counts"])),
                "sum": v["sum"], "count": v["count"]}


# ---------------------------------------------------------------- exposition
def registry_snapshot() -> Dict[str, dict]:
    with _REGISTRY_LOCK:
        metrics = list(_REGISTRY.values())
    return {m.name: {"kind": m.kind, "description": m.description,
                     "series": m.snapshot()} for m in metrics}


def _fmt_tags(tags: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in sorted(tags.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(snapshot: Optional[Dict[str, dict]] = None) -> str:
    """Render a snapshot in the Prometheus exposition format."""
    snap = snapshot if snapshot is not None else registry_snapshot()
    out: List[str] = []
    for name, m in sorted(snap.items()):
        if m["description"]:
            out.append(f"# HELP {name} {m['description']}")
        out.append(f"# TYPE {name} {m['kind']}")
        for s in m["series"]:
            tags, v = s["tags"], s["value"]
            if m["kind"] == "histogram":
                acc = 0
                for b, c in v["buckets"].items():
                    acc += c
                    out.append(f"{name}_bucket"
                               f"{_fmt_tags(tags, f'le=\"{b}\"')} {acc}")
                out.append(f"{name}_sum{_fmt_tags(tags)} {v['sum']}")
                out.append(f"{name}_count{_fmt_tags(tags)} {v['count']}")
            else:
                out.append(f"{name}{_fmt_tags(tags)} {v}")
    return "\n".join(out) + "\n"


# ------------------------------------------------------------- cluster push
def publish(worker=None) -> None:
    """Publish this process's metrics snapshot to the GCS KV."""
    import json

    from ray_tpu._private import worker as worker_mod
    w = worker or worker_mod.try_global_worker()
    if w is None:
        return
    w.rpc("kv_put", key=f"__metrics__/{w.worker_id}",
          value=json.dumps({"ts": time.time(),
                            "snapshot": registry_snapshot()}).encode())


def collect_cluster() -> Dict[str, dict]:
    """Merge every live process's published snapshot (driver-side).

    Each series gains a ``worker`` tag so identical name+tags from two
    processes stay distinct samples (duplicate labels are invalid
    Prometheus); snapshots from dead workers are skipped.
    """
    import json

    from ray_tpu._private import worker as worker_mod
    w = worker_mod.global_worker()
    live = {wk["worker_id"] for wk in w.rpc("list_workers")["workers"]
            if wk["state"] != "dead"}
    keys = w.rpc("kv_keys", prefix="__metrics__/")["keys"]
    merged: Dict[str, dict] = {}
    for key in keys:
        wid = key.split("/", 1)[1]
        if wid not in live:
            w.rpc("kv_del", key=key)  # reap dead publishers' snapshots
            continue
        raw = w.rpc("kv_get", key=key).get("value")
        if not raw:
            continue
        snap = json.loads(raw)["snapshot"]
        for name, m in snap.items():
            dst = merged.setdefault(name, {"kind": m["kind"],
                                           "description": m["description"],
                                           "series": []})
            for s in m["series"]:
                dst["series"].append(
                    {"tags": {**s["tags"], "worker": wid},
                     "value": s["value"]})
    # native slab-store counters (reference: src/ray/stats/ metrics in the
    # plasma/raylet process — SURVEY.md §2.1 Stats row): the C++ store
    # keeps hits/misses/allocs/fails in its shared header; surface them as
    # first-class gauges so `ray_tpu metrics` / Prometheus see the native
    # data plane, not just Python-side registries.
    # (the slab is per-HOST shared state — one series tagged with the
    # collecting node, not one per worker; remote agent hosts use spools,
    # not slabs, so this meters the head-host store)
    slab = w.slab
    if slab is not None:
        try:
            for name, val in slab.stats().items():
                merged[f"rtpu_native_store_{name}"] = {
                    "kind": "gauge",
                    "description": f"native slab store {name} (head host)",
                    "series": [{"tags": {"node": str(w.node_id)[:8]},
                                "value": float(val)}]}
        except Exception:  # noqa: BLE001 - store detached mid-collect
            pass
    merged.update(device_memory_gauges())
    return merged


def device_memory_gauges() -> Dict[str, dict]:
    """Per-chip HBM gauges from PJRT ``device.memory_stats()`` (SURVEY.md
    §5.5 rebuild note: per-chip HBM/duty-cycle on the dashboard).

    Best-effort by design: only reads devices when jax is ALREADY imported
    in this process (collecting metrics must never pay a backend init), and
    only platforms whose PJRT client implements memory_stats report.
    Documented platform gaps rather than silent ones:

    - the relay-attached ``axon`` platform returns ``None`` from
      memory_stats (no allocator stats over the relay), so on this rig the
      gauges appear only for locally-attached chips;
    - duty-cycle/TensorCore-utilization needs libtpu's gRPC metrics
      service (what ``tpu-info`` reads), which PJRT does not expose — no
      gauge is synthesized for it.
    """
    import sys as _sys
    jax_mod = _sys.modules.get("jax")
    if jax_mod is None:
        return {}
    try:
        # merely having jax imported is not enough: local_devices() on an
        # UNinitialized process triggers full PJRT backend init (seconds,
        # and on TPU a second-process libtpu init can hang or contend for
        # the trainer's chip).  Only read devices from a backend some
        # other code already paid for.
        if not jax_mod._src.xla_bridge._backends:
            return {}
    except AttributeError:  # internal layout moved: skip, never init
        return {}
    names = (("bytes_in_use", "rtpu_device_hbm_bytes_in_use",
              "HBM bytes currently allocated (PJRT memory_stats)"),
             ("peak_bytes_in_use", "rtpu_device_hbm_peak_bytes",
              "peak HBM bytes allocated (PJRT memory_stats)"),
             ("bytes_limit", "rtpu_device_hbm_bytes_limit",
              "HBM allocator capacity (PJRT memory_stats)"))
    out: Dict[str, dict] = {}
    try:
        for d in jax_mod.local_devices():
            if d.platform == "cpu":
                continue
            stats = d.memory_stats() or {}
            for key, mname, desc in names:
                if key not in stats:
                    continue
                dst = out.setdefault(mname, {"kind": "gauge",
                                             "description": desc,
                                             "series": []})
                dst["series"].append(
                    {"tags": {"device": str(getattr(d, "id", 0)),
                              "kind": getattr(d, "device_kind", d.platform)},
                     "value": float(stats[key])})
    except Exception:  # noqa: BLE001 - backend half-initialized/detached
        return out
    return out


def _reset_for_tests() -> None:
    with _REGISTRY_LOCK:
        _REGISTRY.clear()
