"""Distributed FIFO queue backed by an async actor.

Reference: ``python/ray/util/queue.py`` (SURVEY.md §2.3) — same API:
put/get (blocking w/ timeout), put_nowait/get_nowait, qsize/empty/full,
put_async/get_async, shutdown.

Every actor method is a coroutine, so all queue state lives on the actor's
event-loop thread (no cross-thread asyncio hazards) and a parked ``get``
holds no executor thread — the actor server replies from the loop when the
coroutine completes, so hundreds of blocked consumers cost nothing.
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote(max_concurrency=16)
class _QueueActor:
    def __init__(self, maxsize: int):
        self._q: asyncio.Queue = asyncio.Queue(maxsize)

    async def put(self, item: Any, timeout: Optional[float] = None) -> bool:
        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        try:
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def put_nowait(self, item: Any) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    # item-returning forms for put_async/get_async (block until done)
    async def put_item(self, item: Any) -> bool:
        await self._q.put(item)
        return True

    async def get_item(self) -> Any:
        return await self._q.get()

    async def qsize(self) -> int:
        return self._q.qsize()


class Queue:
    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict] = None):
        opts = dict(actor_options or {})
        self.maxsize = maxsize
        self.actor = _QueueActor.options(**opts).remote(maxsize) if opts \
            else _QueueActor.remote(maxsize)

    # -- blocking ------------------------------------------------------------
    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            return self.put_nowait(item)
        ok = ray_tpu.get(self.actor.put.remote(item, timeout))
        if not ok:
            raise Full("queue full")

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            return self.get_nowait()
        ok, item = ray_tpu.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty("queue empty")
        return item

    # -- non-blocking --------------------------------------------------------
    def put_nowait(self, item: Any) -> None:
        if not ray_tpu.get(self.actor.put_nowait.remote(item)):
            raise Full("queue full")

    def get_nowait(self) -> Any:
        ok, item = ray_tpu.get(self.actor.get_nowait.remote())
        if not ok:
            raise Empty("queue empty")
        return item

    # -- async refs (for use inside other actors/tasks) ----------------------
    def put_async(self, item: Any):
        """ObjectRef resolving to True once the item is enqueued."""
        return self.actor.put_item.remote(item)

    def get_async(self):
        """ObjectRef resolving to the dequeued ITEM (blocks until one)."""
        return self.actor.get_item.remote()

    # -- introspection -------------------------------------------------------
    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def shutdown(self) -> None:
        ray_tpu.kill(self.actor)
