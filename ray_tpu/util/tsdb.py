"""Head-resident, fixed-memory metrics time-series store (DESIGN.md §4k).

The metrics plane (§4b) publishes per-process registry snapshots into
the GCS KV (``__metrics__/<worker>``); until this module the head threw
each snapshot's history away on the next publish, so nothing could
answer "what was the task rate five minutes ago" or "which rank's step
time is drifting".  :class:`TSDB` is the Prometheus/Monarch-shaped layer
built on top of that existing receipt path — the GCS hands every
snapshot it already receives to :meth:`TSDB.ingest` (zero new RPCs; see
``gcs._h_kv_put``), and the store keeps a bounded ring of samples per
series behind a query engine (``rate()`` / ``increase()`` /
``*_over_time()`` / ``quantile_over_time()`` with label matchers)
exposed via the ``metrics_query`` GCS op, ``state.metrics_history()``,
the dashboard's ``/metrics/history`` endpoint, and ``ray_tpu top``.

Memory model (all bounds are fixed at construction):

- One :class:`Series` per (metric name, tagset incl. the publisher's
  ``worker`` tag).  Series count is bounded twice: per-metric by the
  §4b publisher-side cardinality cap, and globally by ``max_series``
  (beyond it new series are dropped and counted, never grown).
- Per series, a three-rung downsampling ladder of fixed-size rings:
  every received sample lands in the *raw* ring (one slot per publish,
  ~30min at the 5s default export period), and rolls up into the *mid*
  (30s resolution, ~4h) and *long* (300s resolution, ~48h) rings by
  last-sample-wins within a resolution bucket — correct for cumulative
  values (counters, histogram states) and honest for gauges (the rung
  you query tells you its resolution).  A query picks the finest rung
  that still covers the window's start.
- Counter and gauge samples are one float; histogram samples keep the
  full cumulative state ``(bucket counts, sum, count)`` so windowed
  quantiles and SLO burn rates come from *bucket deltas*, not guesses.

Timestamps are head receipt wall-clock (one clock for every series —
publisher clocks never skew a window), mirroring the §4b sweep's
receipt-time discipline.

Query syntax (the subset ``ray_tpu top`` and the detectors need)::

    rtpu_raylet_queue_depth                      latest value per series
    rtpu_tasks_total{state="ok"}                 label matchers (= != =~)
    rate(rtpu_tasks_total[60s])                  per-second increase
    increase(rtpu_llm_tokens_total{phase="decode"}[5m])
    avg_over_time(rtpu_llm_batch_occupancy[2m])  also min_/max_
    quantile_over_time(0.99, rtpu_llm_ttft_seconds[5m])
    sum(rate(rtpu_tasks_total[60s]))             whole-cluster scalars
    sum by (rank) (increase(rtpu_train_step_seconds[1m]))

On top of the store run two always-on detectors (driven by the GCS
monitor loop, results emitted into the §4j fleet-event feed and the
§4h flight recorder): :class:`StragglerDetector` (per-rank train step
time vs the group median over a sliding window) and
:class:`SloBurnAlerter` (multi-window error-budget burn rates over the
latency histograms named by ``metrics_catalog.SLO_RULES``).

Locking: one leaf lock (``TSDB_LOCK_DAG`` in lock_watchdog.py) guards
the series table and rings; queries copy sample lists out under it and
evaluate outside.  Never acquired together with any GCS lock — the GCS
calls in with none of its own locks held.
"""

from __future__ import annotations

import json
import re
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "TSDB", "Series", "StragglerDetector", "SloBurnAlerter",
    "QueryError", "parse_duration",
]

# Downsampling ladder: (resolution seconds, ring slots).  Rung 0 is the
# raw ring — one slot per received sample, resolution 0 meaning "as
# published".  Coverage at the 5s default export period: raw ~30min,
# mid 4h, long 48h.  DESIGN.md §4k discusses the sizing.
RAW_SLOTS_DEFAULT = 360
LADDER: Tuple[Tuple[float, int], ...] = ((30.0, 480), (300.0, 576))

# Hard ceiling on evaluation points per range query: the instant
# evaluation is pure Python on a GCS handler thread, so the step count
# — caller-controlled, possibly straight off a dashboard URL — must be
# bounded (a 60-point sparkline is the intended scale).
MAX_RANGE_STEPS = 2000

# A bare (windowless) selector answers with the newest sample no older
# than this — the §4b grace window, so a just-dead worker's final flush
# still reads as "current" exactly as long as the collector shows it.
STALENESS_S = 120.0

# Series that stop receiving samples are dropped once their newest
# sample ages past the longest rung's coverage — history survives the
# publisher by hours (the whole point), not forever (fixed memory).
IDLE_PRUNE_S = LADDER[-1][0] * LADDER[-1][1]


class QueryError(ValueError):
    """Malformed expression handed to :meth:`TSDB.query`."""


# --------------------------------------------------------------------- rings
class _Ring:
    """Fixed-capacity (ts, value) ring with last-wins resolution buckets.

    ``res == 0`` appends every sample (raw rung); ``res > 0`` overwrites
    the newest slot while the sample falls in the same ``ts // res``
    bucket (cumulative values downsample losslessly this way — the
    bucket keeps its final state)."""

    __slots__ = ("res", "cap", "_ts", "_val", "_n", "_head")

    def __init__(self, res: float, cap: int):
        self.res = res
        self.cap = cap
        self._ts: List[float] = [0.0] * cap
        self._val: List[Any] = [None] * cap
        self._n = 0          # filled slots
        self._head = 0       # next write index

    def add(self, ts: float, val: Any) -> None:
        if self.res > 0 and self._n:
            last_i = (self._head - 1) % self.cap
            if int(self._ts[last_i] // self.res) == int(ts // self.res):
                self._ts[last_i] = ts
                self._val[last_i] = val
                return
        self._ts[self._head] = ts
        self._val[self._head] = val
        self._head = (self._head + 1) % self.cap
        self._n = min(self._n + 1, self.cap)

    def oldest_ts(self) -> Optional[float]:
        if not self._n:
            return None
        return self._ts[(self._head - self._n) % self.cap]

    def newest_ts(self) -> Optional[float]:
        if not self._n:
            return None
        return self._ts[(self._head - 1) % self.cap]

    def samples(self, start: float, end: float) -> List[Tuple[float, Any]]:
        """(ts, value) pairs with start <= ts <= end, oldest first."""
        out: List[Tuple[float, Any]] = []
        base = (self._head - self._n) % self.cap
        for k in range(self._n):
            i = (base + k) % self.cap
            ts = self._ts[i]
            if start <= ts <= end:
                out.append((ts, self._val[i]))
        return out


class Series:
    """One (name, tagset) series: kind, boundaries, and its ring ladder."""

    __slots__ = ("name", "kind", "tags", "boundaries", "rings", "last_ts")

    def __init__(self, name: str, kind: str, tags: Dict[str, str],
                 boundaries: Optional[Tuple[str, ...]], raw_slots: int):
        self.name = name
        self.kind = kind
        self.tags = dict(tags)
        # histogram bucket upper bounds as published ("0.005"... "+Inf")
        self.boundaries = boundaries
        self.rings = [_Ring(0.0, raw_slots)] + \
            [_Ring(res, cap) for res, cap in LADDER]
        self.last_ts = 0.0

    def add(self, ts: float, val: Any) -> None:
        self.last_ts = ts
        for r in self.rings:
            r.add(ts, val)

    def window(self, start: float, end: float) -> List[Tuple[float, Any]]:
        """Samples over [start, end] from the finest rung covering start
        (falling back to coarser rungs when raw has already wrapped).
        When history is shorter than the window, every rung holds the
        full history — use the finest that reaches back furthest."""
        best = None
        best_oldest = None
        for r in self.rings:
            oldest = r.oldest_ts()
            if oldest is None:
                continue
            if oldest <= start:
                return r.samples(start, end)
            if best_oldest is None or oldest < best_oldest:
                best, best_oldest = r, oldest
        return best.samples(start, end) if best is not None else []


# --------------------------------------------------------------- expressions
_DUR_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s|m|h|d)$")
_DUR_UNIT = {"ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}


def parse_duration(text: str) -> float:
    m = _DUR_RE.match(text.strip())
    if not m:
        raise QueryError(f"bad duration {text!r} (want e.g. 30s, 5m, 1h)")
    return float(m.group(1)) * _DUR_UNIT[m.group(2)]


# the matcher block ends at the first '}' OUTSIDE a quoted value —
# =~ regexes legitimately contain braces ({n} quantifiers), so the
# block body admits quoted strings with any escaped content
_SELECTOR_RE = re.compile(
    r"^\s*(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r'(?:\{(?P<matchers>(?:[^}"]|"(?:[^"\\]|\\.)*")*)\})?'
    r"(?:\[(?P<window>[^\]]+)\])?\s*$")
_MATCHER_RE = re.compile(
    r'\s*([a-zA-Z_][a-zA-Z0-9_]*)\s*(=~|!=|=)\s*"((?:[^"\\]|\\.)*)"\s*')
_FUNC_RE = re.compile(
    r"^\s*(?P<fn>rate|increase|avg_over_time|min_over_time|max_over_time"
    r"|quantile_over_time)\s*\((?P<args>.*)\)\s*$", re.S)
_AGG_RE = re.compile(
    r"^\s*(?P<agg>sum|avg|max|min)\s*"
    r"(?:by\s*\(\s*(?P<by>[a-zA-Z0-9_,\s]*)\)\s*)?"
    r"\((?P<inner>.*)\)\s*$", re.S)

_OVER_TIME_FNS = ("avg_over_time", "min_over_time", "max_over_time")


class _Selector:
    def __init__(self, name: str, matchers: List[Tuple[str, str, str]],
                 window_s: Optional[float]):
        self.name = name
        self.matchers = matchers
        self.window_s = window_s

    def matches(self, tags: Dict[str, str]) -> bool:
        for key, op, val in self.matchers:
            got = tags.get(key, "")
            if op == "=" and got != val:
                return False
            if op == "!=" and got == val:
                return False
            if op == "=~" and re.fullmatch(val, got) is None:
                return False
        return True


def _parse_selector(text: str) -> _Selector:
    m = _SELECTOR_RE.match(text)
    if not m:
        raise QueryError(f"bad selector {text!r}")
    matchers: List[Tuple[str, str, str]] = []
    raw = m.group("matchers")
    if raw:
        pos = 0
        while pos < len(raw):
            mm = _MATCHER_RE.match(raw, pos)
            if not mm:
                raise QueryError(f"bad label matcher at {raw[pos:]!r}")
            val = mm.group(3).replace('\\"', '"').replace("\\\\", "\\")
            if mm.group(2) == "=~":
                # validate at parse time: a broken regex must be a
                # QueryError (the caller's 400), not a re.error at
                # match time that only fires once a series exists
                try:
                    re.compile(val)
                except re.error as exc:
                    raise QueryError(
                        f"bad =~ regex {val!r}: {exc}") from None
            matchers.append((mm.group(1), mm.group(2), val))
            pos = mm.end()
            if pos < len(raw):
                if raw[pos] != ",":
                    raise QueryError(f"expected ',' at {raw[pos:]!r}")
                pos += 1
    window = m.group("window")
    return _Selector(m.group("name"), matchers,
                     parse_duration(window) if window else None)


class _Expr:
    """Parsed query: optional aggregator over an optional function over
    one selector."""

    def __init__(self, fn: Optional[str], q: Optional[float],
                 sel: _Selector, agg: Optional[str],
                 by: Optional[Tuple[str, ...]]):
        self.fn = fn
        self.q = q
        self.sel = sel
        self.agg = agg
        self.by = by


def _parse_expr(text: str) -> _Expr:
    agg = by = None
    m = _AGG_RE.match(text)
    if m and m.group("inner").count("(") == m.group("inner").count(")"):
        agg = m.group("agg")
        if m.group("by") is not None:
            by = tuple(p.strip() for p in m.group("by").split(",")
                       if p.strip())
        text = m.group("inner")
    fn = q = None
    m = _FUNC_RE.match(text)
    if m:
        fn = m.group("fn")
        args = m.group("args").strip()
        if fn == "quantile_over_time":
            if "," not in args:
                raise QueryError("quantile_over_time(q, selector[window])")
            q_text, args = args.split(",", 1)
            try:
                q = float(q_text)
            except ValueError:
                raise QueryError(f"bad quantile {q_text!r}") from None
            if not 0.0 <= q <= 1.0:
                raise QueryError(f"quantile {q} outside [0, 1]")
        text = args
    sel = _parse_selector(text)
    if fn is not None and sel.window_s is None:
        raise QueryError(f"{fn}() needs a [window] on its selector")
    if fn is None and sel.window_s is not None:
        raise QueryError("a bare selector takes no [window] "
                         "(wrap it in rate()/increase()/*_over_time())")
    return _Expr(fn, q, sel, agg, by)


# ------------------------------------------------------------ sample algebra
def _scalar_of(kind: str, val: Any) -> float:
    """Instant value of one sample (histograms read as their count)."""
    if kind == "histogram":
        return float(val[2])
    return float(val)


def _counter_delta(first: float, rest: Iterable[float]) -> float:
    """Increase over a sample run with reset detection: a drop means
    the publisher restarted — each monotone run contributes its own
    growth (the post-reset value counts from zero)."""
    total = 0.0
    prev = first
    for v in rest:
        if v < prev:
            total += prev - first
            first = 0.0 if v >= 0 else v
        prev = v
    return total + (prev - first)


def _hist_delta(first, last) -> Tuple[List[float], float, float]:
    """Bucket-wise increase of a cumulative histogram state; a count
    reset restarts the window from zero (the post-reset state IS the
    increase since the reset)."""
    fc, fs, fn = first
    lc, ls, ln = last
    if ln < fn or len(lc) != len(fc):
        return list(lc), float(ls), float(ln)
    return [lc[i] - fc[i] for i in range(len(lc))], ls - fs, ln - fn


def _bucket_quantile(q: float, boundaries: Tuple[str, ...],
                     counts: List[float]) -> Optional[float]:
    """Prometheus-style histogram_quantile over per-bucket increases.

    ``boundaries`` are the finite upper bounds as strings (the "+Inf"
    bucket is counts[-1]); linear interpolation inside the hit bucket,
    with the +Inf bucket clamping to the highest finite bound."""
    total = sum(counts)
    if total <= 0:
        return None
    bounds = [float(b) for b in boundaries]
    target = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        cum += c
        if cum >= target and c > 0:
            if i >= len(bounds):              # +Inf bucket
                return bounds[-1] if bounds else None
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            return lo + (hi - lo) * (target - (cum - c)) / c
    return bounds[-1] if bounds else None


def _empirical_quantile(q: float, values: List[float]) -> float:
    """Gauge-sample quantile: sorted values, linear interpolation at
    rank ``q * (n - 1)``."""
    vs = sorted(values)
    if len(vs) == 1:
        return vs[0]
    pos = q * (len(vs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(vs) - 1)
    return vs[lo] + (vs[hi] - vs[lo]) * (pos - lo)


def _eval_samples(e: _Expr, rec: dict, now: float) -> Optional[float]:
    """One series' instant value for a parsed expression, from the
    window samples copied out under the store lock."""
    kind, samples = rec["kind"], rec["samples"]
    if e.fn is None:
        # bare selector: newest sample within the staleness window
        return _scalar_of(kind, samples[-1][1]) if samples else None
    if e.fn in ("rate", "increase"):
        if len(samples) < 2:
            return None
        span = samples[-1][0] - samples[0][0]
        if kind == "histogram":
            # rate()/increase() of a histogram = its observation count
            # (reset-aware over the scalar count sequence)
            delta = _counter_delta(
                samples[0][1][2], (v[2] for _, v in samples[1:]))
        else:
            delta = _counter_delta(samples[0][1],
                                   (v for _, v in samples[1:]))
        if e.fn == "increase":
            return delta
        return delta / span if span > 0 else None
    if e.fn in _OVER_TIME_FNS:
        vals = [_scalar_of(kind, v) for _, v in samples]
        if not vals:
            return None
        if e.fn == "avg_over_time":
            return sum(vals) / len(vals)
        return max(vals) if e.fn == "max_over_time" else min(vals)
    if e.fn == "quantile_over_time":
        if kind == "histogram":
            if len(samples) < 2 or rec["boundaries"] is None:
                return None
            counts, _, _ = _hist_delta(samples[0][1], samples[-1][1])
            return _bucket_quantile(e.q, rec["boundaries"], counts)
        vals = [float(v) for _, v in samples]
        if not vals:
            return None
        return _empirical_quantile(e.q, vals)
    raise QueryError(f"unhandled function {e.fn!r}")


# ----------------------------------------------------------------------- TSDB
class TSDB:
    """The store: ingest snapshots, answer instant + range queries."""

    def __init__(self, max_series: int = 4096,
                 raw_slots: int = RAW_SLOTS_DEFAULT,
                 clock: Callable[[], float] = time.time):
        self.max_series = int(max_series)
        self.raw_slots = max(16, int(raw_slots))
        self._clock = clock
        # one leaf lock (TSDB_LOCK_DAG): series table + rings + counters;
        # O(dict/ring op) critical sections only — queries copy samples
        # out under it and evaluate outside
        self._lock = threading.Lock()
        # (name, sorted tag tuple) -> Series     guarded by: _lock
        self._series: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                           Series] = {}
        # name -> its Series list: queries select by metric name first,
        # and a full-table scan per query would be O(tsdb_max_series)
        # under the lock on a GCS handler thread
        # guarded by: _lock
        self._by_name: Dict[str, List[Series]] = {}
        self._samples_total = 0                # guarded by: _lock
        self._dropped_series = 0               # guarded by: _lock
        self._last_prune = 0.0                 # guarded by: _lock

    # ------------------------------------------------------------- ingest
    def ingest(self, worker_id: str, payload: Any,
               now: Optional[float] = None) -> int:
        """One publisher snapshot (the raw ``__metrics__/`` KV bytes, or
        the decoded dict) into the rings.  Timestamped with head receipt
        time.  Returns samples stored; never raises on malformed input
        (telemetry must not take down the KV handler)."""
        try:
            if isinstance(payload, (bytes, bytearray)):
                payload = json.loads(payload)
            snapshot = payload["snapshot"]
        except Exception:  # noqa: BLE001 - corrupt snapshot: skip whole
            return 0
        stored = 0
        with self._lock:
            # stamped INSIDE the lock: export_since's cursor is a single
            # global high-water mark, so store order must match timestamp
            # order — a sample stamped before the lock could land BEHIND
            # an already-exported newest and be skipped forever
            ts = self._clock() if now is None else now
            for name, m in snapshot.items():
                kind = m.get("kind", "untyped")
                for s in m.get("series", ()):
                    try:
                        tags = dict(s["tags"])
                        tags["worker"] = worker_id
                        val = self._pack(kind, s["value"])
                    except Exception:  # noqa: BLE001 - one bad series
                        continue
                    key = (name, tuple(sorted(tags.items())))
                    ser = self._series.get(key)
                    if ser is None:
                        if len(self._series) >= self.max_series:
                            self._dropped_series += 1
                            continue
                        ser = Series(name, kind, tags,
                                     self._boundaries(kind, s["value"]),
                                     self.raw_slots)
                        self._series[key] = ser
                        self._by_name.setdefault(name, []).append(ser)
                    ser.add(ts, val)
                    stored += 1
            self._samples_total += stored
            nseries = len(self._series)
            if ts - self._last_prune > 300.0:
                self._last_prune = ts
                for key in [k for k, ser in self._series.items()
                            if ts - ser.last_ts > IDLE_PRUNE_S]:
                    ser = self._series.pop(key)
                    peers = self._by_name.get(key[0])
                    if peers is not None:
                        peers[:] = [s for s in peers if s is not ser]
                        if not peers:
                            del self._by_name[key[0]]
        self._publish_self_stats(nseries, stored)
        return stored

    @staticmethod
    def _pack(kind: str, value: Any):
        if kind == "histogram":
            # cumulative state: (per-bucket counts in bound order incl.
            # +Inf, sum, count) — windowed quantiles need the buckets
            return (tuple(value["buckets"].values()),
                    float(value["sum"]), float(value["count"]))
        return float(value)

    @staticmethod
    def _boundaries(kind: str, value: Any) -> Optional[Tuple[str, ...]]:
        if kind != "histogram":
            return None
        return tuple(b for b in value["buckets"] if b != "+Inf")

    def _publish_self_stats(self, nseries: int, stored: int) -> None:
        """Registry-side mirror of the store's own health (cataloged
        rtpu_tsdb_* series; outside _lock — metric locks are theirs)."""
        if not stored:
            return
        try:
            from ray_tpu._private.config import GLOBAL_CONFIG
            if not GLOBAL_CONFIG.metrics_enabled:
                return
            from ray_tpu.util import metrics_catalog as mcat
            mcat.get("rtpu_tsdb_series").set(nseries)
            mcat.get("rtpu_tsdb_samples_total").inc(stored)
        except Exception:  # noqa: BLE001 - telemetry best-effort
            pass

    # --------------------------------------------------- replication export
    def export_since(self, since_ts: float) -> Tuple[List[dict], float]:
        """Raw-ring samples strictly newer than ``since_ts``, per
        series — the GCS replication hub ships these deltas to warm
        standbys (DESIGN.md §4l) so the head's metric history survives
        a failover.  Returns ``(dump, newest_ts)``; feed ``newest_ts``
        back as the next cursor.  Copies out under the leaf lock; cost
        scales with NEW samples, not store size, once the cursor
        advances."""
        out: List[dict] = []
        newest = since_ts
        with self._lock:
            for ser in self._series.values():
                if ser.last_ts <= since_ts:
                    continue
                samples = [(ts, v) for ts, v in
                           ser.rings[0].samples(since_ts, ser.last_ts)
                           if ts > since_ts]
                if not samples:
                    continue
                newest = max(newest, samples[-1][0])
                out.append({"name": ser.name, "kind": ser.kind,
                            "tags": dict(ser.tags),
                            "boundaries": ser.boundaries,
                            "samples": samples})
        return out, newest

    def seed(self, dump: Iterable[dict]) -> int:
        """Inverse of :meth:`export_since`: adopt exported samples into
        this store (a promoted standby inheriting the dead primary's
        history).  Samples route through ``Series.add`` so every ladder
        rung populates; per-series monotonicity (``ts > last_ts``)
        makes overlapping deltas idempotent; ``max_series`` is honored
        exactly like ingest."""
        added = 0
        with self._lock:
            for rec in dump:
                try:
                    name = rec["name"]
                    tags = dict(rec["tags"])
                    samples = rec.get("samples") or ()
                except Exception:  # noqa: BLE001 - one malformed record
                    continue
                key = (name, tuple(sorted(tags.items())))
                ser = self._series.get(key)
                if ser is None:
                    if len(self._series) >= self.max_series:
                        self._dropped_series += 1
                        continue
                    bounds = rec.get("boundaries")
                    ser = Series(name, rec.get("kind", "untyped"), tags,
                                 tuple(bounds) if bounds else None,
                                 self.raw_slots)
                    self._series[key] = ser
                    self._by_name.setdefault(name, []).append(ser)
                for ts, val in samples:
                    ts = float(ts)
                    if ts > ser.last_ts:
                        ser.add(ts, tuple(val)
                                if isinstance(val, list) else val)
                        added += 1
            self._samples_total += added
        return added

    # -------------------------------------------------------------- stats
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"series": len(self._series),
                    "samples_total": self._samples_total,
                    "dropped_series": self._dropped_series,
                    "max_series": self.max_series}

    def list_series(self, match: Optional[str] = None) -> List[dict]:
        """Series metadata (name, kind, tags, newest sample age)."""
        sel = _parse_selector(match) if match else None
        now = self._clock()
        out = []
        with self._lock:
            for ser in self._series.values():
                if sel is not None and (ser.name != sel.name
                                        or not sel.matches(ser.tags)):
                    continue
                out.append({"name": ser.name, "kind": ser.kind,
                            "tags": dict(ser.tags),
                            "age_s": round(now - ser.last_ts, 3)})
        out.sort(key=lambda d: (d["name"], sorted(d["tags"].items())))
        return out

    # ------------------------------------------------------------- querying
    def _collect(self, sel: _Selector, start: float,
                 end: float) -> List[dict]:
        """Copy matching series' metadata + window samples out under the
        lock (rings mutate under ingest; evaluation happens outside).
        Name-indexed: cost scales with the metric's own tagsets, not
        the whole store."""
        out = []
        with self._lock:
            for ser in self._by_name.get(sel.name, ()):
                if not sel.matches(ser.tags):
                    continue
                out.append({"kind": ser.kind, "tags": dict(ser.tags),
                            "boundaries": ser.boundaries,
                            "samples": ser.window(start, end)})
        return out

    def query(self, expr: str, at: Optional[float] = None) -> List[dict]:
        """Instant query: ``[{"tags": {...}, "value": float}, ...]``.
        Series with no data in the window are omitted."""
        e = _parse_expr(expr)
        now = self._clock() if at is None else at
        window = e.sel.window_s if e.fn is not None else STALENESS_S
        rows: List[dict] = []
        for rec in self._collect(e.sel, now - window, now):
            v = _eval_samples(e, rec, now)
            if v is not None:
                rows.append({"tags": rec["tags"], "value": v})
        if e.agg is not None:
            rows = self._aggregate(e, rows)
        rows.sort(key=lambda r: sorted(r["tags"].items()))
        return rows

    def query_range(self, expr: str, start: Optional[float] = None,
                    end: Optional[float] = None,
                    step: Optional[float] = None) -> List[dict]:
        """Range query: the instant expression evaluated at each step —
        ``[{"tags": {...}, "points": [[ts, value], ...]}, ...]`` (the
        dashboard's sparkline feed).

        One parse and ONE locked collection cover the whole range (the
        rung is chosen once, for the earliest step's window); each step
        then evaluates over a bisected slice — a 60-point sparkline
        costs the store one lock acquisition, not sixty."""
        import bisect

        e = _parse_expr(expr)
        now = self._clock()
        end = now if end is None else float(end)
        start = end - 600.0 if start is None else float(start)
        if step is None:
            step = max((end - start) / 60.0, 1e-9)
        else:
            # caller-supplied (possibly straight off a URL): a zero /
            # negative step would spin this loop forever on a GCS
            # handler thread, and a microscopic one is the same DoS in
            # disguise — bound the step count, not just the sign
            step = float(step)
            if not step > 0:
                raise QueryError(f"step must be > 0 (got {step})")
            if (end - start) / step > MAX_RANGE_STEPS:
                raise QueryError(
                    f"range has more than {MAX_RANGE_STEPS} steps "
                    f"(span {end - start:.0f}s / step {step}s) — "
                    f"raise the step or narrow the range")
        window = e.sel.window_s if e.fn is not None else STALENESS_S
        recs = self._collect(e.sel, start - window, end)
        out: Dict[Tuple[Tuple[str, str], ...], dict] = {}
        ts = start
        while ts <= end + 1e-9:
            rows: List[dict] = []
            for rec in recs:
                samples = rec["samples"]
                lo = bisect.bisect_left(samples, ts - window,
                                        key=lambda s: s[0])
                hi = bisect.bisect_right(samples, ts,
                                         key=lambda s: s[0])
                v = _eval_samples(
                    e, {"kind": rec["kind"],
                        "boundaries": rec["boundaries"],
                        "samples": samples[lo:hi]}, ts)
                if v is not None:
                    rows.append({"tags": rec["tags"], "value": v})
            if e.agg is not None:
                rows = self._aggregate(e, rows)
            for row in rows:
                key = tuple(sorted(row["tags"].items()))
                dst = out.setdefault(key, {"tags": row["tags"],
                                           "points": []})
                dst["points"].append([round(ts, 3), row["value"]])
            ts += step
        return sorted(out.values(),
                      key=lambda r: sorted(r["tags"].items()))

    @staticmethod
    def _aggregate(e: _Expr, rows: List[dict]) -> List[dict]:
        groups: Dict[Tuple[Tuple[str, str], ...], List[float]] = {}
        for r in rows:
            key = tuple((k, r["tags"].get(k, "")) for k in (e.by or ()))
            groups.setdefault(key, []).append(r["value"])
        out = []
        for key, vals in groups.items():
            if e.agg == "sum":
                v = sum(vals)
            elif e.agg == "avg":
                v = sum(vals) / len(vals)
            elif e.agg == "max":
                v = max(vals)
            else:
                v = min(vals)
            out.append({"tags": dict(key), "value": v})
        return out

    # ----------------------------------------------------- detector helpers
    def windowed_mean_per_series(self, name: str, window_s: float,
                                 now: Optional[float] = None,
                                 min_count: int = 1) -> List[dict]:
        """Per-series histogram window mean (Δsum / Δcount) — the
        straggler detector's statistic.  Series with fewer than
        ``min_count`` new observations in the window are omitted."""
        now = self._clock() if now is None else now
        sel = _parse_selector(name)
        out = []
        for rec in self._collect(sel, now - window_s, now):
            samples = rec["samples"]
            if rec["kind"] != "histogram" or len(samples) < 2:
                continue
            _, dsum, dcount = _hist_delta(samples[0][1], samples[-1][1])
            if dcount < min_count or dcount <= 0:
                continue
            out.append({"tags": rec["tags"],
                        "mean": dsum / dcount, "count": dcount})
        return out

    def forecast(self, expr: str, horizon_s: float,
                 period_s: float = 86400.0, smooth_s: float = 600.0,
                 now: Optional[float] = None) -> List[dict]:
        """Seasonal-naive forecast: the predicted value of each matching
        series at ``now + horizon_s`` is its mean over the ``smooth_s``
        window ending one season earlier (``now + horizon_s -
        period_s``) — yesterday's value at the hour we are scaling for,
        read from whichever ladder rung still covers it (the 48h long
        rung holds two diurnal periods).  Cold start (no samples near
        the seasonal anchor yet) falls back to the mean over the most
        recent ``smooth_s``, i.e. "no better guess than now" — the
        autopilot's forecast reflex then never *withholds* capacity it
        would have requested reactively.

        Gauge (and untyped) series only — a forecast of a cumulative
        counter or histogram is not a level, so those are omitted.  Returns
        rows shaped like :meth:`query`, each with ``value`` (the
        prediction) and ``seasonal`` (False on the cold-start
        fallback)."""
        if horizon_s < 0:
            raise QueryError(f"horizon_s must be >= 0 (got {horizon_s})")
        if period_s <= 0:
            raise QueryError(f"period_s must be > 0 (got {period_s})")
        now = self._clock() if now is None else now
        sel = _parse_selector(expr)
        if sel.window_s is not None:
            raise QueryError("forecast() takes a bare selector "
                             "(no [window])")
        anchor = now + horizon_s - period_s
        rows: List[dict] = []
        for rec in self._collect(sel, anchor - smooth_s, now):
            if rec["kind"] not in ("gauge", "untyped"):
                continue    # counters/histograms: cumulative, not a level
            seasonal = [float(v) for ts, v in rec["samples"]
                        if anchor - smooth_s <= ts <= anchor]
            if seasonal:
                rows.append({"tags": rec["tags"],
                             "value": sum(seasonal) / len(seasonal),
                             "seasonal": True})
                continue
            recent = [float(v) for ts, v in rec["samples"]
                      if ts >= now - smooth_s]
            if recent:
                rows.append({"tags": rec["tags"],
                             "value": sum(recent) / len(recent),
                             "seasonal": False})
        rows.sort(key=lambda r: sorted(r["tags"].items()))
        return rows

    def burn_rate(self, series: str, threshold_s: float, objective: float,
                  window_s: float, now: Optional[float] = None
                  ) -> Optional[float]:
        """Error-budget burn over a window, aggregated across every
        tagset of ``series``: fraction of observations slower than
        ``threshold_s`` (by bucket deltas, threshold rounded UP to the
        next bucket bound) divided by the budget ``1 - objective``.
        1.0 = burning exactly at budget; None = no observations."""
        now = self._clock() if now is None else now
        sel = _parse_selector(series)
        bad = total = 0.0
        for rec in self._collect(sel, now - window_s, now):
            samples = rec["samples"]
            if rec["kind"] != "histogram" or rec["boundaries"] is None \
                    or len(samples) < 2:
                continue
            counts, _, dcount = _hist_delta(samples[0][1], samples[-1][1])
            if dcount <= 0:
                continue
            # cumulative count at the first bound >= threshold: every
            # observation provably <= threshold
            ok = 0.0
            for i, b in enumerate(rec["boundaries"]):
                ok += counts[i]
                if float(b) >= threshold_s:
                    break
            else:
                ok = dcount  # threshold above every finite bound
            bad += max(dcount - ok, 0.0)
            total += dcount
        if total <= 0:
            return None
        budget = max(1.0 - objective, 1e-9)
        return (bad / total) / budget


# ------------------------------------------------------------------ detectors
class StragglerDetector:
    """Per-rank train step-time skew vs the group median.

    Over a sliding ``window_s``, each ``rtpu_train_step_seconds`` series
    (one per rank per worker process) yields a window-mean step time
    (Δsum/Δcount).  Series are COHORTED by their ``group`` tag before
    comparison (the elastic worker loop stamps its training group;
    untagged session runs form their own cohort): ranks are only
    stragglers relative to THEIR job's median — two concurrent jobs
    with different step times must not read each other as sick, and a
    cross-job median would misdirect the autopilot's drains.  Within a
    cohort of >= ``min_ranks`` active ranks, any rank whose mean
    exceeds ``ratio`` x the cohort median is a straggler — reported
    once per ``cooldown_s`` (default: the window) so a persistently
    slow rank doesn't flood the fleet-event feed.  The event carries
    the worker id; the GCS tags on the node id so the elasticity
    manager can drain the slow host."""

    SERIES = "rtpu_train_step_seconds"

    def __init__(self, tsdb: TSDB, window_s: float = 30.0,
                 ratio: float = 1.75, min_steps: int = 3,
                 min_ranks: int = 3, cooldown_s: Optional[float] = None):
        self.tsdb = tsdb
        self.window_s = float(window_s)
        self.ratio = float(ratio)
        self.min_steps = int(min_steps)
        self.min_ranks = int(min_ranks)
        self.cooldown_s = self.window_s if cooldown_s is None \
            else float(cooldown_s)
        self._last_fired: Dict[Tuple[str, str], float] = {}

    def check(self, now: Optional[float] = None) -> List[dict]:
        now = self.tsdb._clock() if now is None else now
        # cooldown entries older than their window suppress nothing —
        # drop them, or worker churn grows this dict for the head's
        # lifetime (the store's fixed-memory contract applies here too)
        self._last_fired = {k: t for k, t in self._last_fired.items()
                            if now - t < self.cooldown_s}
        rows = self.tsdb.windowed_mean_per_series(
            self.SERIES, self.window_s, now=now, min_count=self.min_steps)
        cohorts: Dict[str, List[dict]] = {}
        for r in rows:
            cohorts.setdefault(r["tags"].get("group", ""), []).append(r)
        out: List[dict] = []
        for group, members in sorted(cohorts.items()):
            if len(members) < self.min_ranks:
                continue
            means = sorted(r["mean"] for r in members)
            mid = len(means) // 2
            median = means[mid] if len(means) % 2 \
                else (means[mid - 1] + means[mid]) / 2.0
            if median <= 0:
                continue
            for r in members:
                if r["mean"] <= self.ratio * median:
                    continue
                key = (r["tags"].get("rank", "?"),
                       r["tags"].get("worker", "?"))
                fired = self._last_fired.get(key, 0.0)
                if now - fired < self.cooldown_s:
                    continue
                self._last_fired[key] = now
                ev = {
                    "kind": "straggler",
                    "rank": key[0], "worker": key[1],
                    "mean_step_s": round(r["mean"], 6),
                    "median_step_s": round(median, 6),
                    "skew_ratio": round(r["mean"] / median, 3),
                    "steps": r["count"], "window_s": self.window_s}
                if group:
                    ev["group"] = group
                out.append(ev)
        return out


class SloBurnAlerter:
    """Multi-window error-budget burn alerts over latency histograms.

    Rules come from ``metrics_catalog.SLO_RULES`` (declared next to the
    series they reference so rtlint's metrics pass can prove each rule
    names a live cataloged histogram).  Classic multi-window gating: an
    alert fires only when BOTH the long and the short window burn above
    ``factor`` x budget — long filters blips, short proves it is still
    happening.  One alert per rule per ``cooldown`` (the short window)."""

    def __init__(self, tsdb: TSDB, rules: Iterable[dict]):
        self.tsdb = tsdb
        self.rules = tuple(rules)
        self._last_fired: Dict[Tuple[str, int], float] = {}

    def check(self, now: Optional[float] = None) -> List[dict]:
        now = self.tsdb._clock() if now is None else now
        out: List[dict] = []
        for rule in self.rules:
            for wi, (long_s, short_s, factor) in enumerate(rule["windows"]):
                long_burn = self.tsdb.burn_rate(
                    rule["series"], rule["threshold_s"], rule["objective"],
                    long_s, now=now)
                if long_burn is None or long_burn <= factor:
                    continue
                short_burn = self.tsdb.burn_rate(
                    rule["series"], rule["threshold_s"], rule["objective"],
                    short_s, now=now)
                if short_burn is None or short_burn <= factor:
                    continue
                key = (rule["name"], wi)
                if now - self._last_fired.get(key, 0.0) < short_s:
                    continue
                self._last_fired[key] = now
                out.append({
                    "kind": "slo_burn", "rule": rule["name"],
                    "series": rule["series"],
                    "threshold_s": rule["threshold_s"],
                    "objective": rule["objective"],
                    "burn_long": round(long_burn, 3),
                    "burn_short": round(short_burn, 3),
                    "factor": factor,
                    "window_long_s": long_s, "window_short_s": short_s})
        return out
