"""``multiprocessing.Pool``-compatible shim over ray_tpu tasks.

Reference: ``python/ray/util/multiprocessing/`` (SURVEY.md §2.3) — lets
``Pool(...)``-based code scale across the cluster unchanged: apply/map/
imap/starmap (+ _async variants with AsyncResult.get/wait/ready).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional, Sequence

import ray_tpu


class AsyncResult:
    def __init__(self, refs: List[Any], single: bool):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        out = ray_tpu.get(self._refs, timeout=timeout)
        return out[0] if self._single else out

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                               timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0)
            return True
        except Exception:  # noqa: BLE001
            return False


class Pool:
    """Task-backed process pool.

    ``processes`` sizes the chunking of map-style calls; execution
    concurrency is governed by the cluster scheduler (tasks queue against
    available CPUs), not by a dedicated worker set — so per-worker state
    via ``initializer`` runs once per TASK, not once per process.
    """

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = (), ray_remote_args: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._limit = processes or int(
            ray_tpu.cluster_resources().get("CPU", 4))
        self._remote_args = ray_remote_args or {}
        self._initializer = initializer
        self._initargs = initargs
        self._closed = False

    def _task(self, fn: Callable) -> Any:
        init, initargs = self._initializer, self._initargs

        def call(args, kwargs):
            if init is not None:
                init(*initargs)
            return fn(*args, **(kwargs or {}))

        return ray_tpu.remote(**self._remote_args)(call) \
            if self._remote_args else ray_tpu.remote(call)

    def _check_open(self):
        if self._closed:
            raise ValueError("Pool not running")

    # -- apply ---------------------------------------------------------------
    def apply(self, fn: Callable, args: Sequence = (), kwds: dict = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: Sequence = (),
                    kwds: dict = None) -> AsyncResult:
        self._check_open()
        return AsyncResult([self._task(fn).remote(tuple(args), kwds)], True)

    # -- map -----------------------------------------------------------------
    def _submit_chunked(self, fn: Callable, iterables, chunksize, star):
        items = list(zip(*iterables)) if len(iterables) > 1 \
            else [(x,) for x in iterables[0]]
        chunksize = chunksize or max(1, len(items) // (self._limit * 4) or 1)
        task = self._task(_run_chunk)
        chunks = [items[i:i + chunksize]
                  for i in range(0, len(items), chunksize)]
        refs = [task.remote((fn, chunk, star), None) for chunk in chunks]
        return refs, [len(c) for c in chunks]

    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn: Callable, iterable: Iterable,
                  chunksize: Optional[int] = None) -> "AsyncResult":
        self._check_open()
        refs, _ = self._submit_chunked(fn, [list(iterable)], chunksize, False)
        return _ChunkedResult(refs)

    def starmap(self, fn: Callable, iterable: Iterable[Sequence],
                chunksize: Optional[int] = None) -> List[Any]:
        self._check_open()
        items = list(iterable)
        refs, _ = self._submit_chunked(
            fn, [list(x) for x in zip(*items)] if items else [[]],
            chunksize, True)
        return _ChunkedResult(refs).get()

    def imap(self, fn: Callable, iterable: Iterable,
             chunksize: Optional[int] = None):
        self._check_open()
        refs, sizes = self._submit_chunked(fn, [list(iterable)], chunksize,
                                           False)
        for ref in refs:
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn: Callable, iterable: Iterable,
                       chunksize: Optional[int] = None):
        self._check_open()
        refs, _ = self._submit_chunked(fn, [list(iterable)], chunksize, False)
        pending = list(refs)
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1)
            yield from ray_tpu.get(done[0])

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True

    def join(self) -> None:
        if not self._closed:
            raise ValueError("join() before close()")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()


class _ChunkedResult(AsyncResult):
    def __init__(self, refs):
        super().__init__(refs, single=False)

    def get(self, timeout: Optional[float] = None):
        chunks = ray_tpu.get(self._refs, timeout=timeout)
        return list(itertools.chain.from_iterable(chunks))


def _run_chunk(fn, chunk, star):
    return [fn(*item) if star else fn(item[0]) for item in chunk]
