"""Scheduling strategies (reference: ``python/ray/util/scheduling_strategies.py``)."""

from __future__ import annotations

from typing import Optional


class PlacementGroupSchedulingStrategy:
    def __init__(self, placement_group, placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        self.placement_group = placement_group
        self.placement_group_bundle_index = placement_group_bundle_index
        self.placement_group_capture_child_tasks = placement_group_capture_child_tasks

    def to_spec(self) -> dict:
        return {"type": "placement_group",
                "pg_id": self.placement_group.id,
                "bundle_index": self.placement_group_bundle_index}


class NodeAffinitySchedulingStrategy:
    def __init__(self, node_id: str, soft: bool = False):
        self.node_id = node_id
        self.soft = soft

    def to_spec(self) -> dict:
        return {"type": "node_affinity", "node_id": self.node_id,
                "soft": self.soft}


def strategy_to_spec(strategy) -> Optional[object]:
    if strategy is None or isinstance(strategy, str):
        return strategy
    return strategy.to_spec()
