"""joblib backend: ``with joblib.parallel_backend("ray_tpu"): ...``.

Reference: ``python/ray/util/joblib/`` (SURVEY.md §2.3) — lets
scikit-learn's ``n_jobs`` parallelism fan out as cluster tasks.
Call :func:`register_ray_tpu` once (importing this module does it).
"""

from __future__ import annotations

from typing import Any, Callable

import ray_tpu

try:
    from joblib._parallel_backends import ParallelBackendBase
    from joblib.parallel import register_parallel_backend
    _HAVE_JOBLIB = True
except ImportError:  # pragma: no cover
    ParallelBackendBase = object
    _HAVE_JOBLIB = False


class _TaskFuture:
    """Duck-typed future joblib can poll: get(timeout)."""

    def __init__(self, ref, callback: Callable | None):
        self._ref = ref
        self._callback = callback
        self._done = False

    def get(self, timeout: float | None = None) -> Any:
        out = ray_tpu.get(self._ref, timeout=timeout)
        if not self._done and self._callback is not None:
            self._done = True
            self._callback(out)
        return out


class RayTpuBackend(ParallelBackendBase):
    """Each joblib batch becomes one cluster task."""

    supports_timeout = True
    uses_threads = False
    supports_sharedmem = False

    def configure(self, n_jobs: int = 1, parallel=None, **kwargs) -> int:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self.parallel = parallel
        return self.effective_n_jobs(n_jobs)

    def effective_n_jobs(self, n_jobs: int) -> int:
        if n_jobs == 0:
            raise ValueError("n_jobs == 0 has no meaning")
        cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
        return cpus if n_jobs is None or n_jobs < 0 else n_jobs

    def apply_async(self, func: Callable, callback: Callable | None = None):
        # joblib's retrieval calls future.get(), which fires the callback —
        # eager dispatch isn't required for correctness
        return _TaskFuture(_run_joblib_batch.remote(func), callback)

    def abort_everything(self, ensure_ready: bool = True) -> None:
        if ensure_ready:
            self.configure(n_jobs=self.parallel.n_jobs,
                           parallel=self.parallel)


@ray_tpu.remote
def _run_joblib_batch(f):
    return f()


def register_ray_tpu() -> None:
    if _HAVE_JOBLIB:
        register_parallel_backend("ray_tpu", RayTpuBackend)


register_ray_tpu()
