"""Ray-Client-equivalent remote driver mode.

Reference: ``python/ray/util/client/`` (SURVEY.md §2.3) — a gRPC proxy at
``ray://host:10001``; the client process runs a thin API facade and the
server translates to real core calls.  Here the proxy is a TCP tunnel
(``server.ClientProxyServer``): a connecting client names a target ("gcs"
or an actor socket path) and the proxy pipes messages to the cluster-local
unix socket — so the normal control-plane *and* direct actor-call protocols
work remotely unchanged.  The data plane differs by necessity: a remote
client cannot mmap the cluster's /dev/shm, so client ``put`` always inlines
through the control plane and ``get`` fetches object bytes via the
``fetch_object`` RPC (the reference's client server proxies object
transport the same way).
"""

from ray_tpu.util.client.server import ClientProxyServer  # noqa: F401
