"""Client proxy server: TCP ⇄ cluster-local unix sockets.

See package docstring.  Wire format: the first message on a new TCP
connection is ``{"target": "gcs" | "<unix socket path>"}``; afterwards the
proxy pumps pickled messages both ways until either side disconnects.
Actor targets are validated against the session socket dir so a client
cannot use the proxy to reach arbitrary local sockets.
"""

from __future__ import annotations

import threading
from typing import Optional

from ray_tpu._private import protocol, rtlog

logger = rtlog.get("client-proxy")


class ClientProxyServer:
    """Binds loopback by default; exposing it beyond localhost requires an
    explicit host AND sharing the session auth key (RTPU_AUTH_KEY on the
    client) — the connection handshake HMACs against the per-session
    secret, never the module default."""

    def __init__(self, session, host: str = "127.0.0.1", port: int = 10001):
        self.session = session
        self.host = host
        self.port = port
        self._listener = protocol.make_tcp_listener(host, port)
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="client-proxy", daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _resolve_target(self, target: str) -> Optional[str]:
        import os
        if target == "gcs":
            return self.session.socket_path("gcs.sock")
        # actor sockets live in the session socket dir; refuse anything
        # else — realpath first so ../ traversal cannot escape it
        path = os.path.realpath(str(target))
        sock_dir = os.path.realpath(str(self.session.socket_dir))
        if os.path.dirname(path) == sock_dir:
            return path
        return None

    def _serve(self, client_conn) -> None:
        try:
            hello = client_conn.recv()
            path = self._resolve_target(hello.get("target", ""))
            if path is None:
                client_conn.send({"error": "invalid target"})
                client_conn.close()
                return
            upstream = protocol.connect(path)
            client_conn.send({"ok": True})
        except (EOFError, OSError, FileNotFoundError) as e:
            try:
                client_conn.send({"error": str(e)})
            except (OSError, ValueError):
                pass
            client_conn.close()
            return

        def pump(src, dst):
            while True:
                try:
                    dst.send(src.recv())
                except (EOFError, OSError, ValueError):
                    break
            for c in (src, dst):
                try:
                    c.close()
                except OSError:
                    pass

        t = threading.Thread(target=pump, args=(client_conn, upstream),
                             daemon=True)
        t.start()
        pump(upstream, client_conn)

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._listener.close()
        except OSError:
            pass
