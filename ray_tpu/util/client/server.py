"""Client proxy server: TCP ⇄ cluster-local unix sockets.

See package docstring.  Wire format: the first message on a new TCP
connection is ``{"target": "gcs" | "<unix socket path>"}``; afterwards the
proxy pumps pickled messages both ways until either side disconnects.
Actor targets are validated against the session socket dir so a client
cannot use the proxy to reach arbitrary local sockets.
"""

from __future__ import annotations

import threading
from typing import Optional

from ray_tpu._private import protocol, rtlog

logger = rtlog.get("client-proxy")


class ClientProxyServer:
    """Binds loopback by default; exposing it beyond localhost requires an
    explicit host AND sharing the session auth key (RTPU_AUTH_KEY on the
    client) — the connection handshake HMACs against the per-session
    secret, never the module default."""

    def __init__(self, session, host: str = "127.0.0.1", port: int = 10001):
        self.session = session
        self.host = host
        self.port = port
        self._listener = protocol.make_tcp_listener(host, port)
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="client-proxy", daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        protocol.serve_accept_loop(self._listener, self._stopped.is_set,
                                   self._serve, "client-proxy-serve")

    def _resolve_target(self, target: str) -> Optional[str]:
        import os
        if target == "gcs":
            return self.session.socket_path("gcs.sock")
        # tcp://host:port: dial-out relay to an actor on a remote-agent
        # host (hub-spoke: clients that can't reach sibling hosts route
        # through the head).  The handshake already HMAC-authenticated the
        # caller against the session secret — an authed principal can run
        # arbitrary tasks anyway, so relaying adds no privilege.
        if protocol.parse_tcp_addr(target) is not None:
            return target
        # actor sockets live in the session socket dir; refuse anything
        # else — realpath first so ../ traversal cannot escape it
        path = os.path.realpath(str(target))
        sock_dir = os.path.realpath(str(self.session.socket_dir))
        if os.path.dirname(path) == sock_dir:
            return path
        return None

    def _serve(self, client_conn) -> None:
        try:
            hello = client_conn.recv()
            path = self._resolve_target(hello.get("target", ""))
            if path is None:
                client_conn.send({"error": "invalid target"})
                client_conn.close()
                return
            upstream = protocol.connect_addr(path)
            client_conn.send({"ok": True})
        except (EOFError, OSError, FileNotFoundError) as e:
            try:
                client_conn.send({"error": str(e)})
            except (OSError, ValueError):
                pass
            client_conn.close()
            return

        # Teardown protocol for the conn pair.  The FIRST pump to exit
        # only shutdown()s both sockets: that interrupts the sibling's
        # blocked recv() AND sends FIN to both far ends (a bare close()
        # would do neither while a read is in flight — the kernel socket
        # stays alive and death detection upstream never fires).  The
        # SECOND pump then close()s the fds — only once no thread can
        # touch them again, so a recycled fd number can never belong to
        # some unrelated new connection when we act on it.
        lock = threading.Lock()
        state = {"finished": False}

        def pump(src, dst):
            # opaque byte-frame relay: never decode — versioned wire
            # frames (_private/wire.py) and legacy pickle pass through
            # identically, and the proxy skips a pickle round-trip
            while True:
                try:
                    dst.send_bytes(src.recv_bytes())
                except (EOFError, OSError, ValueError):
                    break
            with lock:
                first = not state["finished"]
                state["finished"] = True
            if first:
                protocol.shutdown_conn(src)
                protocol.shutdown_conn(dst)
            else:
                for c in (src, dst):
                    try:
                        c.close()
                    except OSError:
                        pass

        t = threading.Thread(target=pump, args=(client_conn, upstream),
                             daemon=True, name="client-proxy-pump")
        t.start()
        pump(upstream, client_conn)

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._listener.close()
        except OSError:
            pass
