"""ParallelIterator: sharded lazy iteration over actors.

Reference: ``python/ray/util/iter.py`` (older-vintage forks; SURVEY.md
§2.3 ray.util misc) — ``from_items``/``from_range`` shard a sequence
across shard ACTORS; transformations (``for_each``/``filter``/
``batch``/``flat_map``) compose lazily per shard; ``gather_sync``
round-robins shards in order while ``gather_async`` yields whichever
shard produces next.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterator, List, Sequence

import ray_tpu

__all__ = ["from_items", "from_range", "from_iterators",
           "ParallelIterator"]


@ray_tpu.remote
class _ShardActor:
    """Holds one shard's source items and applies the op chain lazily."""

    def __init__(self, items: List[Any]):
        self._items = items
        self._cursors: dict = {}  # cursor_id -> (live iterator, position)

    def _build(self, ops: List[tuple]) -> Iterator[Any]:
        import cloudpickle
        it: Iterator[Any] = iter(self._items)
        for kind, blob in ops:
            # "batch" carries its size as a plain int, not a pickled fn
            fn = blob if kind == "batch" else cloudpickle.loads(blob)
            if kind == "for_each":
                it = map(fn, it)
            elif kind == "filter":
                it = filter(fn, it)
            elif kind == "flat_map":
                it = itertools.chain.from_iterable(map(fn, it))
            elif kind == "batch":
                def batched(src=it, n=fn):
                    buf: List[Any] = []
                    for x in src:
                        buf.append(x)
                        if len(buf) == n:
                            yield buf
                            buf = []
                    if buf:
                        yield buf
                it = batched()
        return it

    def take(self, ops: List[tuple], cursor: str, start: int,
             count: int) -> List[Any]:
        """Return result slice [start, start+count).  A live iterator is
        kept per ``cursor`` so consuming a shard is O(N), not O(N^2);
        ``start`` is the restart fallback — if the actor died and lost
        the cursor (or the id is new), the chain is rebuilt and skipped
        forward, preserving at-least-once restartability."""
        state = self._cursors.get(cursor)
        if state is None or state[1] != start:
            it = self._build(ops)
            if start:
                next(itertools.islice(it, start, start), None)  # skip
            state = [it, start]
        out = list(itertools.islice(state[0], count))
        state[1] = start + len(out)
        self._cursors[cursor] = state
        if len(self._cursors) > 64:  # abandoned consumers
            self._cursors.pop(next(iter(self._cursors)))
        return out


class ParallelIterator:
    def __init__(self, shards: List[Any], ops: List[tuple]):
        self._shards = shards
        self._ops = ops

    # ------------------------------------------------------- transformations
    def _with(self, kind: str, fn: Any) -> "ParallelIterator":
        import cloudpickle
        blob = cloudpickle.dumps(fn) if kind != "batch" else fn
        return ParallelIterator(self._shards, self._ops + [(kind, blob)])

    def for_each(self, fn: Callable[[Any], Any]) -> "ParallelIterator":
        return self._with("for_each", fn)

    def filter(self, fn: Callable[[Any], bool]) -> "ParallelIterator":
        return self._with("filter", fn)

    def flat_map(self, fn: Callable[[Any], Sequence]) -> "ParallelIterator":
        return self._with("flat_map", fn)

    def batch(self, n: int) -> "ParallelIterator":
        return ParallelIterator(self._shards, self._ops + [("batch", n)])

    def num_shards(self) -> int:
        return len(self._shards)

    # -------------------------------------------------------------- gathers
    _CHUNK = 64

    def _shard_iter(self, idx: int) -> Iterator[Any]:
        import uuid
        cursor = uuid.uuid4().hex
        start = 0
        while True:
            part = ray_tpu.get(self._shards[idx].take.remote(
                self._ops, cursor, start, self._CHUNK))
            yield from part
            if len(part) < self._CHUNK:
                return
            start += self._CHUNK

    def gather_sync(self) -> Iterator[Any]:
        """Round-robin across shards, deterministic order."""
        iters = [self._shard_iter(i) for i in range(len(self._shards))]
        alive = list(iters)
        while alive:
            for it in list(alive):
                try:
                    yield next(it)
                except StopIteration:
                    alive.remove(it)

    def gather_async(self) -> Iterator[Any]:
        """Yield from whichever shard has a chunk ready first."""
        import uuid
        cursors = [uuid.uuid4().hex for _ in self._shards]
        pending = {self._shards[i].take.remote(
                       self._ops, cursors[i], 0, self._CHUNK): (i, 0)
                   for i in range(len(self._shards))}
        while pending:
            done, _ = ray_tpu.wait(list(pending), num_returns=1)
            ref = done[0]
            i, start = pending.pop(ref)
            part = ray_tpu.get(ref)
            yield from part
            if len(part) == self._CHUNK:
                nxt = self._shards[i].take.remote(
                    self._ops, cursors[i], start + self._CHUNK,
                    self._CHUNK)
                pending[nxt] = (i, start + self._CHUNK)

    def take(self, n: int) -> List[Any]:
        return list(itertools.islice(self.gather_sync(), n))

    def __iter__(self) -> Iterator[Any]:
        return self.gather_sync()

    def __repr__(self) -> str:
        return f"ParallelIterator(shards={len(self._shards)}, " \
               f"ops={len(self._ops)})"


def from_items(items: Sequence[Any], num_shards: int = 2) -> ParallelIterator:
    items = list(items)
    shards = []
    for i in range(num_shards):
        shards.append(_ShardActor.remote(items[i::num_shards]))
    return ParallelIterator(shards, [])


def from_range(n: int, num_shards: int = 2) -> ParallelIterator:
    return from_items(list(range(n)), num_shards)


def from_iterators(creators: Sequence[Callable[[], Sequence]]
                   ) -> ParallelIterator:
    return ParallelIterator(
        [_ShardActor.remote(list(c())) for c in creators], [])
