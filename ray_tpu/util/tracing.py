"""Cluster-wide request tracing + device-trace merge onto the timeline.

Reference: ``python/ray/util/tracing/`` (SURVEY.md §5.1) — OpenTelemetry
span context rides task/actor metadata so a request's causal tree spans
processes; and ``ray timeline`` renders host-side Chrome trace events.
TPU-native addition (§5.1 rebuild note): ``jax.profiler`` device traces
are merged ONTO THE SAME CLOCK as the host spans, so one
``ray_tpu.timeline()`` dump shows a train step's host dispatch span above
the XLA ops it ran.

Since the Dapper-style tracing overhaul, span context also rides the wire
protocol itself (the compact optional ``trace`` frame field,
``wire.TRACE_FIELD``, attached only on connections that negotiated a
trace-aware version) so one request's tree spans client → GCS → worker →
data-plane → Serve/LLM engine.  Sampling is **head-based**: the ROOT of a
trace decides once —

- ``tracing.trace(name)`` roots are always sampled (the user asked);
- ``tracing.request_trace(name)`` roots (per-request auto-spans, e.g. the
  Serve proxy) sample at ``trace_sample_rate``;
- children inherit the root's decision, and an UNSAMPLED context neither
  emits events nor rides the wire — the always-on cost of a sampled-out
  request is one ``random()`` call.

Usage::

    from ray_tpu.util import tracing

    with tracing.trace("ingest-and-train"):       # driver: new trace root
        ref = preprocess.remote(batch)            # ctx propagates to tasks
        ...

    with tracing.profile_device("train_step"):    # any process with jax
        state, m = step_fn(state, batch)          # device events captured
        jax.block_until_ready(m)
    # both land in ray_tpu.timeline(): host spans carry
    # trace_id/span_id/parent_id args; device events carry cat="device".

Span context lives in a ``contextvars.ContextVar`` (not a bare
``threading.local``): each thread still has its own current span, and the
context additionally flows into asyncio tasks scheduled from a thread
that holds a span (``run_coroutine_threadsafe`` captures the caller's
context), so async actor methods and Serve deployments inherit it.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import random
import threading
import time
import weakref
from typing import Iterator, List, Optional

_SPAN: "contextvars.ContextVar[Optional[SpanContext]]" = \
    contextvars.ContextVar("rtpu_span", default=None)


class SpanContext:
    __slots__ = ("trace_id", "span_id", "parent_id", "name", "sampled",
                 "attrs")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str,
                 sampled: bool = True, attrs: Optional[dict] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.sampled = sampled
        # mutable span attributes merged into the event args at emit time
        # (lets a caller tag e.g. byte counts known only at span close)
        self.attrs = attrs

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name}

    @staticmethod
    def from_dict(d: Optional[dict]) -> Optional["SpanContext"]:
        if not d:
            return None
        return SpanContext(d["trace_id"], d["span_id"],
                           d.get("parent_id"), d.get("name", ""))

    # ------------------------------------------------- wire frame field
    # Compact form riding the optional ``trace`` frame field
    # (wire.TRACE_FIELD) on trace-aware connections: [trace_id, span_id].
    # parent/name never cross the wire — the receiver only ever creates
    # CHILDREN of the sender's span.  Only sampled contexts are packed
    # (head-based sampling: an unsampled root costs the wire nothing).
    def to_wire(self) -> list:
        return [self.trace_id, self.span_id]

    @staticmethod
    def from_wire(v, name: str = "") -> Optional["SpanContext"]:
        if not isinstance(v, (list, tuple)) or len(v) < 2:
            return None
        return SpanContext(str(v[0]), str(v[1]), None, name)


def current_span() -> Optional[SpanContext]:
    return _SPAN.get()


def _set_span(ctx: Optional[SpanContext]) -> None:
    _SPAN.set(ctx)


# Span/trace id generator: 64 random bits as hex.  NOT uuid4 — that is
# ~30µs/call on small sandboxed hosts (the PR-2 task-id finding), and a
# fully-traced task can mint several ids; a urandom-seeded PRNG is
# ~0.3µs with the same collision math for 64-bit ids.
_ids = random.Random(int.from_bytes(os.urandom(8), "big"))
_ids_lock = threading.Lock()


def _new_id() -> str:
    with _ids_lock:
        return f"{_ids.getrandbits(64):016x}"


# ------------------------------------------------------- wire plumbing
# The ONLY writers/readers of the optional ``trace`` frame field
# (rtlint's wire-trace rule keeps ad-hoc ``msg["trace"]`` plumbing out
# of the protocol layer — see tools/rtlint/wirecheck.py).

def attach_wire_trace(msg: dict,
                      ctx: Optional[SpanContext] = None) -> None:
    """Attach the current (or an explicitly carried) sampled span to an
    outgoing frame dict.

    Callers gate on the negotiated connection version
    (``wire.PROTO_TRACE`` / ``wire.DATA_PROTO_TRACE``) so un-upgraded
    peers never see the field."""
    if ctx is None:
        ctx = _SPAN.get()
    if ctx is not None and ctx.sampled:
        from ray_tpu._private import wire
        msg[wire.TRACE_FIELD] = [ctx.trace_id, ctx.span_id]


def extract_wire_trace(msg: dict, name: str = "") -> Optional[SpanContext]:
    """Pop and decode the ``trace`` field from an incoming frame dict
    (absent / malformed → None; the frame itself is never rejected)."""
    from ray_tpu._private import wire
    v = msg.pop(wire.TRACE_FIELD, None)
    if v is None:
        return None
    return SpanContext.from_wire(v, name=name)


def adopt(ctx: Optional[SpanContext]):
    """Make ``ctx`` the current span; returns a token for restore().
    Server dispatch loops bracket handler execution with adopt/restore
    so an adopted caller span can never leak onto the next frame."""
    return _SPAN.set(ctx)


def restore(token) -> None:
    _SPAN.reset(token)


# -------------------------------------------------------- thread rows
# Stable per-thread timeline rows.  ``threading.get_ident() % 100000``
# collided across threads (idents are reused pthread addresses — a new
# thread can inherit a dead one's ident, and with it its row AND name);
# instead rows are keyed by the Thread OBJECT (unique per thread
# lifetime, weakly held so dead threads' entries drop) and each thread
# gets a monotonically-assigned small id.  The FIRST span from a thread
# also emits a Chrome ``thread_name`` metadata event so multi-threaded
# spans render on distinct, named rows.
_tid_lock = threading.Lock()
_tid_counter = itertools.count(1)
# Thread object -> [tid, name_emitted_for_pid set]
_tids: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _thread_row(pid) -> tuple:
    """(tid, metadata_event_or_None) for the calling thread."""
    t = threading.current_thread()
    with _tid_lock:
        ent = _tids.get(t)
        if ent is None:
            ent = _tids[t] = [next(_tid_counter), set()]
        tid, seen_pids = ent
        if pid in seen_pids:
            return tid, None
        seen_pids.add(pid)
    return tid, {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                 "args": {"name": t.name}}


@contextlib.contextmanager
def trace(name: str, **attrs) -> Iterator[SpanContext]:
    """Open a span (new trace root, or child of the current span).

    Submissions made inside inherit the span context through task
    metadata and the wire trace field, so worker-side spans link back to
    this one in the timeline dump.  Extra keyword ``attrs`` (and anything
    added to ``ctx.attrs`` inside the block) are merged into the event
    args.  A child of an UNSAMPLED root inherits the sampled-out decision
    and emits nothing (head-based sampling)."""
    parent = _SPAN.get()
    ctx = SpanContext(
        trace_id=parent.trace_id if parent else _new_id(),
        span_id=_new_id(),
        parent_id=parent.span_id if parent else None,
        name=name,
        sampled=parent.sampled if parent else True,
        attrs=dict(attrs) if attrs else None)
    _SPAN.set(ctx)
    t0 = time.time()
    try:
        yield ctx
    finally:
        _SPAN.set(parent)
        if ctx.sampled:
            pid = _host_pid()
            tid, meta = _thread_row(pid)
            args = ctx.to_dict()
            if ctx.attrs:
                args.update(ctx.attrs)
            evs = [] if meta is None else [meta]
            evs.append({"name": name, "cat": "span", "ph": "X",
                        "pid": pid, "tid": tid,
                        "ts": t0 * 1e6, "dur": (time.time() - t0) * 1e6,
                        "args": args})
            _emit(evs)


@contextlib.contextmanager
def request_trace(name: str, **attrs) -> Iterator[Optional[SpanContext]]:
    """Per-request auto-root (e.g. one Serve HTTP request): when no span
    is current, roots a new trace sampled at ``trace_sample_rate``; under
    an existing span it is an ordinary child.  Sampled-out requests carry
    an unsampled context so every downstream propagation point skips the
    work — the whole tree costs one random() call."""
    from ray_tpu._private.config import GLOBAL_CONFIG
    parent = _SPAN.get()
    if parent is None:
        rate = GLOBAL_CONFIG.trace_sample_rate
        sampled = bool(rate > 0.0 and random.random() < rate)
        if GLOBAL_CONFIG.metrics_enabled:
            from ray_tpu.util import metrics_catalog as mcat
            mcat.get("rtpu_trace_sampled_total").inc(
                tags={"decision": "sampled" if sampled else "dropped"})
        if not sampled:
            tok = _SPAN.set(SpanContext(_new_id(), _new_id(), None, name,
                                        sampled=False))
            try:
                yield None
            finally:
                _SPAN.reset(tok)
            return
    with trace(name, **attrs) as ctx:
        yield ctx


def child_span(parent: Optional[SpanContext], name: str) -> SpanContext:
    """A child context of ``parent`` (or a fresh sampled root when
    ``parent`` is None) — for execution paths that carry context by hand
    (task exec, actor dispatch) rather than via the context variable."""
    if parent is None:
        return SpanContext(_new_id(), _new_id(), None, name)
    return SpanContext(parent.trace_id, _new_id(), parent.span_id, name,
                       sampled=parent.sampled)


def emit_span(name: str, parent: Optional[SpanContext], t0: float,
              dur: float, cat: str = "span", pid=None, tid=None,
              **attrs) -> Optional[SpanContext]:
    """Emit one completed span as a child of an EXPLICIT parent context —
    for event-loop / cross-thread code (LLM engine iterations, GCS
    dispatch, data-plane serving) where the context variable does not
    follow the work.  ``t0`` is wall-clock seconds; returns the child
    context (so callers can link further spans under it), or None when
    the parent is absent or sampled out."""
    if parent is None or not parent.sampled:
        return None
    ctx = SpanContext(parent.trace_id, _new_id(), parent.span_id, name)
    if pid is None:
        pid = _host_pid()
    evs: List[dict] = []
    if tid is None:
        tid, meta = _thread_row(pid)
        if meta is not None:
            evs.append(meta)
    args = ctx.to_dict()
    if attrs:
        args.update(attrs)
    evs.append({"name": name, "cat": cat, "ph": "X", "pid": pid,
                "tid": tid, "ts": t0 * 1e6, "dur": dur * 1e6,
                "args": args})
    _emit(evs)
    return ctx


def emit_ctx_span(ctx: Optional[SpanContext], name: str, t0: float,
                  dur: float, cat: str = "span", **attrs) -> None:
    """Emit the completed-span event for an EXISTING context (one whose
    id was already handed to children — e.g. an actor method span set
    before execution): the event must carry that same span_id or the
    children orphan."""
    if ctx is None or not ctx.sampled:
        return
    pid = _host_pid()
    tid, meta = _thread_row(pid)
    evs: List[dict] = [] if meta is None else [meta]
    args = ctx.to_dict()
    if attrs:
        args.update(attrs)
    evs.append({"name": name, "cat": cat, "ph": "X", "pid": pid,
                "tid": tid, "ts": t0 * 1e6, "dur": dur * 1e6,
                "args": args})
    _emit(evs)


def span_event(name: str, parent: Optional[SpanContext], t0: float,
               dur: float, cat: str, pid, tid, **attrs) -> Optional[dict]:
    """Build (but do not ship) one span event as a child of ``parent`` —
    for processes that own an event buffer directly (the GCS appends
    under its own ``_events_lock`` instead of paying an RPC)."""
    if parent is None or not parent.sampled:
        return None
    ctx = SpanContext(parent.trace_id, _new_id(), parent.span_id, name)
    args = ctx.to_dict()
    if attrs:
        args.update(attrs)
    return {"name": name, "cat": cat, "ph": "X", "pid": pid, "tid": tid,
            "ts": t0 * 1e6, "dur": dur * 1e6, "args": args}


def _host_pid() -> str:
    """Timeline row for this process: the executing node for workers,
    'driver' for the driver (matching the task-event convention)."""
    from ray_tpu._private import worker as worker_mod
    w = worker_mod.try_global_worker()
    if w is None or w.role == "driver":
        return "driver"
    return w.node_id or "worker"


def _emit(events) -> None:
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.config import GLOBAL_CONFIG
    if not GLOBAL_CONFIG.timeline_enabled:
        return  # operator disabled the timeline: emit nothing anywhere,
        # so trace trees never appear partially (and GCS events stay flat)
    w = worker_mod.try_global_worker()
    if w is None:
        return
    if GLOBAL_CONFIG.metrics_enabled:
        from ray_tpu.util import metrics_catalog as mcat
        for e in events:
            if e.get("ph") != "M":
                mcat.get("rtpu_trace_spans_total").inc(
                    tags={"cat": e.get("cat", "span")})
    if w.role == "driver":
        # drivers have no task conn; ship via rpc (best effort)
        try:
            w.rpc_oneway("ingest_events", events=events)
        except Exception:  # noqa: BLE001 - tracing must never break work
            pass
    else:
        w._send_event({"kind": "profile_events", "events": events})


def profile_event_lists(out_dir: str):
    """Yield one raw Chrome-trace event list per ``*.trace.json.gz``
    file a jax profiler capture wrote under ``out_dir`` — the single
    parser for jax's profile output layout (re-basing in
    :func:`profile_device` and the overlap breakdown in ``bench.py``
    both consume it, so a layout change breaks one place)."""
    import glob
    import gzip
    import json

    for path in glob.glob(os.path.join(out_dir, "plugins", "profile",
                                       "*", "*.trace.json.gz")):
        data = json.loads(gzip.open(path).read())
        yield data.get("traceEvents", [])


def _rebase_device_events(raw, host_start_us: float, span, name: str
                          ) -> List[dict]:
    """Re-base one jax device-trace event list onto the wall-clock epoch
    axis.  Complete (``X``) events AND counter (``C``) events — memory /
    occupancy series — are carried through; counters keep their value
    args (merged with the span tag) so they render in the merged
    timeline.  Returns [] when the capture held no complete events
    (nothing to anchor the re-basing to)."""
    xs = [e["ts"] for e in raw
          if e.get("ts") is not None and e.get("ph") == "X"]
    if not xs:
        return []
    base = min(xs)
    events: List[dict] = []
    for e in raw:
        ph = e.get("ph")
        if ph not in ("X", "C") or e.get("ts") is None:
            continue
        ev = {"name": e.get("name", "?"), "cat": "device",
              "ph": ph,
              "pid": f"device:{name}",
              "tid": e.get("tid", 0),
              "ts": host_start_us + (e["ts"] - base)}
        if ph == "X":
            ev["dur"] = e.get("dur", 0)
        args = dict(e.get("args") or {}) if ph == "C" else {}
        if span is not None:
            args.update(span.to_dict())
        if args:
            ev["args"] = args
        events.append(ev)
    return events


@contextlib.contextmanager
def profile_device(name: str = "device",
                   keep_dir: Optional[str] = None) -> Iterator[None]:
    """Capture a jax.profiler device trace and merge it onto the cluster
    timeline's clock.

    jax writes a Chrome trace (``*.trace.json.gz``) with timestamps
    relative to capture start; events are re-based to wall-clock epoch µs
    (the timeline's clock) using the capture-start host time, tagged
    cat="device", and shipped to the GCS — one ``ray_tpu.timeline()``
    dump then shows host task/span rows and XLA device rows together."""
    import shutil
    import tempfile

    import jax

    out_dir = keep_dir or tempfile.mkdtemp(prefix="rtpu_devtrace_")
    span = current_span()
    host_start_us = time.time() * 1e6
    try:
        with jax.profiler.trace(out_dir):
            yield
    finally:
        events = []
        try:
            for raw in profile_event_lists(out_dir):
                events.extend(_rebase_device_events(
                    raw, host_start_us, span, name))
        except Exception:  # noqa: BLE001 - tracing must never break work
            events = []
        if events:
            _emit(events)
        if keep_dir is None:
            shutil.rmtree(out_dir, ignore_errors=True)
