"""Span tracing + device-trace merge onto the cluster timeline.

Reference: ``python/ray/util/tracing/`` (SURVEY.md §5.1) — OpenTelemetry
span context rides task/actor metadata so a request's causal tree spans
processes; and ``ray timeline`` renders host-side Chrome trace events.
TPU-native addition (§5.1 rebuild note): ``jax.profiler`` device traces
are merged ONTO THE SAME CLOCK as the host spans, so one
``ray_tpu.timeline()`` dump shows a train step's host dispatch span above
the XLA ops it ran.

Usage::

    from ray_tpu.util import tracing

    with tracing.trace("ingest-and-train"):       # driver: new trace root
        ref = preprocess.remote(batch)            # ctx propagates to tasks
        ...

    with tracing.profile_device("train_step"):    # any process with jax
        state, m = step_fn(state, batch)          # device events captured
        jax.block_until_ready(m)
    # both land in ray_tpu.timeline(): host spans carry
    # trace_id/span_id/parent_id args; device events carry cat="device".
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
import uuid
from typing import Iterator, Optional

_tls = threading.local()


class SpanContext:
    __slots__ = ("trace_id", "span_id", "parent_id", "name")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name

    def to_dict(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name}

    @staticmethod
    def from_dict(d: Optional[dict]) -> Optional["SpanContext"]:
        if not d:
            return None
        return SpanContext(d["trace_id"], d["span_id"],
                           d.get("parent_id"), d.get("name", ""))


def current_span() -> Optional[SpanContext]:
    return getattr(_tls, "span", None)


def _set_span(ctx: Optional[SpanContext]) -> None:
    _tls.span = ctx


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


@contextlib.contextmanager
def trace(name: str) -> Iterator[SpanContext]:
    """Open a span (new trace root, or child of the current span).

    Submissions made inside inherit the span context through task
    metadata, so worker-side spans link back to this one in the
    timeline dump."""
    parent = current_span()
    ctx = SpanContext(
        trace_id=parent.trace_id if parent else _new_id(),
        span_id=_new_id(),
        parent_id=parent.span_id if parent else None,
        name=name)
    _set_span(ctx)
    t0 = time.time()
    try:
        yield ctx
    finally:
        _set_span(parent)
        _emit([{"name": name, "cat": "span", "ph": "X",
                "pid": _host_pid(), "tid": threading.get_ident() % 100000,
                "ts": t0 * 1e6, "dur": (time.time() - t0) * 1e6,
                "args": ctx.to_dict()}])


def _host_pid() -> str:
    """Timeline row for this process: the executing node for workers,
    'driver' for the driver (matching the task-event convention)."""
    from ray_tpu._private import worker as worker_mod
    w = worker_mod.try_global_worker()
    if w is None or w.role == "driver":
        return "driver"
    return w.node_id or "worker"


def _emit(events) -> None:
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.config import GLOBAL_CONFIG
    if not GLOBAL_CONFIG.timeline_enabled:
        return  # operator disabled the timeline: emit nothing anywhere,
        # so trace trees never appear partially (and GCS events stay flat)
    w = worker_mod.try_global_worker()
    if w is None:
        return
    if w.role == "driver":
        # drivers have no task conn; ship via rpc (best effort)
        try:
            w.rpc_oneway("ingest_events", events=events)
        except Exception:  # noqa: BLE001 - tracing must never break work
            pass
    else:
        w._send_event({"kind": "profile_events", "events": events})


@contextlib.contextmanager
def profile_device(name: str = "device",
                   keep_dir: Optional[str] = None) -> Iterator[None]:
    """Capture a jax.profiler device trace and merge it onto the cluster
    timeline's clock.

    jax writes a Chrome trace (``*.trace.json.gz``) with timestamps
    relative to capture start; events are re-based to wall-clock epoch µs
    (the timeline's clock) using the capture-start host time, tagged
    cat="device", and shipped to the GCS — one ``ray_tpu.timeline()``
    dump then shows host task/span rows and XLA device rows together."""
    import glob
    import gzip
    import json
    import shutil
    import tempfile

    import jax

    out_dir = keep_dir or tempfile.mkdtemp(prefix="rtpu_devtrace_")
    span = current_span()
    host_start_us = time.time() * 1e6
    try:
        with jax.profiler.trace(out_dir):
            yield
    finally:
        events = []
        try:
            for path in glob.glob(
                    os.path.join(out_dir, "plugins", "profile", "*",
                                 "*.trace.json.gz")):
                data = json.loads(gzip.open(path).read())
                raw = data.get("traceEvents", [])
                xs = [e["ts"] for e in raw
                      if e.get("ts") is not None and e.get("ph") == "X"]
                if not xs:
                    continue
                base = min(xs)
                for e in raw:
                    if e.get("ph") != "X" or e.get("ts") is None:
                        continue
                    ev = {"name": e.get("name", "?"), "cat": "device",
                          "ph": "X",
                          "pid": f"device:{name}",
                          "tid": e.get("tid", 0),
                          "ts": host_start_us + (e["ts"] - base),
                          "dur": e.get("dur", 0)}
                    if span is not None:
                        ev["args"] = span.to_dict()
                    events.append(ev)
        except Exception:  # noqa: BLE001 - tracing must never break work
            events = []
        if events:
            _emit(events)
        if keep_dir is None:
            shutil.rmtree(out_dir, ignore_errors=True)
