"""Cluster state API: ``list_*`` / ``summarize_*`` / ``object_memory``.

Reference: ``python/ray/util/state/`` (SURVEY.md §2.3) — ``ray list tasks``,
``ray list actors``, ``ray summary``, ``ray memory``.  The data comes from
the GCS's live tables over the normal control-plane RPC; no side channel.
"""

from __future__ import annotations

from collections import Counter as _Counter
from typing import Any, Dict, List, Optional

from ray_tpu._private import worker as _worker_mod


def _rpc(kind: str, **kw) -> dict:
    return _worker_mod.global_worker().rpc(kind, **kw)


# ------------------------------------------------------------------ list_*
def list_nodes() -> List[dict]:
    return _rpc("list_nodes")["nodes"]


def list_actors(state: Optional[str] = None) -> List[dict]:
    actors = _rpc("list_actors")["actors"]
    return [a for a in actors if state is None or a["state"] == state]


def list_tasks(state: Optional[str] = None) -> List[dict]:
    tasks = _rpc("list_tasks")["tasks"]
    return [t for t in tasks if state is None or t["state"] == state]


def list_objects() -> List[dict]:
    return _rpc("list_objects")["objects"]


def list_workers() -> List[dict]:
    return _rpc("list_workers")["workers"]


def list_placement_groups() -> List[dict]:
    pgs = _rpc("pg_table")["pgs"]
    return [{"pg_id": pid, **info} for pid, info in pgs.items()]


# --------------------------------------------------------------- summaries
def summarize_tasks() -> Dict[str, int]:
    return dict(_Counter(t["state"] for t in list_tasks()))


def summarize_actors() -> Dict[str, int]:
    return dict(_Counter(a["state"] for a in list_actors()))


def summarize_objects() -> Dict[str, Any]:
    objs = list_objects()
    by_loc = _Counter(o["loc"] for o in objs if o["loc"])
    return {
        "count": len(objs),
        "total_bytes": sum(o["size"] or 0 for o in objs),
        "by_loc": dict(by_loc),
        "store": _rpc("store_stats")["stats"],
    }


def list_raylets() -> List[dict]:
    """Per-node local-scheduler state (held leases, local queue depth,
    last-reconcile age) for nodes running a raylet (DESIGN.md §4i)."""
    return _rpc("raylet_table")["raylets"]


def fleet_state() -> Dict[str, Any]:
    """Fleet elasticity rollup (DESIGN.md §4j): nodes by lifecycle phase
    (pending / running / draining / terminating), the current demand
    backlog, and the last elastic re-mesh event."""
    resp = _rpc("fleet_state")
    resp.pop("error", None)
    return resp


def metrics_history(expr: str, start: Optional[float] = None,
                    end: Optional[float] = None,
                    step: Optional[float] = None,
                    at: Optional[float] = None) -> List[dict]:
    """Query the head-resident metrics TSDB (DESIGN.md §4k).

    Instant form (default): ``metrics_history('rate(rtpu_tasks_total'
    '{state="ok"}[60s])')`` → ``[{"tags": {...}, "value": float}]``,
    evaluated at ``at`` (default: now).  Range form (any of
    start/end/step given): the expression evaluated at each step →
    ``[{"tags": {...}, "points": [[ts, value], ...]}]``.  Supported
    syntax: label matchers (``=``, ``!=``, ``=~``), ``rate()``,
    ``increase()``, ``avg/min/max_over_time()``,
    ``quantile_over_time(q, ...)``, and ``sum/avg/max/min [by (...)]``
    aggregation — see README § Observability."""
    if start is not None or end is not None or step is not None:
        return _rpc("metrics_query", op="query_range", expr=expr,
                    start=start, end=end, step=step)["results"]
    return _rpc("metrics_query", expr=expr, at=at)["results"]


def metrics_forecast(expr: str, horizon_s: float,
                     period_s: float = 86400.0, smooth_s: float = 600.0,
                     at: Optional[float] = None) -> List[dict]:
    """Seasonal-naive forecast over the TSDB's 48h rungs (DESIGN.md
    §4n): the predicted value of each matching gauge series at ``now +
    horizon_s``, read one ``period_s`` earlier from the ladder — the
    autopilot's lead-time demand signal, exposed for operators too."""
    return _rpc("metrics_query", op="forecast", expr=expr,
                horizon_s=horizon_s, period_s=period_s,
                smooth_s=smooth_s, at=at)["results"]


def autopilot_status(limit: int = 50) -> Dict[str, Any]:
    """The autopilot's recent remediation actions + reflex counters
    (DESIGN.md §4n): ``{"enabled": bool, "actions": [...], "stats":
    {...}}`` — every drain / prewarm / forecast / standby action with
    its outcome (applied | skipped | error) and reason."""
    resp = _rpc("autopilot_status", limit=limit)
    resp.pop("error", None)
    return resp


def profile(window_s: float = 300.0, proc: Optional[str] = None,
            node_id: Optional[str] = None) -> Dict[str, Any]:
    """Query the head-resident continuous-profiling store (DESIGN.md
    §4o): the merged folded-stack histogram over the trailing
    ``window_s`` seconds — ``{"samples": int, "stacks": {folded:
    count}, "procs": [...], "window_s": float}``.  ``proc`` narrows to
    one publisher (worker id or ``role:pid``); ``node_id`` narrows to
    one node.  History for dead processes stays queryable until the
    store's window rolls past it."""
    return _rpc("profile_query", window_s=window_s, proc=proc,
                node_id=node_id)


def profile_diff(window_a: float = 300.0, window_b: float = 300.0,
                 proc: Optional[str] = None) -> Dict[str, Any]:
    """Differential flame query (DESIGN.md §4o): window A = the
    trailing ``window_a`` seconds, window B = the ``window_b`` seconds
    before it.  Returns per-stack sample-fraction deltas (``diff``,
    positive = hotter now) alongside the raw A/B histograms — the
    "what changed" view for regressions."""
    return _rpc("profile_query", op="diff", window_a=window_a,
                window_b=window_b, proc=proc)


def metrics_series(match: Optional[str] = None) -> List[dict]:
    """List the TSDB's series (name, kind, tags, newest-sample age);
    ``match`` filters with selector syntax (``name{label="v"}``)."""
    return _rpc("metrics_query", op="series", match=match)["series"]


def cluster_summary() -> Dict[str, Any]:
    """One-call rollup used by `ray_tpu status`."""
    res = _rpc("cluster_resources")
    return {
        "nodes": len([n for n in list_nodes() if n["alive"]]),
        "resources_total": res["total"],
        "resources_available": res["available"],
        "tasks": summarize_tasks(),
        "actors": summarize_actors(),
        "objects": summarize_objects(),
        "raylets": list_raylets(),
        "fleet": fleet_state(),
    }


# ----------------------------------------------------------- object memory
def object_memory(group_by: str = "loc") -> List[dict]:
    """The `ray memory` equivalent: who holds object bytes, grouped."""
    objs = list_objects()
    groups: Dict[str, dict] = {}
    for o in objs:
        key = str(o.get(group_by))
        g = groups.setdefault(key, {group_by: key, "count": 0, "bytes": 0,
                                    "pinned_refs": 0})
        g["count"] += 1
        g["bytes"] += o["size"] or 0
        g["pinned_refs"] += o["refcount"]
    return sorted(groups.values(), key=lambda g: -g["bytes"])
