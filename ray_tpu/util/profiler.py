"""Always-on sampling profiler + head-side profile store (DESIGN.md §4o).

Two halves:

- **Sampler** (one per non-client process): a jittered daemon thread at
  ``profiler_hz`` walks ``sys._current_frames()`` and folds every
  thread's stack into a bounded aggregate table ("folded" =
  root-to-leaf ``file:func`` labels joined with ``;`` — the flamegraph
  wire format).  A thread currently blocked inside a
  ``WatchdogLock.acquire`` is folded under a synthetic
  ``waiting:<lock>`` leaf frame so lock contention is visible in
  flames.  Deltas ride the §4b metrics-publisher cadence as JSON under
  the reserved ``__profile__/<worker_id>`` KV prefix (same
  reject-foreign-writes / strip-at-snapshot treatment as
  ``__metrics__/``).

- **ProfileStore** (head-resident): fixed-memory windowed receipts —
  per publishing process a bounded deque of ``(ts, folded-delta)``
  windows plus role/pid/node metadata.  History SURVIVES process death
  (windows are pruned only by ring capacity and idle age — the PR 10
  SIGKILL-churn contract), so a post-mortem can still ask what a dead
  worker was doing.  Cluster merges and window diffs are computed at
  query time from copies taken under the store's one no-block leaf
  ``_lock`` (PROFILER_LOCK_DAG).

Plus the dependency-free inline-SVG flamegraph writer behind
``ray_tpu profile --flame`` and the dashboard ``/profile/flame``
endpoint.
"""

from __future__ import annotations

import collections
import json
import os
import random
import sys
import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.util.tsdb import QueryError

PROFILE_KV_PREFIX = "__profile__/"

# one folded bucket absorbs everything past profiler_max_stacks so the
# table stays bounded no matter how polymorphic the workload is
OVERFLOW_KEY = "(overflow)"

MAX_FRAMES = 48          # deepest stack kept per sample (leafward wins)


def is_profile_key(key) -> bool:
    """True for keys under the reserved ``__profile__/`` prefix."""
    if isinstance(key, bytes):
        return key.startswith(b"__profile__/")
    return isinstance(key, str) and key.startswith(PROFILE_KV_PREFIX)


# --------------------------------------------------------------- lock waits
# thread ident -> lock name, written by WatchdogLock.acquire around its
# inner blocking acquire.  Single-key dict ops are GIL-atomic; readers
# (the sampler) tolerate torn iteration by copying.
_WAITING: Dict[int, str] = {}


def note_lock_wait(name: str) -> None:
    _WAITING[threading.get_ident()] = name


def clear_lock_wait() -> None:
    _WAITING.pop(threading.get_ident(), None)


# ------------------------------------------------------------------ sampler
class Sampler:
    """The in-process half: sample, fold, hand off deltas."""

    def __init__(self, role: str, hz: float, max_stacks: int):
        self.role = role
        self._period = 1.0 / max(0.5, float(hz))
        self._max_stacks = max(16, int(max_stacks))
        self._lock = threading.Lock()
        self._table: Dict[str, int] = {}     # guarded by: _lock
        self._samples = 0                    # guarded by: _lock
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"rtpu-profiler-{role}", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        # jittered so a fleet of samplers never beats in phase with the
        # workload (the same 0.75-1.25 spread the metrics publisher uses)
        while not self._stop.wait(self._period * random.uniform(0.75, 1.25)):
            try:
                self._sample_once()
            except Exception:  # noqa: BLE001 - sampling must never hurt
                pass

    def _sample_once(self) -> None:
        frames = sys._current_frames()
        me = threading.get_ident()
        waiting = dict(_WAITING)
        folded: List[str] = []
        for tid, frame in frames.items():
            if tid == me:
                continue
            parts: List[str] = []
            f = frame
            while f is not None and len(parts) < MAX_FRAMES:
                code = f.f_code
                parts.append(os.path.basename(code.co_filename)
                             + ":" + code.co_name)
                f = f.f_back
            parts.reverse()
            lock = waiting.get(tid)
            if lock:
                parts.append("waiting:" + lock)
            folded.append(";".join(parts))
        del frames
        with self._lock:
            self._samples += len(folded)
            for key in folded:
                cur = self._table.get(key)
                if cur is not None:
                    self._table[key] = cur + 1
                elif len(self._table) < self._max_stacks:
                    self._table[key] = 1
                else:
                    self._table[OVERFLOW_KEY] = \
                        self._table.get(OVERFLOW_KEY, 0) + 1

    def take_delta(self) -> Optional[dict]:
        """Swap out and return the aggregate since the last call."""
        with self._lock:
            if not self._samples:
                return None
            table, n = self._table, self._samples
            self._table, self._samples = {}, 0
        return {"samples": n, "stacks": table}

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


_SAMPLER: Optional[Sampler] = None
_install_lock = threading.Lock()


def maybe_install(role: str) -> Optional[Sampler]:
    """Start the process sampler once (first role wins), config-gated."""
    global _SAMPLER
    if not GLOBAL_CONFIG.profiler_enabled:
        return None
    with _install_lock:
        if _SAMPLER is None:
            _SAMPLER = Sampler(role, GLOBAL_CONFIG.profiler_hz,
                               GLOBAL_CONFIG.profiler_max_stacks)
        return _SAMPLER


def installed() -> Optional[Sampler]:
    return _SAMPLER


def close() -> None:
    """Stop and discharge the process sampler (idempotent)."""
    global _SAMPLER
    with _install_lock:
        s, _SAMPLER = _SAMPLER, None
    if s is not None:
        s.stop()


def local_payload(node_id: Optional[str] = None) -> Optional[dict]:
    """Drain the local sampler into a wire payload without the KV hop
    (the GCS head ingests its own samples directly)."""
    s = _SAMPLER
    if s is None:
        return None
    delta = s.take_delta()
    if delta is None:
        return None
    return {"ts": time.time(), "role": s.role, "pid": os.getpid(),
            "node_id": node_id, **delta}


def publish(worker=None) -> bool:
    """Ship the delta since the last publish to the head's KV plane.

    Piggybacks on the metrics publisher's cadence and connection; a
    failed put just drops one (lossy-by-design) sampling window.
    """
    s = _SAMPLER
    if s is None:
        return False
    if worker is None:
        from ray_tpu._private.worker import global_worker
        worker = global_worker()
    delta = s.take_delta()
    if delta is None:
        return False
    from ray_tpu.util import metrics_catalog as mcat
    t0 = time.perf_counter()
    payload = {"ts": time.time(), "role": s.role, "pid": os.getpid(),
               "node_id": getattr(worker, "node_id", None), **delta}
    worker.rpc("kv_put", _reconnect=False,
               key=PROFILE_KV_PREFIX + worker.worker_id,
               value=json.dumps(payload).encode())
    mcat.get("rtpu_profile_samples_total").inc(delta["samples"])
    mcat.get("rtpu_profile_stacks").set(float(len(delta["stacks"])))
    mcat.get("rtpu_profile_publish_seconds").observe(
        time.perf_counter() - t0)
    return True


# ------------------------------------------------------------ profile store
class _Proc:
    __slots__ = ("role", "pid", "node_id", "last_ts", "windows")

    def __init__(self, role, pid, node_id):
        self.role = role
        self.pid = pid
        self.node_id = node_id
        self.last_ts = 0.0
        # (ts, samples, stacks) — stacks dicts are frozen after ingest
        self.windows = collections.deque(
            maxlen=ProfileStore.WINDOWS_PER_PROC)

    def key(self) -> str:
        return f"{self.role}:{self.pid}"


def _merge(into: Dict[str, int], stacks: Dict[str, int]) -> None:
    for k, v in stacks.items():
        into[k] = into.get(k, 0) + int(v)


class ProfileStore:
    """Head-side fixed-memory windowed folded-stack aggregates."""

    WINDOWS_PER_PROC = 60     # ~1h at the 60s publish cadence
    MAX_PROCS = 128           # churned-through dead procs beyond this
    IDLE_PRUNE_S = 3600.0     # are evicted oldest-first / past idle age

    def __init__(self, clock=time.time):
        self._clock = clock
        self._lock = threading.Lock()
        self._procs: Dict[str, _Proc] = {}   # guarded by: _lock

    def ingest(self, worker_id: str, value) -> bool:
        """One ``__profile__/`` receipt (bytes or dict) -> the rings."""
        try:
            payload = value if isinstance(value, dict) \
                else json.loads(value)
            stacks = payload["stacks"]
            samples = int(payload["samples"])
            ts = float(payload.get("ts") or self._clock())
            if not isinstance(stacks, dict) or samples <= 0:
                return False
            stacks = {str(k): int(v) for k, v in stacks.items()}
        except (KeyError, TypeError, ValueError):
            return False
        role = str(payload.get("role") or "worker")
        pid = int(payload.get("pid") or 0)
        node_id = payload.get("node_id")
        now = self._clock()
        evict: List[_Proc] = []
        with self._lock:
            p = self._procs.get(worker_id)
            if p is None:
                p = self._procs[worker_id] = _Proc(role, pid, node_id)
            p.role, p.pid = role, pid
            if node_id:
                p.node_id = node_id
            p.last_ts = max(p.last_ts, ts)
            p.windows.append((ts, samples, stacks))
            if len(self._procs) > self.MAX_PROCS:
                victim = min(self._procs, key=lambda k:
                             self._procs[k].last_ts)
                evict.append(self._procs.pop(victim))
            for k in [k for k, q in self._procs.items()
                      if now - q.last_ts > self.IDLE_PRUNE_S]:
                evict.append(self._procs.pop(k))
        del evict
        return True

    def _copy_windows(self, since: float, until: float, proc=None,
                      node_id=None):
        """Window refs + proc meta, copied out under the leaf."""
        out = []
        meta = []
        with self._lock:
            for wid, p in self._procs.items():
                if proc is not None and proc not in (wid, p.key()):
                    continue
                if node_id is not None and p.node_id != node_id:
                    continue
                wins = [w for w in p.windows if since <= w[0] <= until]
                meta.append({"proc": p.key(), "worker_id": wid,
                             "role": p.role, "pid": p.pid,
                             "node_id": p.node_id, "last_ts": p.last_ts,
                             "windows": len(wins)})
                out.extend(wins)
        return out, meta

    def _aggregate(self, since: float, until: float, proc=None,
                   node_id=None) -> dict:
        wins, meta = self._copy_windows(since, until, proc, node_id)
        merged: Dict[str, int] = {}
        samples = 0
        for _, n, stacks in wins:
            samples += n
            _merge(merged, stacks)
        return {"samples": samples, "stacks": merged, "procs": meta}

    def profile(self, window_s: float = 300.0, proc=None,
                node_id=None) -> dict:
        if not (window_s > 0):
            raise QueryError(f"bad window_s {window_s!r}")
        now = self._clock()
        out = self._aggregate(now - float(window_s), now, proc, node_id)
        out["window_s"] = float(window_s)
        return out

    def diff(self, window_a: float, window_b: float, proc=None) -> dict:
        """Recent window A = [now-a, now] vs baseline B of length b
        immediately before it; ``diff`` is A's per-sample fraction
        minus B's for every stack in either."""
        if not (window_a > 0 and window_b > 0):
            raise QueryError(
                f"bad diff windows {window_a!r}/{window_b!r}")
        now = self._clock()
        a = self._aggregate(now - window_a, now, proc)
        b = self._aggregate(now - window_a - window_b,
                            now - window_a, proc)
        diff: Dict[str, float] = {}
        na, nb = max(1, a["samples"]), max(1, b["samples"])
        for k in set(a["stacks"]) | set(b["stacks"]):
            diff[k] = round(a["stacks"].get(k, 0) / na
                            - b["stacks"].get(k, 0) / nb, 6)
        return {"window_a_s": float(window_a),
                "window_b_s": float(window_b),
                "a": {"samples": a["samples"], "stacks": a["stacks"]},
                "b": {"samples": b["samples"], "stacks": b["stacks"]},
                "diff": diff}

    def stats(self) -> dict:
        with self._lock:
            return {"procs": len(self._procs),
                    "windows": sum(len(p.windows)
                                   for p in self._procs.values())}


# ------------------------------------------------------------ presentation
def parse_duration(text) -> float:
    """``'90'``/``'90s'``/``'5m'``/``'2h'`` -> seconds (QueryError on
    junk) — the CLI/dashboard window grammar."""
    if isinstance(text, (int, float)) and not isinstance(text, bool):
        val = float(text)
    else:
        s = str(text).strip().lower()
        mult = 1.0
        if s.endswith(("s", "m", "h")):
            mult = {"s": 1.0, "m": 60.0, "h": 3600.0}[s[-1]]
            s = s[:-1]
        try:
            val = float(s) * mult
        except ValueError:
            raise QueryError(f"bad duration {text!r}") from None
    if not (val > 0) or val != val:
        raise QueryError(f"bad duration {text!r}")
    return val


def folded_text(stacks: Dict[str, int]) -> str:
    """Brendan Gregg folded format, heaviest first."""
    rows = sorted(stacks.items(), key=lambda kv: (-kv[1], kv[0]))
    return "\n".join(f"{k} {v}" for k, v in rows)


def _esc(s: str) -> str:
    return (s.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;").replace('"', "&quot;"))


def _color(name: str) -> str:
    # deterministic warm palette keyed on the frame label; the
    # synthetic lock-wait frames render cold blue so contention pops
    if name.startswith("waiting:"):
        return "rgb(90,130,210)"
    h = 0
    for ch in name:
        h = (h * 131 + ord(ch)) & 0xFFFFFF
    return (f"rgb({205 + (h % 50)},"
            f"{80 + ((h >> 8) % 100)},{(h >> 16) % 60})")


def render_flame_svg(stacks: Dict[str, int],
                     title: str = "ray_tpu flame",
                     width: int = 1200) -> str:
    """Dependency-free flamegraph: folded aggregate -> inline SVG."""
    root: dict = {"c": {}, "v": 0}
    for folded, count in stacks.items():
        if not folded:
            continue
        root["v"] += count
        node = root
        for part in folded.split(";"):
            node = node["c"].setdefault(part, {"c": {}, "v": 0})
            node["v"] += count
    total = root["v"]
    row_h, font = 16, 11
    rects: List[str] = []

    def emit(name, node, x, y, w):
        if w < 0.5:
            return
        pct = 100.0 * node["v"] / total
        label = _esc(name)
        rects.append(
            f'<g><title>{label} ({node["v"]} samples, {pct:.1f}%)'
            f'</title><rect x="{x:.1f}" y="{y}" width="{w:.1f}" '
            f'height="{row_h - 1}" fill="{_color(name)}" rx="1"/>'
            + (f'<text x="{x + 2:.1f}" y="{y + row_h - 5}" '
               f'font-size="{font}" font-family="monospace" '
               f'fill="#fff">{label[:max(1, int(w / 7))]}</text>'
               if w > 20 else "") + "</g>")
        cx = x
        for cname in sorted(node["c"]):
            child = node["c"][cname]
            cw = w * child["v"] / node["v"]
            emit(cname, child, cx, y + row_h, cw)
            cx += cw

    def depth(node):
        return 1 + max((depth(c) for c in node["c"].values()),
                       default=0)

    if total <= 0:
        height = 2 * row_h + 24
        body = (f'<text x="8" y="{row_h + 30}" font-size="{font + 1}" '
                f'font-family="monospace">no samples in window</text>')
    else:
        height = (depth(root) + 1) * row_h + 24
        emit("all", root, 0.0, 24, float(width))
        body = "".join(rects)
    return (f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
            f'height="{height}" viewBox="0 0 {width} {height}">'
            f'<rect width="100%" height="100%" fill="#fbf6ee"/>'
            f'<text x="8" y="16" font-size="{font + 2}" '
            f'font-family="monospace" font-weight="bold">'
            f'{_esc(title)} — {total} samples</text>{body}</svg>')
