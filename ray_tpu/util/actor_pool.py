"""ActorPool — map work over a fixed set of actors.

Reference: ``python/ray/util/actor_pool.py`` (SURVEY.md §2.3 "ray.util
misc") — same API surface: submit / get_next / get_next_unordered / map /
map_unordered / has_next / push / pop_idle.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional, Tuple

import ray_tpu


class ActorPool:
    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        if not self._idle:
            raise ValueError("ActorPool needs at least one actor")
        self._future_to_actor: dict = {}
        self._index_to_future: dict = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._consumed_unordered: set = set()
        self._pending_submits: List[Tuple[Callable, Any]] = []

    # -- submission ----------------------------------------------------------
    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef; queued if no actor is idle."""
        if self._idle:
            self._dispatch(fn, value, reraise=True)
        else:
            self._pending_submits.append((fn, value))

    def _dispatch(self, fn: Callable, value: Any, *, reraise: bool) -> None:
        actor = self._idle.pop()
        try:
            ref = fn(actor, value)
        except BaseException:
            # a raising submit fn must not leak the actor out of the pool —
            # and when invoked from a drain inside get_next's finally,
            # must not mask the result being returned
            self._idle.append(actor)
            if reraise:
                raise
            import logging
            logging.getLogger(__name__).exception(
                "ActorPool submit fn raised; dropping queued item")
            return
        self._future_to_actor[ref] = actor
        self._index_to_future[self._next_task_index] = ref
        self._next_task_index += 1

    def _maybe_drain(self) -> None:
        while self._pending_submits and self._idle:
            fn, value = self._pending_submits.pop(0)
            self._dispatch(fn, value, reraise=False)

    # -- retrieval -----------------------------------------------------------
    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending_submits)

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in submission order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        # skip indices already taken by get_next_unordered
        while self._next_return_index in self._consumed_unordered:
            self._consumed_unordered.discard(self._next_return_index)
            self._next_return_index += 1
        idx = self._next_return_index
        if idx not in self._index_to_future:
            self._maybe_drain()
            if idx not in self._index_to_future:
                raise StopIteration("no pending results")
        # wait non-destructively first: a timeout must leave pool state
        # intact, and a task exception must still return the actor
        ref = self._index_to_future[idx]
        ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next timed out")
        del self._index_to_future[idx]
        self._next_return_index += 1
        try:
            return ray_tpu.get(ref)
        finally:
            self._return_actor(ref)

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Next result to finish, any order."""
        if not self.has_next():
            raise StopIteration("no pending results")
        self._maybe_drain()
        ready, _ = ray_tpu.wait(list(self._future_to_actor), num_returns=1,
                                timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        for idx, f in list(self._index_to_future.items()):
            if f == ref:
                del self._index_to_future[idx]
                self._consumed_unordered.add(idx)
                break
        try:
            return ray_tpu.get(ref)
        finally:
            self._return_actor(ref)

    def _return_actor(self, ref) -> None:
        actor = self._future_to_actor.pop(ref, None)
        if actor is not None:
            self._idle.append(actor)
            self._maybe_drain()

    # -- bulk ----------------------------------------------------------------
    def map(self, fn: Callable[[Any, Any], Any], values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any],
                      values: Iterable[Any]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # -- membership ----------------------------------------------------------
    def push(self, actor: Any) -> None:
        self._idle.append(actor)
        self._maybe_drain()

    def pop_idle(self) -> Optional[Any]:
        return self._idle.pop() if self._idle else None

    def has_free(self) -> bool:
        return bool(self._idle)
