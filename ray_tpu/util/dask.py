"""Dask-on-Ray-equivalent scheduler.

Reference: ``python/ray/util/dask/`` (SURVEY.md §2.3 ray.util misc) —
``ray_dask_get`` is a drop-in dask scheduler: each graph task becomes a
framework task, intermediate results stay in the object store, and
shared dependencies are computed once.

Dask is not installed in this image, so this implements the *dask graph
protocol* directly (a graph is a plain dict of ``key -> computation``
where a computation is a ``(callable, *args)`` tuple, a key reference,
or a literal — the protocol is dependency-free by design).  With dask
present, pass ``get=ray_tpu.util.dask.ray_dask_get`` to ``compute()``
exactly like the reference.
"""

from __future__ import annotations

from typing import Any, Dict, Hashable, Sequence, Union

import ray_tpu

__all__ = ["ray_dask_get"]


def _is_task(x: Any) -> bool:
    return isinstance(x, tuple) and len(x) > 0 and callable(x[0])


def _is_key(x: Any, dsk: Dict) -> bool:
    # dask keys are str|bytes|int|float or TUPLES thereof (collection
    # chunks like ('x', 0)) — a tuple whose head is callable is a task,
    # everything else hashable that appears in the graph is a key
    if _is_task(x) or isinstance(x, list):
        return False
    try:
        return x in dsk
    except TypeError:
        return False


def _deps_of(comp: Any, dsk: Dict) -> set:
    out: set = set()

    def walk(x):
        if _is_task(x):
            for a in x[1:]:
                walk(a)
        elif isinstance(x, list):
            for a in x:
                walk(a)
        elif _is_key(x, dsk):
            out.add(x)
        elif type(x) is tuple:
            # exact-type, matching ev(): tuple SUBCLASSES (NamedTuples)
            # are literal data on both walks — descending here but not in
            # ev() would ship a dep that never gets substituted
            for a in x:
                walk(a)

    walk(comp)
    return out


@ray_tpu.remote
def _exec_task(comp_blob: bytes, *dep_vals):
    import cloudpickle
    comp, dep_keys = cloudpickle.loads(comp_blob)
    env = dict(zip(dep_keys, dep_vals))

    def ev(x):
        if _is_task(x):
            return x[0](*[ev(a) for a in x[1:]])
        if isinstance(x, list):
            return [ev(a) for a in x]
        try:
            if isinstance(x, Hashable) and x in env:
                return env[x]
        except TypeError:
            pass
        if type(x) is tuple:
            # mirror _deps_of: keys may hide inside plain (non-task)
            # tuples — substitute them and rebuild the tuple.  Exact-type
            # check: tuple subclasses (NamedTuples) are literal data and
            # can never be dask keys; rebuilding would downcast them.
            return tuple(ev(a) for a in x)
        return x

    return ev(comp)


def ray_dask_get(dsk: Dict, keys: Union[Sequence, Any], **_: Any):
    """Execute a dask graph with framework tasks; returns computed keys
    in the same (possibly nested-list) structure dask uses."""
    import cloudpickle

    refs: Dict[Any, Any] = {}
    # literals (plain values, no task/key content) never need a remote
    # task — dask collection graphs carry hundreds of them; computing the
    # dependency map ONCE keeps chains O(V+E) instead of O(V^2)
    remaining: Dict[Any, Any] = {}
    dep_map: Dict[Any, set] = {}
    for key, comp in dsk.items():
        deps = _deps_of(comp, dsk)
        if not deps and not _is_task(comp) and not isinstance(comp, list):
            refs[key] = ray_tpu.put(comp)
        else:
            remaining[key] = comp
            dep_map[key] = deps
    guard = len(remaining) + 1
    while remaining:
        guard -= 1
        if guard < 0:
            raise ValueError("cycle detected in dask graph")
        progressed = []
        for key, comp in remaining.items():
            deps = dep_map[key]
            if any(d in remaining for d in deps):
                continue
            dep_keys = sorted(deps, key=repr)
            blob = cloudpickle.dumps((comp, dep_keys))
            refs[key] = _exec_task.remote(blob, *[refs[d] for d in dep_keys])
            progressed.append(key)
        for key in progressed:
            del remaining[key]
        if not progressed and remaining:
            raise ValueError(
                f"unresolvable keys in dask graph: {sorted(remaining, key=repr)[:5]}")

    def fetch(ks):
        if isinstance(ks, list):
            return [fetch(k) for k in ks]
        return ray_tpu.get(refs[ks])

    return fetch(keys if isinstance(keys, list) else [keys])[0] \
        if not isinstance(keys, list) else fetch(keys)
