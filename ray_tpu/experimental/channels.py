"""Experimental: shared-memory channels + compiled actor DAGs.

Reference: ``python/ray/experimental/channel`` + compiled graphs (aDAG)
— newer-vintage upstream features (SURVEY.md §2.6): a ``Channel`` is a
pre-allocated single-producer/single-consumer transport that bypasses
the control plane entirely, and a compiled graph pre-wires channels
through a static DAG of actor methods so repeated executions pay zero
per-call scheduling.

TPU-first framing: the compiled in-mesh program already IS the compiled
dataflow for device work; these channels cover the HOST side — e.g.
feeding an inference actor chain at high rate without per-call
control-plane messages.

``Channel``: a /dev/shm ring buffer (mmap) with head/tail counters and
spin-then-sleep waits; payloads are pickled objects.  Same-host only —
exactly the reference's primary (shared-memory) channel; cross-host
channels fall back to the normal actor-call path when compiled.

``compile_chain``: the aDAG-lite — a linear pipeline of actor methods.
Each hop gets a channel; each actor runs a pump thread reading its
input channel, applying the bound method, writing its output channel.
``execute()`` writes the input channel and reads the final output —
no task submission, no GCS traffic, per-hop latency is a shm write +
wakeup.  All chain actors must live on the driver's host (the channel
re-attach fails with a clear error otherwise); cross-host stages should
use the normal actor-call path.
"""

from __future__ import annotations

import mmap
import os
import pickle
import struct
import time
import uuid
from typing import Any, List, Optional

import ray_tpu

_HDR = struct.Struct("<QQ")          # head (write cursor), tail (read cursor)
_LEN = struct.Struct("<I")


class Channel:
    """SPSC ring buffer over a /dev/shm segment.

    One writer process, one reader process; ``put`` blocks while full,
    ``get`` blocks while empty (spin briefly, then sleep-poll — the
    reference channel uses the same wait shape)."""

    def __init__(self, capacity_bytes: int = 4 * 1024 * 1024,
                 name: Optional[str] = None, create: bool = True):
        self.name = name or f"rtpu_chan_{uuid.uuid4().hex[:12]}"
        self.capacity = capacity_bytes
        path = f"/dev/shm/{self.name}"
        if create:
            fd = os.open(path, os.O_CREAT | os.O_RDWR | os.O_EXCL, 0o600)
            try:
                os.ftruncate(fd, _HDR.size + capacity_bytes)
            finally:
                os.close(fd)
        self._attach()

    def _attach(self) -> None:
        path = f"/dev/shm/{self.name}"
        try:
            fd = os.open(path, os.O_RDWR)
        except FileNotFoundError:
            raise RuntimeError(
                f"channel segment {path} not found: shm channels are "
                f"same-host only — this process is not on the creating "
                f"host (use normal actor calls for cross-host stages)")
        try:
            size = os.fstat(fd).st_size
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.capacity = len(self._mm) - _HDR.size

    # channels pickle by name: the receiving process re-attaches
    def __getstate__(self):
        return {"name": self.name, "capacity": self.capacity}

    def __setstate__(self, st):
        self.name = st["name"]
        self.capacity = st["capacity"]
        self._attach()

    # ------------------------------------------------------------------ ring
    def _cursors(self):
        return _HDR.unpack_from(self._mm, 0)

    def _set_head(self, v: int) -> None:
        struct.pack_into("<Q", self._mm, 0, v)

    def _set_tail(self, v: int) -> None:
        struct.pack_into("<Q", self._mm, 8, v)

    def _write_bytes(self, off: int, data: bytes) -> None:
        base = _HDR.size
        pos = off % self.capacity
        first = min(len(data), self.capacity - pos)
        self._mm[base + pos:base + pos + first] = data[:first]
        if first < len(data):
            self._mm[base:base + len(data) - first] = data[first:]

    def _read_bytes(self, off: int, n: int) -> bytes:
        base = _HDR.size
        pos = off % self.capacity
        first = min(n, self.capacity - pos)
        out = bytes(self._mm[base + pos:base + pos + first])
        if first < n:
            out += bytes(self._mm[base:base + n - first])
        return out

    def _wait(self, cond, timeout: Optional[float]):
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        while True:
            v = cond()
            if v is not None:
                return v
            spins += 1
            if spins < 200:      # ~burst latency: pure spin
                continue
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"channel {self.name} wait timed out")
            time.sleep(0.0002)

    # ------------------------------------------------------------------- api
    def put(self, value: Any, timeout: Optional[float] = None) -> None:
        data = pickle.dumps(value, protocol=5)
        need = _LEN.size + len(data)
        if need > self.capacity:
            raise ValueError(f"object of {len(data)}B exceeds channel "
                             f"capacity {self.capacity}B")

        def has_room():
            head, tail = self._cursors()
            return head if self.capacity - (head - tail) >= need else None

        head = self._wait(has_room, timeout)
        self._write_bytes(head, _LEN.pack(len(data)))
        self._write_bytes(head + _LEN.size, data)
        self._set_head(head + need)   # publish after the payload is in

    def get(self, timeout: Optional[float] = None) -> Any:
        def has_item():
            head, tail = self._cursors()
            return tail if head - tail >= _LEN.size else None

        tail = self._wait(has_item, timeout)
        (n,) = _LEN.unpack(self._read_bytes(tail, _LEN.size))

        def full_item():
            head, _ = self._cursors()
            return tail if head - tail >= _LEN.size + n else None

        self._wait(full_item, timeout)
        data = self._read_bytes(tail + _LEN.size, n)
        value = pickle.loads(data)
        self._set_tail(tail + _LEN.size + n)
        return value

    def close(self) -> None:
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass

    def destroy(self) -> None:
        self.close()
        try:
            os.unlink(f"/dev/shm/{self.name}")
        except OSError:
            pass


def _pump(instance, method_name: str, in_chan: Channel, out_chan: Channel,
          stop_flag: dict) -> None:
    method = getattr(instance, method_name)

    def put_checked(item) -> bool:
        """Bounded put that honors the stop flag while the ring is full
        (an unbounded put would strand this thread forever if the
        downstream consumer died)."""
        while not stop_flag.get("stop"):
            try:
                out_chan.put(item, timeout=0.5)
                return True
            except TimeoutError:
                continue
        return False

    while not stop_flag.get("stop"):
        try:
            item = in_chan.get(timeout=0.5)
        except TimeoutError:
            continue
        if isinstance(item, _Stop):
            put_checked(item)
            return
        if isinstance(item, _Err):
            put_checked(item)   # forward the ORIGINAL upstream error —
            continue            # feeding it to this stage would mask it
        try:
            out = method(item)
        except Exception as e:  # noqa: BLE001 - surfaced to the caller
            try:
                put_checked(_Err(e))
            except Exception:   # noqa: BLE001 - unpicklable exception:
                # a crashed pump would wedge the chain with no diagnosis
                put_checked(_Err(RuntimeError(
                    f"stage {method_name} error (unpicklable): {e!r}")))
            continue
        try:
            put_checked(out)
        except Exception as e:  # noqa: BLE001 - unpicklable/oversized
            # RESULT: forward a descriptive error instead of dying (a
            # dead pump wedges the chain with only a bare timeout)
            put_checked(_Err(RuntimeError(
                f"stage {method_name} result not transportable: {e!r}")))


class _Stop:
    pass


class _Err:
    def __init__(self, e: BaseException):
        self.e = e


class CompiledChain:
    """A pre-wired pipeline: input channel → actor method → ... → output.

    ``execute`` is synchronous; ``execute_async``/``result`` overlap
    pipeline stages across consecutive inputs (each hop has its own
    channel, so N in-flight items occupy N stages concurrently)."""

    def __init__(self, actors: List[Any], methods: List[str],
                 capacity_bytes: int = 4 * 1024 * 1024):
        assert len(actors) == len(methods) and actors
        self._chain_id = uuid.uuid4().hex[:12]
        self._chans = [Channel(capacity_bytes)
                       for _ in range(len(actors) + 1)]
        self._actors = actors
        self._inflight = 0
        # start a pump thread inside every actor (same-host shm channels)
        refs = []
        try:
            for i, (a, m) in enumerate(zip(actors, methods)):
                refs.append(a.rtpu_channel_pump_start.remote(
                    m, self._chans[i], self._chans[i + 1], self._chain_id))
            ray_tpu.get(refs)  # pumps running before first execute
        except BaseException:
            # partial start (e.g. a dead actor): stop the pumps that DID
            # start and free the segments, or they leak forever
            for a in actors:
                try:
                    a.rtpu_channel_pump_stop.remote(self._chain_id)
                except Exception:  # noqa: BLE001
                    pass
            for c in self._chans:
                c.destroy()
            raise

    def execute(self, value: Any, timeout: Optional[float] = 60.0) -> Any:
        self.execute_async(value, timeout=timeout)
        return self.result(timeout=timeout)

    def execute_async(self, value: Any,
                      timeout: Optional[float] = 60.0) -> None:
        # bounded: a dead/stalled first stage must surface as a
        # TimeoutError here, not an unkillable spin in the ring wait
        self._chans[0].put(value, timeout=timeout)
        self._inflight += 1

    def result(self, timeout: Optional[float] = 60.0) -> Any:
        if self._inflight <= 0:
            raise RuntimeError("no execution in flight")
        out = self._chans[-1].get(timeout=timeout)
        self._inflight -= 1
        if isinstance(out, _Err):
            raise out.e
        return out

    def teardown(self) -> None:
        try:
            self._chans[0].put(_Stop(), timeout=1.0)
            self._chans[-1].get(timeout=5.0)  # drained through every stage
        except (TimeoutError, OSError):
            pass
        # belt and braces: raise every pump's stop flag too — if the
        # _Stop could not flow (full ring, dead stage) the threads exit
        # at their next 0.5s poll instead of leaking forever
        try:
            ray_tpu.get([a.rtpu_channel_pump_stop.remote(self._chain_id)
                         for a in self._actors], timeout=10)
        except Exception:  # noqa: BLE001 - actor may already be dead
            pass
        for c in self._chans:
            c.destroy()


def enable_channels(actor_cls):
    """Class decorator: adds the channel-pump entry point to an actor.

    (The reference injects its accelerated-DAG machinery into every
    actor; here opting in is explicit.)"""
    def rtpu_channel_pump_start(self, method, in_chan, out_chan,
                                chain_id="default"):
        import threading
        flag = {}
        t = threading.Thread(target=_pump,
                             args=(self, method, in_chan, out_chan, flag),
                             daemon=True, name="channel-pump")
        t.start()
        if not hasattr(self, "_rtpu_pump_flags"):
            self._rtpu_pump_flags = {}
        # scoped per chain: tearing one chain down must not kill the
        # pumps another live chain runs on this same actor
        self._rtpu_pump_flags.setdefault(chain_id, []).append(flag)
        return True

    def rtpu_channel_pump_stop(self, chain_id=None):
        """Stop one chain's pumps, or ALL pumps when called with no
        chain id (the orphan-recovery escape hatch)."""
        flags = getattr(self, "_rtpu_pump_flags", {})
        for cid in ([chain_id] if chain_id is not None else list(flags)):
            for flag in flags.pop(cid, []):
                flag["stop"] = True
        return True

    actor_cls.rtpu_channel_pump_start = rtpu_channel_pump_start
    actor_cls.rtpu_channel_pump_stop = rtpu_channel_pump_stop
    return actor_cls


def compile_chain(bindings: List[tuple],
                  capacity_bytes: int = 4 * 1024 * 1024) -> CompiledChain:
    """``bindings``: [(actor_handle, "method"), ...] — a linear DAG.
    Actor classes must be decorated with ``@enable_channels`` (below
    ``@ray_tpu.remote``)."""
    actors = [a for a, _ in bindings]
    methods = [m for _, m in bindings]
    return CompiledChain(actors, methods, capacity_bytes)
