"""Cluster-wide internal key-value store client.

Reference: ``python/ray/experimental/internal_kv.py`` — a thin client over
the GCS ``InternalKVManager`` (SURVEY.md §2.1).  Used by the collective
layer for rendezvous, by Train for worker-group coordination, and by Serve
for config snapshots.  Keys are strings, values are bytes.
"""

from __future__ import annotations

from typing import List, Optional

from ray_tpu._private import worker as _worker_mod


def _w():
    return _worker_mod.global_worker()


def _internal_kv_initialized() -> bool:
    return _worker_mod.try_global_worker() is not None


def _internal_kv_put(key: str, value: bytes, overwrite: bool = True,
                     namespace: str = "default") -> bool:
    """Store ``value``; returns True if the key already existed."""
    resp = _w().rpc("kv_put", key=key, value=bytes(value),
                    overwrite=overwrite, namespace=namespace)
    return bool(resp["existed"])


def _internal_kv_get(key: str, namespace: str = "default") -> Optional[bytes]:
    return _w().rpc("kv_get", key=key, namespace=namespace)["value"]


def _internal_kv_exists(key: str, namespace: str = "default") -> bool:
    return _internal_kv_get(key, namespace=namespace) is not None


def _internal_kv_del(key: str, namespace: str = "default") -> bool:
    return bool(_w().rpc("kv_del", key=key, namespace=namespace)["deleted"])


def _internal_kv_list(prefix: str, namespace: str = "default") -> List[str]:
    return list(_w().rpc("kv_keys", prefix=prefix, namespace=namespace)["keys"])
