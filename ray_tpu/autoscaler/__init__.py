"""Autoscaler: demand-driven node scaling (reference:
``python/ray/autoscaler/``; SURVEY.md §2.3)."""

from ray_tpu.autoscaler.autoscaler import (  # noqa: F401
    AutoscalerConfig, AutoscalerLoop, StandardAutoscaler,
)
from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    FakeMultiNodeProvider, NodeProvider,
)
from ray_tpu.autoscaler.resource_demand_scheduler import (  # noqa: F401
    get_nodes_to_launch, infeasible_shapes,
)
