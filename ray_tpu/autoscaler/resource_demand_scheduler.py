"""Bin-packing: pending resource demand → nodes to launch.

Reference: ``python/ray/autoscaler/_private/resource_demand_scheduler.py``
(SURVEY.md §2.3) — the autoscaler packs the resource shapes of pending
tasks/actors/PG bundles onto hypothetical nodes of the configured node
types and launches the difference.  TPU note: a demand shape may name a
slice resource (e.g. ``{"tpu-v4-8": 1}``) that only one node type offers —
slice-shaped work therefore scales the right pool.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Tuple

ResourceDict = Dict[str, float]


def _fits(avail: ResourceDict, shape: ResourceDict) -> bool:
    return all(avail.get(k, 0.0) >= v for k, v in shape.items() if v > 0)


def _consume(avail: ResourceDict, shape: ResourceDict) -> None:
    for k, v in shape.items():
        avail[k] = avail.get(k, 0.0) - v


def get_nodes_to_launch(
        node_types: Dict[str, dict],
        current_counts: Dict[str, int],
        demand: List[ResourceDict],
        max_total_nodes: int = 1000) -> Dict[str, int]:
    """Decide how many nodes of each type to launch.

    node_types: {type: {"resources": {...}, "min_workers": n,
                        "max_workers": n}}.
    current_counts: live nodes per type.  demand: pending resource shapes
    (one per queued task/actor/bundle).  Returns {type: count} to launch.
    """
    to_launch: Dict[str, int] = {}
    counts = dict(current_counts)
    pools: List[Tuple[str, ResourceDict]] = []  # launched-but-unfilled nodes

    # 1. honor min_workers — these fresh nodes join the packing pools so
    # step 2 places demand on them before launching extras
    for t, cfg in node_types.items():
        need = cfg.get("min_workers", 0) - counts.get(t, 0)
        if need > 0:
            to_launch[t] = to_launch.get(t, 0) + need
            counts[t] = counts.get(t, 0) + need
            for _ in range(need):
                pools.append((t, dict(cfg["resources"])))

    # 2. pack remaining demand onto (existing capacity is handled by the
    # caller passing only UNFULFILLED demand) hypothetical new nodes,
    # largest shapes first so big bundles don't fragment
    for shape in sorted(demand, key=lambda s: -sum(s.values())):
        placed = False
        for _, avail in pools:
            if _fits(avail, shape):
                _consume(avail, shape)
                placed = True
                break
        if placed:
            continue
        # launch the cheapest node type that fits the shape
        for t, cfg in sorted(node_types.items(),
                             key=lambda kv: sum(kv[1]["resources"].values())):
            res = cfg["resources"]
            if not _fits(dict(res), shape):
                continue
            if counts.get(t, 0) >= cfg.get("max_workers", max_total_nodes):
                continue
            if sum(counts.values()) >= max_total_nodes:
                break
            avail = dict(res)
            _consume(avail, shape)
            pools.append((t, avail))
            to_launch[t] = to_launch.get(t, 0) + 1
            counts[t] = counts.get(t, 0) + 1
            placed = True
            break
        # unplaceable shape (no type big enough): skipped — surfaced by the
        # autoscaler as infeasible
    return to_launch


def infeasible_shapes(node_types: Dict[str, dict],
                      demand: List[ResourceDict]) -> List[ResourceDict]:
    """Shapes no configured node type can ever satisfy."""
    out = []
    for shape in demand:
        if not any(_fits(dict(cfg["resources"]), shape)
                   for cfg in node_types.values()):
            out.append(shape)
    return out
