"""Node providers: the autoscaler's cloud abstraction.

Reference: ``python/ray/autoscaler/node_provider.py`` + provider plugins
(AWS/GCP/K8s/fake_multi_node; SURVEY.md §2.3).  A provider knows how to
create/terminate/list nodes of named *node types*; the autoscaler decides
how many of each.  Shipped providers:

- :class:`FakeMultiNodeProvider` — adds/removes logical nodes in a running
  cluster via the control-plane ``add_node``/``remove_node`` RPCs (the
  reference's ``fake_multi_node`` test provider).
- :class:`~ray_tpu.autoscaler.kube.GkeTpuNodeProvider` — the real K8s
  REST provider (``autoscaler/kube.py``): node pools of TPU slices via
  the apiserver, GKE TPU node selectors, e2e-tested against a fake
  apiserver (``tests/test_autoscaler_kube.py``).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional

NODE_KIND_HEAD = "head"
NODE_KIND_WORKER = "worker"

TAG_NODE_KIND = "node-kind"
TAG_NODE_TYPE = "node-type"
TAG_NODE_STATUS = "node-status"

STATUS_UP_TO_DATE = "up-to-date"
STATUS_TERMINATED = "terminated"


class NodeProvider:
    """Interface; all methods operate on provider-native node ids."""

    def __init__(self, provider_config: Dict[str, Any], cluster_name: str):
        self.provider_config = provider_config
        self.cluster_name = cluster_name

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> Dict[str, str]:
        raise NotImplementedError

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> List[str]:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def internal_ip(self, node_id: str) -> str:
        return "127.0.0.1"

    def drain_node(self, node_id: str, deadline_s: float = 0.0,
                   reason: str = "preemption") -> None:
        """Emit a provider-initiated preemption warning for ``node_id``
        (DESIGN.md §4j): the cluster node turns ``draining`` — no new
        placement, running work keeps going until ``terminate_node`` —
        and a ``node_draining`` fleet event reaches subscribers (the
        elasticity manager re-meshes the training group away during the
        window).  Base implementation maps the provider node id through
        the ``ray-pod`` label the pod-based providers stamp; providers
        whose ids ARE cluster node ids override."""
        from ray_tpu.elastic import events as fleet
        fleet.drain_node(label={"ray-pod": node_id},
                         deadline_s=deadline_s, reason=reason)


class FakeMultiNodeProvider(NodeProvider):
    """Logical nodes inside a live cluster (control-plane RPCs).

    ``node_config`` carries the resource dict for ``add_node`` (e.g.
    ``{"CPU": 4}`` or ``{"CPU": 8, "TPU": 4, "tpu-v4-8": 1}``).
    """

    def __init__(self, provider_config: Dict[str, Any] = None,
                 cluster_name: str = "fake"):
        super().__init__(provider_config or {}, cluster_name)
        self._lock = threading.Lock()
        self._nodes: Dict[str, Dict[str, str]] = {}  # node_id -> tags

    def _worker(self):
        from ray_tpu._private import worker as worker_mod
        return worker_mod.global_worker()

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        with self._lock:
            out = []
            for nid, tags in self._nodes.items():
                if all(tags.get(k) == v for k, v in tag_filters.items()):
                    out.append(nid)
            return out

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            return dict(self._nodes.get(node_id, {}))

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> List[str]:
        created = []
        for _ in range(count):
            resp = self._worker().rpc(
                "add_node", resources=dict(node_config.get("resources", {})),
                labels={"autoscaler": "1",
                        "node_type": tags.get(TAG_NODE_TYPE, "")})
            nid = resp["node_id"]
            with self._lock:
                self._nodes[nid] = {**tags, TAG_NODE_STATUS: STATUS_UP_TO_DATE}
            created.append(nid)
        return created

    def terminate_node(self, node_id: str) -> None:
        self._worker().rpc("remove_node", node_id=node_id)
        with self._lock:
            self._nodes.pop(node_id, None)

    def drain_node(self, node_id: str, deadline_s: float = 0.0,
                   reason: str = "preemption") -> None:
        # logical-node ids ARE cluster node ids: signal directly
        self._worker().rpc("node_draining", node_id=node_id,
                           deadline_s=deadline_s, reason=reason)


def __getattr__(name):  # lazy: kube.py pulls in ssl/http only when used
    if name in ("KubernetesNodeProvider", "GkeTpuNodeProvider",
                "KubeClient"):
        from ray_tpu.autoscaler import kube
        return getattr(kube, name)
    raise AttributeError(name)
