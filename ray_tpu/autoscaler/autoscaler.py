"""StandardAutoscaler: reconcile resource demand against running nodes.

Reference: ``python/ray/autoscaler/_private/autoscaler.py`` (SURVEY.md
§2.3) — a periodic ``update()``: read unfulfilled demand from the control
plane, bin-pack onto configured node types (resource_demand_scheduler),
launch the difference through the NodeProvider, and reap nodes idle longer
than ``idle_timeout_s`` (never below ``min_workers``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler import resource_demand_scheduler as rds
from ray_tpu.autoscaler.node_provider import (
    NODE_KIND_WORKER, NodeProvider, TAG_NODE_KIND, TAG_NODE_TYPE,
)


class AutoscalerConfig:
    """Subset of the reference cluster YAML that matters here.

    node_types: {name: {"resources": {...}, "min_workers": int,
                        "max_workers": int}}
    """

    def __init__(self, node_types: Dict[str, dict],
                 max_workers: int = 100, idle_timeout_s: float = 60.0):
        self.node_types = node_types
        self.max_workers = max_workers
        self.idle_timeout_s = idle_timeout_s


class StandardAutoscaler:
    def __init__(self, config: AutoscalerConfig, provider: NodeProvider):
        self.config = config
        self.provider = provider
        self._idle_since: Dict[str, float] = {}
        self._lock = threading.Lock()

    # -- inputs --------------------------------------------------------------
    def _demand(self) -> List[Dict[str, float]]:
        from ray_tpu._private import worker as worker_mod
        resp = worker_mod.global_worker().rpc("resource_demand")
        return list(resp["task_shapes"]) + list(resp["pg_bundles"])

    def _node_utilization(self) -> Dict[str, bool]:
        """provider-node-id -> is_idle (all resources available == total).

        Keyed by BOTH the cluster node id (FakeMultiNodeProvider ids) and
        the node's ``ray-pod`` label (Kubernetes provider ids are pod
        names; the provider stamps each pod's agent with its own pod
        name, see kube.py)."""
        from ray_tpu._private import worker as worker_mod
        nodes = worker_mod.global_worker().rpc("list_nodes")["nodes"]
        out = {}
        for n in nodes:
            if not n["alive"]:
                continue
            total = {k: v for k, v in n["resources_total"].items()
                     if not k.startswith("node:")}
            avail = n["resources_available"]
            idle = all(avail.get(k, 0.0) >= v for k, v in total.items())
            out[n["node_id"]] = idle
            pod = (n.get("labels") or {}).get("ray-pod")
            if pod:
                out[pod] = idle
        return out

    def _counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for nid in self.provider.non_terminated_nodes({}):
            t = self.provider.node_tags(nid).get(TAG_NODE_TYPE, "")
            counts[t] = counts.get(t, 0) + 1
        return counts

    # -- reconcile -----------------------------------------------------------
    def update(self) -> Dict[str, Any]:
        """One reconcile step; returns a report for logging/tests."""
        with self._lock:
            demand = self._demand()
            counts = self._counts()
            to_launch = rds.get_nodes_to_launch(
                self.config.node_types, counts, demand,
                max_total_nodes=self.config.max_workers)
            launched = {}
            for t, n in to_launch.items():
                cfg = self.config.node_types[t]
                # pass the node type's whole config through (labels, TPU
                # selectors, pod overrides...), not just resources — the
                # provider decides what it understands
                node_cfg = {k: v for k, v in cfg.items()
                            if k not in ("min_workers", "max_workers")}
                ids = self.provider.create_node(
                    node_cfg,
                    {TAG_NODE_KIND: NODE_KIND_WORKER, TAG_NODE_TYPE: t}, n)
                launched[t] = ids

            terminated = self._scale_down(counts, launched)
            infeasible = rds.infeasible_shapes(self.config.node_types, demand)
            return {"demand": demand, "launched": launched,
                    "terminated": terminated, "infeasible": infeasible}

    def _scale_down(self, counts: Dict[str, int],
                    launched: Dict[str, list]) -> List[str]:
        now = time.monotonic()
        idle = self._node_utilization()
        just_launched = {nid for ids in launched.values() for nid in ids}
        terminated = []
        terminated_per_type: Dict[str, int] = {}
        for nid in self.provider.non_terminated_nodes({}):
            if nid in just_launched:
                self._idle_since.pop(nid, None)
                continue
            if not idle.get(nid, False):
                self._idle_since.pop(nid, None)
                continue
            since = self._idle_since.setdefault(nid, now)
            if now - since < self.config.idle_timeout_s:
                continue
            # resolve the type BEFORE terminating (providers forget
            # terminated nodes) and count kills per type so the
            # min_workers floor holds within one update
            t = self.provider.node_tags(nid).get(TAG_NODE_TYPE, "")
            cfg = self.config.node_types.get(t, {})
            live = counts.get(t, 0) + len(launched.get(t, [])) \
                - terminated_per_type.get(t, 0)
            if live <= cfg.get("min_workers", 0):
                continue
            self.provider.terminate_node(nid)
            self._idle_since.pop(nid, None)
            terminated.append(nid)
            terminated_per_type[t] = terminated_per_type.get(t, 0) + 1
        return terminated


class AutoscalerLoop:
    """Background thread calling update() periodically (the monitor)."""

    def __init__(self, autoscaler: StandardAutoscaler,
                 interval_s: float = 5.0):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.autoscaler.update()
            except Exception:  # noqa: BLE001 - keep reconciling
                import logging
                logging.getLogger(__name__).exception("autoscaler update")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
