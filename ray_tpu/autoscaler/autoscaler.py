"""StandardAutoscaler: reconcile resource demand against running nodes.

Reference: ``python/ray/autoscaler/_private/autoscaler.py`` (SURVEY.md
§2.3) — a periodic ``update()``: read unfulfilled demand from the control
plane, bin-pack onto configured node types (resource_demand_scheduler),
launch the difference through the NodeProvider, and reap nodes idle longer
than ``idle_timeout_s`` (never below ``min_workers``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler import resource_demand_scheduler as rds
from ray_tpu.autoscaler.node_provider import (
    NODE_KIND_WORKER, NodeProvider, TAG_NODE_KIND, TAG_NODE_TYPE,
)


class AutoscalerConfig:
    """Subset of the reference cluster YAML that matters here.

    node_types: {name: {"resources": {...}, "min_workers": int,
                        "max_workers": int}}
    """

    def __init__(self, node_types: Dict[str, dict],
                 max_workers: int = 100, idle_timeout_s: float = 60.0,
                 boot_grace_s: float = 300.0):
        self.node_types = node_types
        self.max_workers = max_workers
        self.idle_timeout_s = idle_timeout_s
        # how long a launched node may stay "booting" (provider lists
        # it, cluster doesn't) before its capacity stops absorbing
        # demand — a crashed-before-join agent must not block its own
        # replacement forever
        self.boot_grace_s = boot_grace_s


class StandardAutoscaler:
    def __init__(self, config: AutoscalerConfig, provider: NodeProvider):
        self.config = config
        self.provider = provider
        self._idle_since: Dict[str, float] = {}
        self._pending_since: Dict[str, float] = {}
        # Autopilot pre-warm ledger (DESIGN.md §4n): draining-node id ->
        # {"type", "node_id"}.  While the drained node is still listed,
        # its pre-warmed replacement is RESERVED — excluded from
        # _net_pending_capacity's pools so ordinary backlog cannot eat
        # the credit and the incoming loss re-launch.  Once the drained
        # node disappears the reservation lifts and the materialized
        # loss demand nets against the (by then mostly booted)
        # replacement instead of launching another.
        self._prewarm: Dict[str, dict] = {}      # guarded by: _lock
        # Autopilot forecast floor: extra demand slots packed AHEAD of
        # the measured backlog (the lead-time diurnal signal); also
        # exempts that many idle nodes from scale-down so pre-scaled
        # capacity survives until the predicted demand lands.
        self._forecast_slots = 0                 # guarded by: _lock
        self._forecast_shape: Optional[Dict[str, float]] = None
        self._lock = threading.Lock()
        # injectable clock: the fleet simulator replays hour-long
        # preemption/demand traces against this same reconcile loop on
        # simulated time (elastic/fleet_sim.py)
        self._clock = time.monotonic

    # -- inputs --------------------------------------------------------------
    def _demand(self) -> List[Dict[str, float]]:
        from ray_tpu._private import worker as worker_mod
        resp = worker_mod.global_worker().rpc("resource_demand")
        return list(resp["task_shapes"]) + list(resp["pg_bundles"])

    def _node_phases(self) -> Dict[str, str]:
        """Cluster node lifecycle phases keyed like _node_utilization
        (node id AND ray-pod label) — draining nodes must neither be
        scale-down victims (the provider already owns their death) nor
        count as placement capacity."""
        from ray_tpu._private import worker as worker_mod
        nodes = worker_mod.global_worker().rpc("list_nodes")["nodes"]
        out: Dict[str, str] = {}
        for n in nodes:
            phase = n.get("phase", "running" if n["alive"] else
                          "terminating")
            out[n["node_id"]] = phase
            pod = (n.get("labels") or {}).get("ray-pod")
            if pod:
                out[pod] = phase
        return out

    def _node_utilization(self) -> Dict[str, bool]:
        """provider-node-id -> is_idle (all resources available == total).

        Keyed by BOTH the cluster node id (FakeMultiNodeProvider ids) and
        the node's ``ray-pod`` label (Kubernetes provider ids are pod
        names; the provider stamps each pod's agent with its own pod
        name, see kube.py)."""
        from ray_tpu._private import worker as worker_mod
        nodes = worker_mod.global_worker().rpc("list_nodes")["nodes"]
        out = {}
        for n in nodes:
            if not n["alive"]:
                continue
            total = {k: v for k, v in n["resources_total"].items()
                     if not k.startswith("node:")}
            avail = n["resources_available"]
            idle = all(avail.get(k, 0.0) >= v for k, v in total.items())
            out[n["node_id"]] = idle
            pod = (n.get("labels") or {}).get("ray-pod")
            if pod:
                out[pod] = idle
        return out

    def _snapshot(self):
        """ONE provider listing + tag fetch per reconcile — every
        consumer below works off this snapshot (a Kubernetes provider
        pays an API round-trip per call, and update() used to make
        five of them)."""
        node_ids = list(self.provider.non_terminated_nodes({}))
        tags = {nid: self.provider.node_tags(nid).get(TAG_NODE_TYPE, "")
                for nid in node_ids}
        counts: Dict[str, int] = {}
        for nid in node_ids:
            counts[tags[nid]] = counts.get(tags[nid], 0) + 1
        return node_ids, tags, counts

    # -- autopilot hooks (DESIGN.md §4n) -------------------------------------
    def prewarm_for_drain(self, node_id: str) -> bool:
        """Reserve + launch one replacement for a draining node DURING
        its warning window.  Idempotent per node id; the launch happens
        on the next :meth:`update`.  Returns False when the node's type
        is unknown (nothing to warm) or a pre-warm is already active."""
        with self._lock:
            if node_id in self._prewarm:
                return False
            t = self.provider.node_tags(node_id).get(TAG_NODE_TYPE, "")
            if t not in self.config.node_types:
                return False
            self._prewarm[node_id] = {"type": t, "node_id": None}
            return True

    def set_forecast_demand(self, slots: int,
                            shape: Optional[Dict[str, float]] = None
                            ) -> None:
        """Lead-time demand signal: pack ``slots`` extra shapes ahead of
        the measured backlog on every reconcile (and exempt as many
        idle nodes from scale-down).  ``shape`` defaults to the first
        configured node type's resources — single-shape fleets; mixed
        fleets pass the shape the forecast predicts."""
        with self._lock:
            self._forecast_slots = max(int(slots), 0)
            self._forecast_shape = dict(shape) if shape else None

    def _forecast_shapes(self) -> List[Dict[str, float]]:
        """Caller must hold ``_lock``."""
        if self._forecast_slots <= 0:
            return []
        shape = self._forecast_shape
        if shape is None:
            first = next(iter(self.config.node_types.values()), None)
            if not first:
                return []
            shape = first["resources"]
        return [dict(shape) for _ in range(self._forecast_slots)]

    def _reap_prewarm(self, node_ids: List[str],
                      phases: Dict[str, str]) -> None:
        """Caller must hold ``_lock``.  Release reservations whose
        drained node is gone (the loss demand nets against the pending
        replacement from here on) or whose replacement already joined
        the cluster (it is ordinary capacity now)."""
        listed = set(node_ids)
        for key in list(self._prewarm):
            pw = self._prewarm[key]
            joined = pw["node_id"] is not None and \
                phases.get(pw["node_id"], "pending") != "pending"
            if key not in listed or joined:
                del self._prewarm[key]

    # -- reconcile -----------------------------------------------------------
    def update(self) -> Dict[str, Any]:
        """One reconcile step; returns a report for logging/tests."""
        with self._lock:
            demand = self._demand()
            node_ids, tags, counts = self._snapshot()
            phases = self._node_phases()
            # a draining node's capacity is already forfeit: exclude it
            # from the packing counts so the replacement launches DURING
            # the warning window, not after the node dies
            draining = {nid for nid, ph in phases.items()
                        if ph == "draining"}
            if draining:
                packing_counts = dict(counts)
                for nid in node_ids:
                    if nid in draining:
                        t = tags[nid]
                        packing_counts[t] = max(
                            packing_counts.get(t, 0) - 1, 0)
            else:
                packing_counts = counts
            # autopilot inputs: release stale pre-warm reservations and
            # splice the forecast floor into what we pack (the floor is
            # packed like real demand but never counted in the backlog
            # metric — it is a prediction, not a queue)
            self._reap_prewarm(node_ids, phases)
            reserved = {pw["node_id"]
                        for pw in self._prewarm.values()
                        if pw["node_id"] is not None}
            forecast_extra = self._forecast_shapes()
            idle = None
            if forecast_extra:
                # the floor asks for CAPACITY, not launches: idle
                # running nodes already ARE the pre-scaled capacity
                # (the packer only sees unfulfilled demand, so without
                # this netting every reconcile would re-launch the
                # same floor).  The utilization snapshot is shared with
                # _scale_down below — one list_nodes RPC per reconcile.
                idle = self._node_utilization()
                n_idle = sum(1 for nid in node_ids
                             if idle.get(nid, False)
                             and phases.get(nid) == "running")
                forecast_extra = forecast_extra[
                    :max(len(forecast_extra) - n_idle, 0)]
            # net BOOTING capacity against demand before packing: a
            # launched-but-not-yet-joined node (provider lists it, the
            # cluster doesn't → phase "pending") will absorb its share
            # of the backlog when it comes up; without this every
            # reconcile during the boot window re-launches for the same
            # demand (the churn sim caught the over-launch)
            demand_to_pack = self._net_pending_capacity(
                demand + forecast_extra, phases, node_ids, tags,
                reserved=reserved)
            to_launch = rds.get_nodes_to_launch(
                self.config.node_types, packing_counts, demand_to_pack,
                max_total_nodes=self.config.max_workers)
            launched = {}
            for t, n in to_launch.items():
                cfg = self.config.node_types[t]
                # pass the node type's whole config through (labels, TPU
                # selectors, pod overrides...), not just resources — the
                # provider decides what it understands
                node_cfg = {k: v for k, v in cfg.items()
                            if k not in ("min_workers", "max_workers")}
                ids = self.provider.create_node(
                    node_cfg,
                    {TAG_NODE_KIND: NODE_KIND_WORKER, TAG_NODE_TYPE: t}, n)
                launched[t] = ids
            self._launch_prewarm(launched, node_ids)

            terminated = self._scale_down(counts, launched, draining,
                                          node_ids, tags,
                                          keep_idle=self._forecast_slots,
                                          idle=idle)
            infeasible = rds.infeasible_shapes(self.config.node_types, demand)
            self._publish_metrics(demand, phases, launched, terminated,
                                  node_ids)
            return {"demand": demand, "launched": launched,
                    "terminated": terminated, "infeasible": infeasible,
                    "draining": sorted(draining)}

    def _net_pending_capacity(self, demand: List[Dict[str, float]],
                              phases: Dict[str, str],
                              node_ids: List[str],
                              tags: Dict[str, str],
                              reserved: Optional[set] = None
                              ) -> List[Dict[str, float]]:
        """Drop the demand shapes that fit onto provider nodes still
        booting (listed by the provider, not yet joined the cluster).
        Largest shapes first, mirroring the packer's own order.  A node
        "booting" longer than ``boot_grace_s`` stops absorbing demand:
        its agent probably crashed before registering, and a phantom
        must not block its own replacement forever.  ``reserved`` ids
        (active pre-warm replacements, DESIGN.md §4n) never absorb
        ordinary demand — their credit is held for the loss their
        draining node is about to become."""
        now = self._clock()
        pending_ids = set()
        pools: List[Dict[str, float]] = []
        for nid in node_ids:
            if phases.get(nid, "pending") != "pending":
                self._pending_since.pop(nid, None)
                continue
            pending_ids.add(nid)
            since = self._pending_since.setdefault(nid, now)
            if now - since > self.config.boot_grace_s:
                continue               # phantom: stop counting it
            if reserved and nid in reserved:
                continue               # pre-warm credit: held for the loss
            cfg = self.config.node_types.get(tags.get(nid, ""))
            if cfg:
                pools.append(dict(cfg["resources"]))
        # forget nodes the provider no longer lists
        for nid in list(self._pending_since):
            if nid not in pending_ids:
                self._pending_since.pop(nid, None)
        if not pools:
            return demand
        remaining = []
        for shape in sorted(demand, key=lambda s: -sum(s.values())):
            for avail in pools:
                if rds._fits(avail, shape):
                    rds._consume(avail, shape)
                    break
            else:
                remaining.append(shape)
        return remaining

    def _launch_prewarm(self, launched: Dict[str, list],
                        node_ids: List[str]) -> None:
        """Caller must hold ``_lock``.  Launch one replacement per
        active pre-warm reservation that has none yet, bounded by
        ``max_workers``.  A provider launch failure (capacity outage)
        leaves the entry pending — retried next reconcile."""
        total = len(node_ids) + sum(len(ids) for ids in launched.values())
        for key, pw in self._prewarm.items():
            if pw["node_id"] is not None:
                continue
            if total >= self.config.max_workers:
                break
            t = pw["type"]
            cfg = self.config.node_types[t]
            node_cfg = {k: v for k, v in cfg.items()
                        if k not in ("min_workers", "max_workers")}
            try:
                ids = self.provider.create_node(
                    node_cfg,
                    {TAG_NODE_KIND: NODE_KIND_WORKER, TAG_NODE_TYPE: t}, 1)
            except Exception:  # noqa: BLE001 - outage: retry next pass
                continue
            if ids:
                pw["node_id"] = ids[0]
                launched.setdefault(t, []).extend(ids)
                total += 1

    def _publish_metrics(self, demand, phases, launched, terminated,
                         node_ids) -> None:
        from ray_tpu._private.config import GLOBAL_CONFIG
        if not GLOBAL_CONFIG.metrics_enabled:
            return
        from ray_tpu.util import metrics_catalog as mcat
        mcat.get("rtpu_autoscaler_demand_backlog").set(float(len(demand)))
        by_phase: Dict[str, int] = {}
        for nid in node_ids:
            by_phase[phases.get(nid, "pending")] = \
                by_phase.get(phases.get(nid, "pending"), 0) + 1
        for phase in ("pending", "running", "draining"):
            mcat.get("rtpu_autoscaler_nodes").set(
                float(by_phase.get(phase, 0)), tags={"phase": phase})
        mcat.get("rtpu_autoscaler_forecast_slots").set(
            float(self._forecast_slots))
        n_launched = sum(len(ids) for ids in launched.values())
        if n_launched:
            mcat.get("rtpu_autoscaler_decisions_total").inc(
                n_launched, tags={"action": "launch"})
        if terminated:
            mcat.get("rtpu_autoscaler_decisions_total").inc(
                len(terminated), tags={"action": "terminate"})

    def _scale_down(self, counts: Dict[str, int],
                    launched: Dict[str, list],
                    draining: Optional[set] = None,
                    node_ids: Optional[List[str]] = None,
                    tags: Optional[Dict[str, str]] = None,
                    keep_idle: int = 0,
                    idle: Optional[Dict[str, bool]] = None) -> List[str]:
        now = self._clock()
        if idle is None:
            idle = self._node_utilization()
        just_launched = {nid for ids in launched.values() for nid in ids}
        terminated = []
        terminated_per_type: Dict[str, int] = {}
        # forecast floor (DESIGN.md §4n): the first keep_idle idle nodes
        # are pre-scaled capacity for predicted demand — reaping them
        # would thrash against the very launches the forecast asked for
        spared = 0
        if node_ids is None:
            node_ids, tags, _ = self._snapshot()
        for nid in node_ids:
            if nid in just_launched:
                self._idle_since.pop(nid, None)
                continue
            if draining and nid in draining:
                # the provider owns a draining node's death; reaping it
                # here would double-terminate and skew the type counts
                self._idle_since.pop(nid, None)
                continue
            if not idle.get(nid, False):
                self._idle_since.pop(nid, None)
                continue
            since = self._idle_since.setdefault(nid, now)
            if now - since < self.config.idle_timeout_s:
                continue
            if spared < keep_idle:
                spared += 1
                continue
            # resolve the type BEFORE terminating (providers forget
            # terminated nodes) and count kills per type so the
            # min_workers floor holds within one update
            t = tags.get(nid, "")
            cfg = self.config.node_types.get(t, {})
            live = counts.get(t, 0) + len(launched.get(t, [])) \
                - terminated_per_type.get(t, 0)
            if live <= cfg.get("min_workers", 0):
                continue
            self.provider.terminate_node(nid)
            self._idle_since.pop(nid, None)
            terminated.append(nid)
            terminated_per_type[t] = terminated_per_type.get(t, 0) + 1
        return terminated


class AutoscalerLoop:
    """Background thread calling update() periodically (the monitor)."""

    def __init__(self, autoscaler: StandardAutoscaler,
                 interval_s: float = 5.0):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._attach_autopilot()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def _attach_autopilot(self) -> None:
        """When this loop runs in the head process, hand the autoscaler
        to the autopilot's actuator (DESIGN.md §4n) — the pre-warm and
        forecast reflexes actuate through it.  Out-of-process operators
        (the Kubernetes operator) run without the reflexes; the
        autopilot records their actions as skipped(no-autoscaler)."""
        try:
            from ray_tpu._private import gcs as gcs_mod
            head = gcs_mod._INPROC_SERVER
            if head is not None and head._autopilot is not None:
                head._autopilot.actuator.autoscaler = self.autoscaler
        except Exception:  # noqa: BLE001 - attach is best-effort
            pass

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.autoscaler.update()
            except Exception:  # noqa: BLE001 - keep reconciling
                import logging
                logging.getLogger(__name__).exception("autoscaler update")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
