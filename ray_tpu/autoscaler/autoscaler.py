"""StandardAutoscaler: reconcile resource demand against running nodes.

Reference: ``python/ray/autoscaler/_private/autoscaler.py`` (SURVEY.md
§2.3) — a periodic ``update()``: read unfulfilled demand from the control
plane, bin-pack onto configured node types (resource_demand_scheduler),
launch the difference through the NodeProvider, and reap nodes idle longer
than ``idle_timeout_s`` (never below ``min_workers``).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler import resource_demand_scheduler as rds
from ray_tpu.autoscaler.node_provider import (
    NODE_KIND_WORKER, NodeProvider, TAG_NODE_KIND, TAG_NODE_TYPE,
)


class AutoscalerConfig:
    """Subset of the reference cluster YAML that matters here.

    node_types: {name: {"resources": {...}, "min_workers": int,
                        "max_workers": int}}
    """

    def __init__(self, node_types: Dict[str, dict],
                 max_workers: int = 100, idle_timeout_s: float = 60.0,
                 boot_grace_s: float = 300.0):
        self.node_types = node_types
        self.max_workers = max_workers
        self.idle_timeout_s = idle_timeout_s
        # how long a launched node may stay "booting" (provider lists
        # it, cluster doesn't) before its capacity stops absorbing
        # demand — a crashed-before-join agent must not block its own
        # replacement forever
        self.boot_grace_s = boot_grace_s


class StandardAutoscaler:
    def __init__(self, config: AutoscalerConfig, provider: NodeProvider):
        self.config = config
        self.provider = provider
        self._idle_since: Dict[str, float] = {}
        self._pending_since: Dict[str, float] = {}
        self._lock = threading.Lock()
        # injectable clock: the fleet simulator replays hour-long
        # preemption/demand traces against this same reconcile loop on
        # simulated time (elastic/fleet_sim.py)
        self._clock = time.monotonic

    # -- inputs --------------------------------------------------------------
    def _demand(self) -> List[Dict[str, float]]:
        from ray_tpu._private import worker as worker_mod
        resp = worker_mod.global_worker().rpc("resource_demand")
        return list(resp["task_shapes"]) + list(resp["pg_bundles"])

    def _node_phases(self) -> Dict[str, str]:
        """Cluster node lifecycle phases keyed like _node_utilization
        (node id AND ray-pod label) — draining nodes must neither be
        scale-down victims (the provider already owns their death) nor
        count as placement capacity."""
        from ray_tpu._private import worker as worker_mod
        nodes = worker_mod.global_worker().rpc("list_nodes")["nodes"]
        out: Dict[str, str] = {}
        for n in nodes:
            phase = n.get("phase", "running" if n["alive"] else
                          "terminating")
            out[n["node_id"]] = phase
            pod = (n.get("labels") or {}).get("ray-pod")
            if pod:
                out[pod] = phase
        return out

    def _node_utilization(self) -> Dict[str, bool]:
        """provider-node-id -> is_idle (all resources available == total).

        Keyed by BOTH the cluster node id (FakeMultiNodeProvider ids) and
        the node's ``ray-pod`` label (Kubernetes provider ids are pod
        names; the provider stamps each pod's agent with its own pod
        name, see kube.py)."""
        from ray_tpu._private import worker as worker_mod
        nodes = worker_mod.global_worker().rpc("list_nodes")["nodes"]
        out = {}
        for n in nodes:
            if not n["alive"]:
                continue
            total = {k: v for k, v in n["resources_total"].items()
                     if not k.startswith("node:")}
            avail = n["resources_available"]
            idle = all(avail.get(k, 0.0) >= v for k, v in total.items())
            out[n["node_id"]] = idle
            pod = (n.get("labels") or {}).get("ray-pod")
            if pod:
                out[pod] = idle
        return out

    def _snapshot(self):
        """ONE provider listing + tag fetch per reconcile — every
        consumer below works off this snapshot (a Kubernetes provider
        pays an API round-trip per call, and update() used to make
        five of them)."""
        node_ids = list(self.provider.non_terminated_nodes({}))
        tags = {nid: self.provider.node_tags(nid).get(TAG_NODE_TYPE, "")
                for nid in node_ids}
        counts: Dict[str, int] = {}
        for nid in node_ids:
            counts[tags[nid]] = counts.get(tags[nid], 0) + 1
        return node_ids, tags, counts

    # -- reconcile -----------------------------------------------------------
    def update(self) -> Dict[str, Any]:
        """One reconcile step; returns a report for logging/tests."""
        with self._lock:
            demand = self._demand()
            node_ids, tags, counts = self._snapshot()
            phases = self._node_phases()
            # a draining node's capacity is already forfeit: exclude it
            # from the packing counts so the replacement launches DURING
            # the warning window, not after the node dies
            draining = {nid for nid, ph in phases.items()
                        if ph == "draining"}
            if draining:
                packing_counts = dict(counts)
                for nid in node_ids:
                    if nid in draining:
                        t = tags[nid]
                        packing_counts[t] = max(
                            packing_counts.get(t, 0) - 1, 0)
            else:
                packing_counts = counts
            # net BOOTING capacity against demand before packing: a
            # launched-but-not-yet-joined node (provider lists it, the
            # cluster doesn't → phase "pending") will absorb its share
            # of the backlog when it comes up; without this every
            # reconcile during the boot window re-launches for the same
            # demand (the churn sim caught the over-launch)
            demand_to_pack = self._net_pending_capacity(
                demand, phases, node_ids, tags)
            to_launch = rds.get_nodes_to_launch(
                self.config.node_types, packing_counts, demand_to_pack,
                max_total_nodes=self.config.max_workers)
            launched = {}
            for t, n in to_launch.items():
                cfg = self.config.node_types[t]
                # pass the node type's whole config through (labels, TPU
                # selectors, pod overrides...), not just resources — the
                # provider decides what it understands
                node_cfg = {k: v for k, v in cfg.items()
                            if k not in ("min_workers", "max_workers")}
                ids = self.provider.create_node(
                    node_cfg,
                    {TAG_NODE_KIND: NODE_KIND_WORKER, TAG_NODE_TYPE: t}, n)
                launched[t] = ids

            terminated = self._scale_down(counts, launched, draining,
                                          node_ids, tags)
            infeasible = rds.infeasible_shapes(self.config.node_types, demand)
            self._publish_metrics(demand, phases, launched, terminated,
                                  node_ids)
            return {"demand": demand, "launched": launched,
                    "terminated": terminated, "infeasible": infeasible,
                    "draining": sorted(draining)}

    def _net_pending_capacity(self, demand: List[Dict[str, float]],
                              phases: Dict[str, str],
                              node_ids: List[str],
                              tags: Dict[str, str]) -> List[Dict[str, float]]:
        """Drop the demand shapes that fit onto provider nodes still
        booting (listed by the provider, not yet joined the cluster).
        Largest shapes first, mirroring the packer's own order.  A node
        "booting" longer than ``boot_grace_s`` stops absorbing demand:
        its agent probably crashed before registering, and a phantom
        must not block its own replacement forever."""
        now = self._clock()
        pending_ids = set()
        pools: List[Dict[str, float]] = []
        for nid in node_ids:
            if phases.get(nid, "pending") != "pending":
                self._pending_since.pop(nid, None)
                continue
            pending_ids.add(nid)
            since = self._pending_since.setdefault(nid, now)
            if now - since > self.config.boot_grace_s:
                continue               # phantom: stop counting it
            cfg = self.config.node_types.get(tags.get(nid, ""))
            if cfg:
                pools.append(dict(cfg["resources"]))
        # forget nodes the provider no longer lists
        for nid in list(self._pending_since):
            if nid not in pending_ids:
                self._pending_since.pop(nid, None)
        if not pools:
            return demand
        remaining = []
        for shape in sorted(demand, key=lambda s: -sum(s.values())):
            for avail in pools:
                if rds._fits(avail, shape):
                    rds._consume(avail, shape)
                    break
            else:
                remaining.append(shape)
        return remaining

    def _publish_metrics(self, demand, phases, launched, terminated,
                         node_ids) -> None:
        from ray_tpu._private.config import GLOBAL_CONFIG
        if not GLOBAL_CONFIG.metrics_enabled:
            return
        from ray_tpu.util import metrics_catalog as mcat
        mcat.get("rtpu_autoscaler_demand_backlog").set(float(len(demand)))
        by_phase: Dict[str, int] = {}
        for nid in node_ids:
            by_phase[phases.get(nid, "pending")] = \
                by_phase.get(phases.get(nid, "pending"), 0) + 1
        for phase in ("pending", "running", "draining"):
            mcat.get("rtpu_autoscaler_nodes").set(
                float(by_phase.get(phase, 0)), tags={"phase": phase})
        n_launched = sum(len(ids) for ids in launched.values())
        if n_launched:
            mcat.get("rtpu_autoscaler_decisions_total").inc(
                n_launched, tags={"action": "launch"})
        if terminated:
            mcat.get("rtpu_autoscaler_decisions_total").inc(
                len(terminated), tags={"action": "terminate"})

    def _scale_down(self, counts: Dict[str, int],
                    launched: Dict[str, list],
                    draining: Optional[set] = None,
                    node_ids: Optional[List[str]] = None,
                    tags: Optional[Dict[str, str]] = None) -> List[str]:
        now = self._clock()
        idle = self._node_utilization()
        just_launched = {nid for ids in launched.values() for nid in ids}
        terminated = []
        terminated_per_type: Dict[str, int] = {}
        if node_ids is None:
            node_ids, tags, _ = self._snapshot()
        for nid in node_ids:
            if nid in just_launched:
                self._idle_since.pop(nid, None)
                continue
            if draining and nid in draining:
                # the provider owns a draining node's death; reaping it
                # here would double-terminate and skew the type counts
                self._idle_since.pop(nid, None)
                continue
            if not idle.get(nid, False):
                self._idle_since.pop(nid, None)
                continue
            since = self._idle_since.setdefault(nid, now)
            if now - since < self.config.idle_timeout_s:
                continue
            # resolve the type BEFORE terminating (providers forget
            # terminated nodes) and count kills per type so the
            # min_workers floor holds within one update
            t = tags.get(nid, "")
            cfg = self.config.node_types.get(t, {})
            live = counts.get(t, 0) + len(launched.get(t, [])) \
                - terminated_per_type.get(t, 0)
            if live <= cfg.get("min_workers", 0):
                continue
            self.provider.terminate_node(nid)
            self._idle_since.pop(nid, None)
            terminated.append(nid)
            terminated_per_type[t] = terminated_per_type.get(t, 0) + 1
        return terminated


class AutoscalerLoop:
    """Background thread calling update() periodically (the monitor)."""

    def __init__(self, autoscaler: StandardAutoscaler,
                 interval_s: float = 5.0):
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="autoscaler")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.autoscaler.update()
            except Exception:  # noqa: BLE001 - keep reconciling
                import logging
                logging.getLogger(__name__).exception("autoscaler update")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
