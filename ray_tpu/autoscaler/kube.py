"""Minimal Kubernetes API client + pod-based node provider.

Reference: ``python/ray/autoscaler/_private/kuberay/node_provider.py`` and
the K8s provider plugin (SURVEY.md §2.3 autoscaler row) — the reference
speaks the Kubernetes REST API directly (create/list/delete pods with
label selectors) rather than shelling out to kubectl; so does this.

No kubernetes pip package (environment constraint): the client is a thin
JSON-over-HTTP layer on ``http.client`` with the standard in-cluster
auth discovery (``KUBERNETES_SERVICE_HOST`` + the mounted serviceaccount
token/CA) and explicit overrides for tests, which run it against an
in-tree fake API server (tests/test_autoscaler_kube.py — the reference's
mock-provider pattern, SURVEY.md §4 ``test_autoscaler*.py``).

TPU awareness (GKE): pods carry the GKE TPU nodeSelectors
(``cloud.google.com/gke-tpu-accelerator`` / ``gke-tpu-topology``) and a
``google.com/tpu`` resource limit; the pod entrypoint runs the
``ray_tpu`` node-agent, which autodetects slice topology from the GKE
environment (``node_agent._detect_tpu_env``) and joins the head with
``ici_domain``/``slice_host`` labels for topology-aware placement.
"""

from __future__ import annotations

import json
import os
import ssl
import threading
import time
import uuid
from typing import Any, Dict, List, Optional
from urllib.parse import quote

from ray_tpu.autoscaler.node_provider import (
    NODE_KIND_WORKER, NodeProvider, STATUS_UP_TO_DATE, TAG_NODE_KIND,
    TAG_NODE_STATUS, TAG_NODE_TYPE,
)

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeApiError(RuntimeError):
    def __init__(self, status: int, body: str):
        super().__init__(f"kubernetes api error {status}: {body[:300]}")
        self.status = status


class KubeClient:
    """JSON REST client for the few pod operations the provider needs."""

    def __init__(self, api_server: Optional[str] = None,
                 token: Optional[str] = None,
                 ca_cert: Optional[str] = None,
                 namespace: Optional[str] = None,
                 insecure: bool = False):
        if api_server is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "no api_server configured and not running in-cluster "
                    "(KUBERNETES_SERVICE_HOST unset)")
            api_server = f"https://{host}:{port}"
        self.api_server = api_server.rstrip("/")
        if token is None and os.path.exists(f"{_SA_DIR}/token"):
            token = open(f"{_SA_DIR}/token").read().strip()
        self.token = token
        if ca_cert is None and os.path.exists(f"{_SA_DIR}/ca.crt"):
            ca_cert = f"{_SA_DIR}/ca.crt"
        self.ca_cert = ca_cert
        if namespace is None:
            ns_file = f"{_SA_DIR}/namespace"
            namespace = (open(ns_file).read().strip()
                         if os.path.exists(ns_file) else "default")
        self.namespace = namespace
        self.insecure = insecure

    # ------------------------------------------------------------- transport
    def _request(self, method: str, path: str,
                 body: Optional[dict] = None) -> dict:
        import http.client

        scheme, rest = self.api_server.split("://", 1)
        hostport = rest
        if scheme == "https":
            if not self.ca_cert and not self.insecure:
                # never silently downgrade: the request carries the bearer
                # token — an unverified endpoint could be a MITM capturing
                # cluster credentials
                raise ValueError(
                    "https api_server with no ca_cert: pass ca_cert=... "
                    "or explicitly opt in with insecure=True")
            ctx = ssl.create_default_context(
                cafile=self.ca_cert if self.ca_cert else None)
            if self.insecure:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            conn = http.client.HTTPSConnection(hostport, context=ctx,
                                               timeout=15)
        else:
            conn = http.client.HTTPConnection(hostport, timeout=15)
        try:
            headers = {"Accept": "application/json"}
            if self.token:
                headers["Authorization"] = f"Bearer {self.token}"
            payload = None
            if body is not None:
                payload = json.dumps(body)
                headers["Content-Type"] = "application/json"
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            data = resp.read().decode("utf-8", "replace")
            if resp.status >= 300:
                raise KubeApiError(resp.status, data)
            return json.loads(data) if data else {}
        finally:
            conn.close()

    # ------------------------------------------------------------------ pods
    def create_pod(self, manifest: dict) -> dict:
        return self._request(
            "POST", f"/api/v1/namespaces/{self.namespace}/pods", manifest)

    def list_pods(self, label_selector: str = "") -> List[dict]:
        path = f"/api/v1/namespaces/{self.namespace}/pods"
        if label_selector:
            path += f"?labelSelector={quote(label_selector)}"
        return self._request("GET", path).get("items", [])

    def get_pod(self, name: str) -> dict:
        return self._request(
            "GET", f"/api/v1/namespaces/{self.namespace}/pods/{name}")

    def delete_pod(self, name: str) -> None:
        try:
            self._request(
                "DELETE", f"/api/v1/namespaces/{self.namespace}/pods/{name}")
        except KubeApiError as e:
            if e.status != 404:
                raise


class KubernetesNodeProvider(NodeProvider):
    """Workers are pods; node ids are pod names.

    ``provider_config``:
      api_server/token/ca_cert/namespace/insecure — KubeClient wiring
        (all optional in-cluster);
      head_address — "host:port" the node-agent dials (required);
      image — container image (default: the head's own image via
        ``RTPU_IMAGE``);
      auth_key_secret — name of the Secret holding ``RTPU_AUTH_KEY``
        (optional: falls back to passing the env through).

    ``node_config`` (per node type):
      resources: {"CPU": n, "TPU": chips} — agent flags;
      tpu_accelerator: e.g. "tpu-v5-lite-podslice" → GKE nodeSelector;
      tpu_topology: e.g. "2x4" → GKE nodeSelector;
      labels: extra ``--labels`` for the agent;
      pod_overrides: deep-merged into the generated pod spec.
    """

    def __init__(self, provider_config: Dict[str, Any],
                 cluster_name: str = "ray-tpu"):
        super().__init__(provider_config or {}, cluster_name)
        cfg = self.provider_config
        self.kube = cfg.get("client") or KubeClient(
            api_server=cfg.get("api_server"), token=cfg.get("token"),
            ca_cert=cfg.get("ca_cert"), namespace=cfg.get("namespace"),
            insecure=bool(cfg.get("insecure")))
        self.head_address = cfg.get("head_address") or \
            os.environ.get("RTPU_HEAD_ADDRESS", "")
        self.image = cfg.get("image") or os.environ.get(
            "RTPU_IMAGE", "ray-tpu:latest")

    # ------------------------------------------------------------- inventory
    def _selector(self) -> str:
        return f"ray-tpu/cluster={self.cluster_name}," \
               f"ray-tpu/kind={NODE_KIND_WORKER}"

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        out = []
        for pod in self.kube.list_pods(self._selector()):
            phase = (pod.get("status") or {}).get("phase", "Pending")
            if phase in ("Succeeded", "Failed"):
                continue
            if (pod.get("metadata") or {}).get("deletionTimestamp"):
                continue
            tags = self._tags_of(pod)
            if all(tags.get(k) == v for k, v in tag_filters.items()):
                out.append(pod["metadata"]["name"])
        return out

    @staticmethod
    def _tags_of(pod: dict) -> Dict[str, str]:
        labels = (pod.get("metadata") or {}).get("labels", {})
        tags = {TAG_NODE_KIND: labels.get("ray-tpu/kind", ""),
                TAG_NODE_TYPE: labels.get("ray-tpu/node-type", ""),
                TAG_NODE_STATUS: STATUS_UP_TO_DATE}
        return tags

    def node_tags(self, node_id: str) -> Dict[str, str]:
        try:
            return self._tags_of(self.kube.get_pod(node_id))
        except KubeApiError:
            return {}

    def internal_ip(self, node_id: str) -> str:
        try:
            return (self.kube.get_pod(node_id).get("status") or {}) \
                .get("podIP", "")
        except KubeApiError:
            return ""

    # -------------------------------------------------------------- lifecycle
    def _pod_manifest(self, node_config: Dict[str, Any],
                      tags: Dict[str, str]) -> dict:
        res = dict(node_config.get("resources", {}))
        cpus = res.get("CPU", 1)
        tpus = res.get("TPU", 0)
        name = f"{self.cluster_name}-worker-{uuid.uuid4().hex[:8]}"
        env = [
            {"name": "RTPU_NUM_TPUS", "value": str(tpus)},
        ]
        # preemption warning plumbing (DESIGN.md §4j): with a grace
        # window configured, the pod's SIGTERM (kubelet eviction / spot
        # preemption notice) makes the agent report ``node_draining``
        # and keep serving until the deadline instead of dying silently
        grace = node_config.get("drain_grace_s",
                                self.provider_config.get("drain_grace_s"))
        if grace:
            env.append({"name": "RTPU_DRAIN_GRACE_S", "value": str(grace)})
        if self.provider_config.get("auth_key_secret"):
            env.append({"name": "RTPU_AUTH_KEY", "valueFrom": {
                "secretKeyRef": {
                    "name": self.provider_config["auth_key_secret"],
                    "key": "auth-key"}}})
        elif os.environ.get("RTPU_AUTH_KEY"):
            env.append({"name": "RTPU_AUTH_KEY",
                        "value": os.environ["RTPU_AUTH_KEY"]})
        # ray-pod=<name> lets the autoscaler map the cluster node this
        # agent registers back to its pod for idle-based scale-down
        agent_labels = {"ray-pod": name,
                        **(node_config.get("labels") or {})}
        labels_flag = ",".join(f"{k}={v}" for k, v in agent_labels.items())
        args = ["-m", "ray_tpu._private.node_agent",
                "--address", self.head_address,
                "--num-cpus", str(int(cpus))]
        if tpus:
            args += ["--num-tpus", str(tpus)]
        if labels_flag:
            args += ["--labels", labels_flag]
        container: Dict[str, Any] = {
            "name": "ray-tpu-worker",
            "image": self.image,
            "command": ["python"],
            "args": args,
            "env": env,
            # the agent registers --num-cpus with the head; the SAME count
            # must be requested from Kubernetes or its bin-packing would
            # place pods onto cores that don't exist
            "resources": {"limits": {},
                          "requests": {"cpu": str(int(cpus))}},
        }
        node_selector: Dict[str, str] = {}
        if tpus:
            # GKE TPU node pools: the accelerator/topology selectors pin
            # the pod to the right slice hosts; google.com/tpu is the
            # device-plugin resource
            container["resources"]["limits"]["google.com/tpu"] = int(tpus)
            container["resources"]["requests"]["google.com/tpu"] = int(tpus)
            if node_config.get("tpu_accelerator"):
                node_selector["cloud.google.com/gke-tpu-accelerator"] = \
                    node_config["tpu_accelerator"]
            if node_config.get("tpu_topology"):
                node_selector["cloud.google.com/gke-tpu-topology"] = \
                    node_config["tpu_topology"]
        manifest: Dict[str, Any] = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": name,
                "labels": {
                    "ray-tpu/cluster": self.cluster_name,
                    "ray-tpu/kind": tags.get(TAG_NODE_KIND,
                                             NODE_KIND_WORKER),
                    "ray-tpu/node-type": tags.get(TAG_NODE_TYPE, ""),
                },
            },
            "spec": {
                "restartPolicy": "Never",
                "containers": [container],
                **({"nodeSelector": node_selector} if node_selector else {}),
            },
        }
        overrides = node_config.get("pod_overrides")
        if overrides:
            _deep_merge(manifest, overrides)
        return manifest

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> List[str]:
        created = []
        for _ in range(count):
            pod = self.kube.create_pod(self._pod_manifest(node_config, tags))
            created.append(pod["metadata"]["name"])
        return created

    def terminate_node(self, node_id: str) -> None:
        self.kube.delete_pod(node_id)


class GkeTpuNodeProvider(KubernetesNodeProvider):
    """GKE flavor: identical pod mechanics; node types are expected to
    carry ``tpu_accelerator``/``tpu_topology`` (the GKE TPU node-pool
    selectors) so slices land on the right hosts.  Multi-host slice
    atomicity stays in the placement-group layer (SURVEY.md §2.4): every
    host's agent joins with the same ``ici_domain`` label, autodetected
    from the GKE TPU environment inside the pod."""


def _deep_merge(dst: dict, src: dict) -> dict:
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _deep_merge(dst[k], v)
        else:
            dst[k] = v
    return dst
