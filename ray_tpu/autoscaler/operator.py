"""RayCluster operator: declarative cluster spec → pods, reconciled.

Reference: the KubeRay operator (SURVEY.md §2.6 deploy row) — a
controller that watches RayCluster custom resources and reconciles the
pod set: head + worker groups, each group with a replica count and a pod
shape.  Here the "CR" is a plain JSON/dict spec (file or GCS KV — no CRD
machinery needed to get the behavior), and the reconciler drives the
same KubernetesNodeProvider the autoscaler uses, so both controllers
speak one pod dialect:

    {"cluster_name": "demo",
     "worker_groups": [
        {"name": "cpu", "replicas": 2,
         "node_config": {"resources": {"CPU": 4}}},
        {"name": "v5e", "replicas": 1,
         "node_config": {"resources": {"CPU": 8, "TPU": 4},
                          "tpu_accelerator": "tpu-v5-lite-podslice",
                          "tpu_topology": "2x4"}}]}

``autoscaling: {"min_replicas": .., "max_replicas": ..}`` on a group
delegates that group's replica count to the in-cluster autoscaler
(exactly the KubeRay split: the operator owns pod lifecycle, the
autoscaler owns the numbers).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private import rtlog
from ray_tpu.autoscaler.node_provider import (
    NODE_KIND_WORKER, TAG_NODE_KIND, TAG_NODE_TYPE)

logger = rtlog.get("operator")


class RayClusterOperator:
    """One reconcile target: a cluster spec against a pod provider."""

    def __init__(self, provider, spec: Optional[Dict[str, Any]] = None,
                 spec_path: Optional[str] = None):
        self.provider = provider
        self._spec = spec
        self.spec_path = spec_path
        self._lock = threading.Lock()

    # ------------------------------------------------------------------ spec
    def spec(self) -> Dict[str, Any]:
        if self.spec_path:
            with open(self.spec_path) as f:
                return json.load(f)
        return dict(self._spec or {})

    def update_spec(self, spec: Dict[str, Any]) -> None:
        with self._lock:
            self._spec = spec
            # an explicit programmatic update overrides a file source —
            # silently preferring the stale file would make this a no-op
            self.spec_path = None

    # ------------------------------------------------------------- reconcile
    def _group_pods(self, group: str) -> List[str]:
        return self.provider.non_terminated_nodes({TAG_NODE_TYPE: group})

    def reconcile(self) -> Dict[str, Any]:
        """One pass: make each group's pod count match its spec.  Returns
        a report for logging/tests."""
        with self._lock:
            spec = self.spec()
        report: Dict[str, Any] = {"created": {}, "deleted": {},
                                  "groups": {}}
        seen_groups = set()
        for g in spec.get("worker_groups", []):
            name = g["name"]
            seen_groups.add(name)
            if g.get("autoscaling"):
                # the autoscaler owns this group's count (KubeRay split);
                # the operator only reports it
                report["groups"][name] = {
                    "managed_by": "autoscaler",
                    "current": len(self._group_pods(name))}
                continue
            want = int(g.get("replicas", 0))
            have = self._group_pods(name)
            if len(have) < want:
                ids = self.provider.create_node(
                    dict(g.get("node_config", {})),
                    {TAG_NODE_KIND: NODE_KIND_WORKER,
                     TAG_NODE_TYPE: name},
                    want - len(have))
                report["created"][name] = ids
                logger.info("group %s: created %d pods", name, len(ids))
            elif len(have) > want:
                # newest-first deletion (provider lists in creation order
                # for the fake; real K8s ordering is irrelevant — any
                # surplus pod is equivalent)
                victims = have[want:]
                for pod in victims:
                    self.provider.terminate_node(pod)
                report["deleted"][name] = victims
                logger.info("group %s: deleted %d pods", name, len(victims))
            report["groups"][name] = {
                "target": want,
                "current": len(self._group_pods(name))}
        # groups removed from the spec: drain their pods entirely
        for pod in self.provider.non_terminated_nodes({}):
            t = self.provider.node_tags(pod).get(TAG_NODE_TYPE, "")
            if t and t not in seen_groups:
                self.provider.terminate_node(pod)
                report["deleted"].setdefault(t, []).append(pod)
        return report

    def run(self, interval_s: float = 5.0,
            stop: Optional[threading.Event] = None) -> None:
        stop = stop or threading.Event()
        while not stop.is_set():
            try:
                self.reconcile()
            except Exception:  # noqa: BLE001 - a flaky API server pass
                # must not kill the operator
                logger.exception("reconcile pass failed")
            stop.wait(interval_s)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    from ray_tpu.autoscaler.kube import KubernetesNodeProvider

    ap = argparse.ArgumentParser(prog="ray_tpu operator")
    ap.add_argument("--spec", required=True,
                    help="path to the cluster spec JSON (reconciled every "
                         "--interval; edit the file to scale)")
    ap.add_argument("--interval", type=float, default=5.0)
    ap.add_argument("--api-server", default=None)
    ap.add_argument("--namespace", default=None)
    ap.add_argument("--head-address", default=None,
                    help="HOST:PORT workers dial (default: "
                         "$RTPU_HEAD_ADDRESS)")
    args = ap.parse_args(argv)
    with open(args.spec) as f:
        spec = json.load(f)
    provider = KubernetesNodeProvider(
        {"api_server": args.api_server, "namespace": args.namespace,
         "head_address": args.head_address},
        cluster_name=spec.get("cluster_name", "ray-tpu"))
    op = RayClusterOperator(provider, spec_path=args.spec)
    op.run(args.interval)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
