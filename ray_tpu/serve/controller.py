"""ServeController: deployment state machine + replica autoscaler.

Reference: ``python/ray/serve/_private/controller.py`` (SURVEY.md §3.6):
a detached named actor that owns the desired/actual replica sets, runs a
control loop that (a) reconciles replica counts, (b) marks replicas ready
once their ``__init__`` finished, (c) health-checks live replicas,
(d) gracefully drains downscaled replicas, and (e) runs the autoscaling
policy over handle-reported ongoing-request metrics.

The control loop runs on a thread inside the controller actor; all external
interaction is via actor calls (``max_concurrency > 1`` so stats reports
never queue behind a slow deploy).
"""

from __future__ import annotations

import math
import threading
import time
import uuid
from typing import Dict, List, Optional

import ray_tpu
from ray_tpu._private import rtlog
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.serve._replica import Replica
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.util import metrics_catalog as mcat

logger = rtlog.get("serve.controller")

_STATS_TTL_S = 10.0


class _ReplicaState:
    def __init__(self, tag: str, actor_name: str, handle, ready_ref):
        self.tag = tag
        self.actor_name = actor_name
        self.handle = handle
        self.ready_ref = ready_ref          # None once ready
        self.health_ref = None
        self.started_at = time.monotonic()


class _DeploymentState:
    def __init__(self, key: str, payload: dict):
        self.key = key
        self.payload = payload              # user_cls, init_args/kwargs
        self.config: DeploymentConfig = payload["config"]
        self.target = self.config.initial_target()
        self.replicas: Dict[str, _ReplicaState] = {}
        self.ready: Dict[str, _ReplicaState] = {}
        self.draining: List[tuple] = []     # (kill_at, _ReplicaState)
        self.version = 0
        self.up_since: Optional[float] = None
        self.down_since: Optional[float] = None


class ServeController:
    def __init__(self):
        self._deployments: Dict[str, _DeploymentState] = {}
        self._routes: Dict[str, str] = {}        # route_prefix -> ingress key
        self._apps: Dict[str, dict] = {}         # app -> {ingress, deployments}
        self._stats: Dict[tuple, tuple] = {}     # (router, dep) -> (ts, n)
        self._http_address: Optional[tuple] = None
        self._lock = threading.RLock()
        self._stop = threading.Event()
        threading.Thread(target=self._control_loop, name="serve-control",
                         daemon=True).start()

    # ----------------------------------------------------------------- deploy
    def deploy_application(self, app_name: str, route_prefix: str,
                           deployments: List[dict], ingress: str) -> bool:
        """deployments: [{name, user_cls, init_args, init_kwargs, config}]."""
        with self._lock:
            keys = []
            for d in deployments:
                key = f"{app_name}#{d['name']}"
                keys.append(key)
                existing = self._deployments.get(key)
                if existing is None:
                    self._deployments[key] = _DeploymentState(key, d)
                else:
                    # Redeploy: replace code/config, restart replicas.
                    existing.payload = d
                    existing.config = d["config"]
                    existing.target = d["config"].initial_target()
                    for rs in list(existing.replicas.values()):
                        self._retire(existing, rs, now=time.monotonic())
                    existing.version += 1
            # Drop deployments removed from the app.
            old = self._apps.get(app_name, {}).get("deployments", [])
            for stale in set(old) - set(keys):
                self._delete_deployment(stale)
            ingress_key = f"{app_name}#{ingress}"
            old_ingress = self._apps.get(app_name, {}).get("ingress")
            self._apps[app_name] = {"ingress": ingress_key,
                                    "deployments": keys}
            # Drop stale prefixes from earlier deploys of this app before
            # (re)registering — a route_prefix change must not leave the
            # old URL serving.
            self._routes = {p: k for p, k in self._routes.items()
                            if k not in (ingress_key, old_ingress)}
            if route_prefix is not None:
                self._routes[route_prefix] = ingress_key
        return True

    def delete_application(self, app_name: str) -> bool:
        with self._lock:
            app = self._apps.pop(app_name, None)
            if app is None:
                return False
            for key in app["deployments"]:
                self._delete_deployment(key)
            self._routes = {p: k for p, k in self._routes.items()
                            if k != app["ingress"]}
        return True

    def _delete_deployment(self, key: str) -> None:
        st = self._deployments.pop(key, None)
        if st is None:
            return
        # drop the deployment's gauge series: _autoscale_tick never runs
        # for it again, so the last value would otherwise be republished
        # by this long-lived controller forever (phantom deployment
        # "wanting" replicas on the dashboard)
        mcat.get("rtpu_serve_autoscaler_desired_replicas").remove_series(
            tags={"deployment": key, "group": key})
        now = time.monotonic()
        for rs in list(st.replicas.values()):
            self._retire(st, rs, now, grace=0.0)
        self._drain_tick(st, now=now + 1e9, orphan=True)

    # ------------------------------------------------------------------ reads
    def get_deployment_targets(self, dep_key: str) -> Optional[dict]:
        with self._lock:
            st = self._deployments.get(dep_key)
            if st is None:
                return None
            return {"version": st.version,
                    "replicas": {t: r.actor_name for t, r in st.ready.items()},
                    "max_ongoing": st.config.max_ongoing_requests}

    def get_routes(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._routes)

    def get_app_ingress(self, app_name: str) -> Optional[str]:
        with self._lock:
            app = self._apps.get(app_name)
            return app["ingress"] if app else None

    def status(self) -> dict:
        with self._lock:
            return {k: {"target": st.target,
                        "ready": len(st.ready),
                        "starting": len(st.replicas) - len(st.ready),
                        "draining": len(st.draining)}
                    for k, st in self._deployments.items()}

    def set_http_address(self, host: str, port: int) -> bool:
        with self._lock:
            self._http_address = (host, port)
        return True

    def get_http_address(self) -> Optional[tuple]:
        with self._lock:
            return self._http_address

    def set_grpc_address(self, host: str, port: int) -> bool:
        with self._lock:
            self._grpc_address = (host, port)
        return True

    def get_grpc_address(self) -> Optional[tuple]:
        with self._lock:
            return getattr(self, "_grpc_address", None)

    def list_app_ingress(self) -> Dict[str, str]:
        """app name → ingress DEPLOYMENT name (grpc proxy routing)."""
        with self._lock:
            return {app: meta["ingress"].split("#", 1)[1]
                    for app, meta in self._apps.items()}

    def ingress_has_method(self, dep_key: str, name: str) -> bool:
        """Does the deployment's user class define a public method
        ``name``?  (grpc proxy: map ``/Pkg.Svc/Method`` onto it.)"""
        with self._lock:
            st = self._deployments.get(dep_key)
            if st is None:
                return False
            cls = st.payload.get("user_cls")
        return callable(getattr(cls, name, None)) and not name.startswith("_")

    # ------------------------------------------------------------------ stats
    def report_handle_stats(self, router_id: str, dep_key: str,
                            ongoing: int) -> None:
        with self._lock:
            self._stats[(router_id, dep_key)] = (time.monotonic(), ongoing)

    def _total_ongoing(self, dep_key: str, now: float) -> int:
        total = 0
        for (rid, key), (ts, n) in list(self._stats.items()):
            if key != dep_key:
                continue
            if now - ts > _STATS_TTL_S:
                del self._stats[(rid, key)]
                continue
            total += n
        return total

    # ----------------------------------------------------------- control loop
    def _control_loop(self) -> None:
        while not self._stop.wait(0.2):
            try:
                with self._lock:
                    states = list(self._deployments.values())
                now = time.monotonic()
                for st in states:
                    with self._lock:
                        self._autoscale_tick(st, now)
                        self._reconcile_tick(st, now)
                    self._readiness_tick(st)
                    self._health_tick(st, now)
                    self._drain_tick(st, now)
            except Exception:  # noqa: BLE001
                if not ray_tpu.is_initialized():
                    return
                logger.exception("serve control loop error")

    def _autoscale_tick(self, st: _DeploymentState, now: float) -> None:
        try:
            self._do_autoscale_tick(st, now)
        finally:
            if GLOBAL_CONFIG.metrics_enabled:
                # the decision gauge makes scaling behavior inspectable:
                # target-vs-ready divergence on the dashboard IS the
                # autoscaler acting (or stuck)
                mcat.get("rtpu_serve_autoscaler_desired_replicas").set(
                    st.target, tags={"deployment": st.key,
                                     "group": st.key})

    def _do_autoscale_tick(self, st: _DeploymentState, now: float) -> None:
        ac: Optional[AutoscalingConfig] = st.config.autoscaling_config
        if ac is None:
            st.target = st.config.num_replicas
            return
        ongoing = self._total_ongoing(st.key, now)
        desired = math.ceil(ongoing / ac.target_ongoing_requests)
        desired = max(ac.min_replicas, min(ac.max_replicas, desired))
        if desired > st.target:
            st.down_since = None
            st.up_since = st.up_since or now
            if now - st.up_since >= ac.upscale_delay_s:
                logger.info("autoscale %s: %d -> %d (ongoing=%d)",
                            st.key, st.target, desired, ongoing)
                st.target = desired
                st.up_since = None
        elif desired < st.target:
            st.up_since = None
            st.down_since = st.down_since or now
            if now - st.down_since >= ac.downscale_delay_s:
                logger.info("autoscale %s: %d -> %d (ongoing=%d)",
                            st.key, st.target, desired, ongoing)
                st.target = desired
                st.down_since = None
        else:
            st.up_since = st.down_since = None

    def _reconcile_tick(self, st: _DeploymentState, now: float) -> None:
        while len(st.replicas) < st.target:
            self._start_replica(st)
        while len(st.replicas) > st.target:
            # Prefer draining not-yet-ready replicas, then newest ready.
            tag = next((t for t in st.replicas if t not in st.ready), None)
            if tag is None:
                tag = next(reversed(st.ready))
            self._retire(st, st.replicas[tag], now)

    def _start_replica(self, st: _DeploymentState) -> None:
        tag = uuid.uuid4().hex[:8]
        actor_name = f"SERVE_REPLICA::{st.key}#{tag}"
        opts = dict(st.config.ray_actor_options or {})
        opts.setdefault("num_cpus", 1)
        p = st.payload
        handle = ray_tpu.remote(Replica).options(
            name=actor_name, lifetime="detached",
            max_concurrency=st.config.max_ongoing_requests, **opts,
        ).remote(st.key, tag, p["user_cls"], p["init_args"], p["init_kwargs"])
        ready_ref = handle.__ray_ready__.remote()
        st.replicas[tag] = _ReplicaState(tag, actor_name, handle, ready_ref)
        logger.info("starting replica %s", actor_name)

    def _retire(self, st: _DeploymentState, rs: _ReplicaState, now: float,
                grace: Optional[float] = None) -> None:
        st.replicas.pop(rs.tag, None)
        if st.ready.pop(rs.tag, None) is not None:
            st.version += 1
        if grace is None:
            grace = st.config.graceful_shutdown_wait_s
        try:
            rs.handle.prepare_shutdown.remote()
        except Exception:  # noqa: BLE001
            pass
        st.draining.append((now + grace, rs))

    def _readiness_tick(self, st: _DeploymentState) -> None:
        pending = [(t, r) for t, r in list(st.replicas.items())
                   if r.ready_ref is not None]
        for tag, rs in pending:
            ready, _ = ray_tpu.wait([rs.ready_ref], num_returns=1, timeout=0)
            if not ready:
                continue
            with self._lock:
                try:
                    ray_tpu.get(rs.ready_ref)
                except Exception:  # noqa: BLE001 - replica died on startup
                    logger.warning("replica %s failed to start", rs.actor_name)
                    st.replicas.pop(tag, None)
                    continue
                rs.ready_ref = None
                if tag in st.replicas:
                    st.ready[tag] = rs
                    st.version += 1

    def _health_tick(self, st: _DeploymentState, now: float) -> None:
        period = st.config.health_check_period_s
        for tag, rs in list(st.ready.items()):
            if rs.health_ref is None:
                if now - rs.started_at >= period:
                    rs.started_at = now
                    try:
                        rs.health_ref = rs.handle.check_health.remote()
                    except Exception:  # noqa: BLE001 - actor already dead:
                        # a raising submit must not abort the whole tick
                        # (it previously left the dead replica in the
                        # ready set forever — every tick re-raised)
                        self._replica_died(st, tag, "health submit failed")
                continue
            done, _ = ray_tpu.wait([rs.health_ref], num_returns=1, timeout=0)
            if not done:
                if now - rs.started_at > 4 * period:
                    self._replica_died(st, tag, "health check timed out")
                continue
            try:
                ray_tpu.get(rs.health_ref)
                rs.health_ref = None
            except Exception:  # noqa: BLE001
                self._replica_died(st, tag, "health check failed")

    def _replica_died(self, st: _DeploymentState, tag: str, why: str) -> None:
        logger.warning("replica %s#%s removed: %s", st.key, tag, why)
        with self._lock:
            rs = st.replicas.pop(tag, None)
            if st.ready.pop(tag, None) is not None:
                st.version += 1
        if rs is not None:
            try:
                ray_tpu.kill(rs.handle)
            except Exception:  # noqa: BLE001
                pass

    def _drain_tick(self, st: _DeploymentState, now: float,
                    orphan: bool = False) -> None:
        with self._lock:
            due = [rs for kill_at, rs in st.draining if now >= kill_at]
            if not orphan:
                st.draining = [(k, r) for k, r in st.draining if now < k]
        for rs in due:
            try:
                ray_tpu.kill(rs.handle)
            except Exception:  # noqa: BLE001
                pass

    # --------------------------------------------------------------- shutdown
    def shutdown_all(self) -> bool:
        self._stop.set()
        with self._lock:
            for st in self._deployments.values():
                draining = [rs for _, rs in st.draining]
                st.draining = []
                for rs in list(st.replicas.values()) + draining:
                    try:
                        ray_tpu.kill(rs.handle)
                    except Exception:  # noqa: BLE001
                        pass
            self._deployments.clear()
            self._apps.clear()
            self._routes.clear()
        return True
