"""FastAPI-style ingress routing (``@serve.ingress``).

Reference: ``python/ray/serve/api.py::ingress`` — the reference mounts a
FastAPI/ASGI app inside the ingress replica so HTTP routes map to
decorated METHODS of the deployment class instead of one ``__call__``.
This image ships no FastAPI/Starlette, so the same surface is provided
ASGI-free:

- :class:`HTTPApp` — a minimal router with ``@app.get/post/put/delete``
  decorators and ``{param}`` path captures (the subset of FastAPI's
  decorator API the reference pattern uses);
- :func:`ingress` — the class decorator wiring the router in: it
  installs a ``__call__(request)`` that dispatches on (method, path)
  against the proxy's :class:`~ray_tpu.serve.http_util.Request`.

A genuine FastAPI app object also works if the library is present —
dispatch duck-types ``app.routes`` (``path``/``methods``/``endpoint``),
though sync endpoints only (no ASGI loop in the replica).

Usage::

    app = serve.HTTPApp()

    @serve.deployment
    @serve.ingress(app)
    class Api:
        @app.get("/items/{item_id}")
        def get_item(self, item_id: str):
            return {"id": item_id}

        @app.post("/items")
        def create(self, request):
            return {"made": request.json()}
"""

from __future__ import annotations

import inspect
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.serve.http_util import Request, Response

_PARAM_RE = re.compile(r"\{([a-zA-Z_][a-zA-Z0-9_]*)\}")


class _Route:
    def __init__(self, method: str, path: str, fn: Callable):
        self.method = method.upper()
        self.path = path
        self.fn = fn
        # literal segments are ESCAPED ("/metrics.json" must not match
        # "/metricsXjson"); only {param} tokens become capture groups
        norm = path.rstrip("/") or "/"
        parts, pos = [], 0
        for m in _PARAM_RE.finditer(norm):
            parts.append(re.escape(norm[pos:m.start()]))
            parts.append(f"(?P<{m.group(1)}>[^/]+)")
            pos = m.end()
        parts.append(re.escape(norm[pos:]))
        self._re = re.compile(f"^{''.join(parts)}/?$")

    def match(self, method: str, path: str) -> Optional[Dict[str, str]]:
        if method != self.method:
            return None
        m = self._re.match(path or "/")
        if m is None:
            return None
        # percent-decode captures: query params arrive decoded (parse_qsl
        # in Request.from_parts), path params must match — and FastAPI,
        # which this API mirrors, decodes them too
        from urllib.parse import unquote
        return {k: unquote(v) for k, v in m.groupdict().items()}


class HTTPApp:
    """Decorator-style route table (the FastAPI surface ``ingress``
    consumes, minus ASGI)."""

    def __init__(self):
        self.routes: List[_Route] = []

    def route(self, path: str, methods: Tuple[str, ...] = ("GET",)):
        def deco(fn: Callable) -> Callable:
            for m in methods:
                self.routes.append(_Route(m, path, fn))
            return fn
        return deco

    def get(self, path: str):
        return self.route(path, ("GET",))

    def post(self, path: str):
        return self.route(path, ("POST",))

    def put(self, path: str):
        return self.route(path, ("PUT",))

    def delete(self, path: str):
        return self.route(path, ("DELETE",))


def _iter_routes(app: Any):
    """Normalize HTTPApp and FastAPI-like apps to (method, path, fn)."""
    if isinstance(app, HTTPApp):
        for r in app.routes:
            yield r
        return
    for r in getattr(app, "routes", ()):   # duck-typed FastAPI/Starlette
        path = getattr(r, "path", None)
        fn = getattr(r, "endpoint", None)
        if path is None or fn is None:
            continue
        for m in (getattr(r, "methods", None) or ("GET",)):
            yield _Route(m, path, fn)


def _call_handler(fn: Callable, instance: Any, request: Request,
                  path_params: Dict[str, str]) -> Any:
    """Bind path params / query params / the request object by NAME, the
    FastAPI convention (sans pydantic coercion: values arrive as str)."""
    sig = inspect.signature(fn)
    kwargs: Dict[str, Any] = {}
    for name, p in sig.parameters.items():
        if name == "self":
            continue
        if name in path_params:
            kwargs[name] = path_params[name]
        elif name == "request":
            kwargs[name] = request
        elif name in request.query_params:
            kwargs[name] = request.query_params[name]
        elif p.default is not inspect.Parameter.empty:
            continue
        elif p.kind in (inspect.Parameter.VAR_KEYWORD,
                        inspect.Parameter.VAR_POSITIONAL):
            continue
        else:
            raise TypeError(
                f"route handler {fn.__name__}: required parameter "
                f"{name!r} not found in path or query")
    out = fn(instance, **kwargs)
    if inspect.iscoroutine(out):
        # async handlers: the ingress __call__ is sync (the replica
        # dispatches on the METHOD being a coroutine function, and
        # __call__ isn't one) — drive the coroutine here, blocking this
        # executor thread exactly like a sync handler would
        import asyncio
        return asyncio.run(out)
    return out


def ingress(app: Any) -> Callable[[type], type]:
    """Class decorator: route HTTP requests to ``app``-decorated methods.

    The proxy invokes the ingress deployment's ``__call__(request)``;
    this installs one that dispatches on (method, path) and 404s
    unmatched routes.  Methods remain directly callable through handles
    and the gRPC proxy (they are plain methods; only HTTP routing is
    added)."""

    def wrap(cls: type) -> type:
        if not inspect.isclass(cls):
            raise TypeError("@serve.ingress decorates a class (put it "
                            "UNDER @serve.deployment)")
        # snapshot here, NOT in ingress(): decorator EXPRESSIONS evaluate
        # before the class body runs, so the @app.get registrations only
        # exist once wrap() is applied to the finished class
        routes = list(_iter_routes(app))

        def __call__(self, request):
            if not isinstance(request, Request):
                raise TypeError(
                    "ingress deployments take HTTP requests; call methods "
                    "directly via a handle for non-HTTP use")
            for r in routes:
                params = r.match(request.method, request.path)
                if params is not None:
                    return _call_handler(r.fn, self, request, params)
            return Response(
                body={"error": f"no route for "
                               f"{request.method} {request.path}"},
                status_code=404, content_type="application/json")

        cls.__call__ = __call__
        cls.__serve_http_app__ = app
        return cls

    return wrap
