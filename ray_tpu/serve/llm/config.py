"""Engine + sampling configuration for serve.llm."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple


@dataclass(frozen=True)
class SamplingParams:
    """Per-request generation parameters.

    Greedy (temperature=0) is the default: deterministic output is what
    the engine tests and the prefill/decode-handoff equivalence checks
    rely on.  ``seed`` makes temperature>0 reproducible per request.
    """

    max_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0                   # 0 = full vocab
    stop_token: Optional[int] = None
    seed: int = 0


@dataclass(frozen=True)
class EngineConfig:
    """Engine-level knobs (model, cache geometry, batching limits).

    ``model`` is "<family>:<preset>" over the in-tree model zoo —
    ``gpt2:tiny``, ``gpt2:gpt2-124m``, ``llama:tiny``, ``llama:llama3-8b``
    … (``models/gpt2.py`` / ``models/llama.py`` PRESETS).
    """

    model: str = "gpt2:tiny"
    seed: int = 0
    # -- paged KV cache geometry ------------------------------------------
    block_size: int = 16             # tokens per KV block
    num_blocks: int = 128            # pool capacity, in blocks
    # -- iteration-level scheduler limits ---------------------------------
    max_num_seqs: int = 8            # max sequences decoded per step
    max_prefill_tokens: int = 512    # prompt-length admission cap
    max_model_len: int = 256         # context cap per sequence
    # -- XLA shape bucketing (bounds recompilation) -----------------------
    # decode batch is padded up to the nearest bucket; prefill prompt
    # length likewise.  Every bucket is one compiled program.
    decode_batch_buckets: Tuple[int, ...] = (1, 2, 4, 8, 16)
    prefill_len_buckets: Tuple[int, ...] = (16, 32, 64, 128, 256, 512)
    # -- weights plane ----------------------------------------------------
    share_weights: bool = True       # publish/attach params via shm

    @property
    def max_blocks_per_seq(self) -> int:
        # the block-table width of every compiled decode program
        return -(-self.max_model_len // self.block_size)

    def model_key(self) -> str:
        return self.model.replace(":", "_").replace("/", "_")


def resolve_model(cfg: EngineConfig):
    """"<family>:<preset>" → (module, model cfg) from the in-tree zoo."""
    family, _, preset = cfg.model.partition(":")
    preset = preset or "tiny"
    if family == "gpt2":
        from ray_tpu.models import gpt2 as mod
    elif family == "llama":
        from ray_tpu.models import llama as mod
    else:
        raise ValueError(f"unknown model family {family!r} "
                         "(expected gpt2|llama)")
    try:
        mcfg = mod.PRESETS[preset]()
    except KeyError:
        raise ValueError(f"unknown {family} preset {preset!r}; have "
                         f"{sorted(mod.PRESETS)}") from None
    return mod, mcfg
