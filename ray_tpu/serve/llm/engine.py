"""LLMEngine: the continuous-batching loop over the paged KV cache.

One engine = one model on one replica process.  Requests enter through
``submit()`` (thread-safe, returns a token stream); a dedicated engine
thread runs ``step()`` forever: drain new requests, plan the iteration
(``scheduler.py``), execute a prefill or a bucketed decode batch
(``model_runner.py``), write new KV into the shm block pool
(``kv_cache.py``), push sampled tokens to the per-request streams.

Disaggregated prefill/decode rides the PR-4 data plane:
``prefill_remote()`` copies the filled blocks into a tmpfs export spool
(under /dev/shm when available, so publish is a page-cache write) served
by the engine's ``DataPlaneServer``; ``attach()`` on another engine
pulls them with pooled streamed ``DataPlanePool`` pulls (sendfile from
tmpfs on the holder side) and continues decoding WITHOUT re-running
prefill (the ``prefill_steps`` counter is the no-recompute oracle the
tests assert on).
"""

from __future__ import annotations

import os
import queue
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ray_tpu._private import rtlog
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.serve.llm.config import EngineConfig, SamplingParams
from ray_tpu.serve.llm.kv_cache import (NoFreeBlocks, PagedKVCache,
                                        reap_orphan_segments)
from ray_tpu.serve.llm.model_runner import ModelRunner
from ray_tpu.serve.llm.scheduler import (FAILED, FINISHED, IterationScheduler,
                                         Plan, Sequence)
from ray_tpu.util import metrics_catalog as mcat
from ray_tpu.util import tracing

logger = rtlog.get("serve.llm.engine")

_DONE = "__llm_done__"
_ERR = "__llm_err__"


class RequestStream:
    """Iterator over one request's generated token ids."""

    def __init__(self, seq_id: str, q: "queue.Queue", engine=None):
        self.seq_id = seq_id
        self._q = q
        self._engine = engine
        self.finish_reason: Optional[str] = None

    def __iter__(self):
        while True:
            item = self._q.get()
            if isinstance(item, tuple):
                kind, payload = item
                if kind == _DONE:
                    self.finish_reason = payload
                    return
                raise RuntimeError(f"llm request failed: {payload}")
            yield item

    def poll(self, max_items: int = 16,
             timeout: float = 0.2) -> tuple:
        """Non-blocking-ish drain: wait up to ``timeout`` for the FIRST
        available token, then take whatever else is already queued (cap
        ``max_items``).  Returns (tokens, done) — the serve streaming
        path's bounded-occupancy pull."""
        out: List[int] = []
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            return out, False
        while True:
            if isinstance(item, tuple):
                kind, payload = item
                if kind == _DONE:
                    self.finish_reason = payload
                    return out, True
                if out:
                    # deliver the tokens drained BEFORE the failure
                    # (parity with __iter__); the error marker goes
                    # back for the next poll — nothing follows it
                    self._q.put(item)
                    return out, False
                raise RuntimeError(f"llm request failed: {payload}")
            out.append(item)
            if len(out) >= max_items:
                return out, False
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return out, False

    def cancel(self) -> None:
        """Abandon the request: the engine frees its KV blocks and
        drops it from the batch at the next iteration."""
        if self._engine is not None:
            self._engine.cancel(self.seq_id)

    def tokens(self) -> List[int]:
        return list(self)


class LLMEngine:
    def __init__(self, cfg: EngineConfig, params=None, *,
                 start: bool = True):
        if cfg.prefill_len_buckets[-1] < cfg.max_model_len:
            raise ValueError(
                "largest prefill bucket must cover max_model_len "
                "(preempted sequences re-prefill their full context)")
        if cfg.decode_batch_buckets[-1] < cfg.max_num_seqs:
            raise ValueError(
                f"largest decode batch bucket "
                f"{cfg.decode_batch_buckets[-1]} < max_num_seqs "
                f"{cfg.max_num_seqs}: a full batch could never compile")
        reap_orphan_segments()
        from ray_tpu.serve.llm import weights as _weights
        _weights.reap_orphans()
        self.cfg = cfg
        self.runner = ModelRunner(cfg, params)
        self.cache = PagedKVCache(
            cfg.num_blocks, self.runner.n_layer, cfg.block_size,
            self.runner.n_kv, self.runner.head_dim, dtype=np.float32)
        self.sched = IterationScheduler(cfg.max_num_seqs,
                                        cfg.max_prefill_tokens,
                                        cfg.max_model_len)
        self._lock = threading.Lock()
        self._inbox: deque = deque()                 # guarded by: _lock
        self._attached: deque = deque()              # guarded by: _lock
        self._streams: Dict[str, queue.Queue] = {}   # guarded by: _lock
        self._cancels: set = set()                   # guarded by: _lock
        self._wake = threading.Event()
        self._stop = threading.Event()
        # decode_steps/preemptions/tokens_out are step-loop-owned
        # (read-only elsewhere; torn reads are benign ints).
        # prefill_steps has a second writer — prefill_remote() on the
        # caller's thread — so its += always runs under _lock.
        self.prefill_steps = 0
        self.decode_steps = 0
        self.preemptions = 0
        self.tokens_out = 0
        self._export_server = None
        self._export_spool: Optional[str] = None
        self._exports: deque = deque()               # guarded by: _lock
        self._pull_pool = None
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name=f"llm-engine-{self.cfg.model_key()}",
            daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        with self._lock:
            streams = list(self._streams.values())
            self._streams.clear()
        for q in streams:           # unblock any readers
            q.put((_ERR, "engine shut down"))
        if self._export_server is not None:
            self._export_server.stop()
            self._export_server = None
        if self._pull_pool is not None:
            self._pull_pool.close_all()
            self._pull_pool = None
        if self._export_spool:
            import shutil
            shutil.rmtree(self._export_spool, ignore_errors=True)
            self._export_spool = None
        if self.runner.weights_key:
            from ray_tpu.serve.llm import weights
            weights.release(self.runner.weights_key)
        self.cache.close()

    # ------------------------------------------------------------ submission
    def submit(self, prompt: List[int],
               sampling: Optional[SamplingParams] = None) -> RequestStream:
        sampling = sampling or SamplingParams()
        seq = Sequence(seq_id=uuid.uuid4().hex[:12],
                       prompt=[int(t) for t in prompt], sampling=sampling)
        # request tracing: the submitter's span (serve replica method /
        # driver trace) parents every engine span for this sequence —
        # captured HERE because the engine loop thread has no context
        span = tracing.current_span()
        if span is not None and span.sampled:
            seq.trace = span
        q: queue.Queue = queue.Queue()
        with self._lock:
            # checked under the same lock shutdown() drains streams
            # under: a submit that slips in before the drain gets its
            # _ERR from the drain; one after it raises here — either
            # way no reader can block on a never-serviced queue
            if self._stop.is_set():
                raise RuntimeError("engine shut down")
            self._streams[seq.seq_id] = q
            self._inbox.append(seq)
        self._wake.set()
        return RequestStream(seq.seq_id, q, self)

    def generate(self, prompt: List[int],
                 sampling: Optional[SamplingParams] = None) -> List[int]:
        return self.submit(prompt, sampling).tokens()

    def cancel(self, seq_id: str) -> None:
        """Request abandonment (thread-safe; applied at the next step)."""
        with self._lock:
            self._cancels.add(seq_id)
        self._wake.set()

    # ------------------------------------------------------------ engine loop
    def _loop(self) -> None:
        while not self._stop.is_set():
            if not self._work_pending():
                self._wake.wait(timeout=0.2)
                self._wake.clear()
                continue
            try:
                if not self.step():
                    # work exists but nothing runnable this iteration
                    # (e.g. the waiting head cannot fit in the free
                    # list yet): don't busy-spin the core
                    self._wake.wait(timeout=0.02)
                    self._wake.clear()
            except Exception:  # noqa: BLE001 - engine must survive a step
                logger.exception("engine step failed")
                time.sleep(0.05)

    def _work_pending(self) -> bool:
        with self._lock:
            backlog = bool(self._inbox or self._attached)
        return backlog or self.sched.has_work()

    def step(self) -> bool:
        """One iteration: admit, (maybe) prefill, decode, publish.
        Returns False when nothing was runnable (loop backs off)."""
        self._drain_cancels()
        self._drain_attached()
        with self._lock:
            while self._inbox:
                seq = self._inbox.popleft()
                try:
                    self.sched.add(seq)
                except ValueError as e:
                    self._finish_locked(seq, FAILED, str(e))
        # a prompt whose blocks can NEVER fit (even with every other
        # sequence evicted) must fail now, not starve the waiting line
        while self.sched.waiting:
            head = self.sched.waiting[0]
            if self.cache.blocks_needed(head.ctx_len) + 1 \
                    <= self.cache.num_blocks:
                break
            self.sched.waiting.popleft()
            self._finish(head, FAILED,
                         f"prompt needs more KV blocks than the pool "
                         f"holds ({self.cache.num_blocks})")
        plan = self.sched.plan(self.cache.free_block_count(),
                               self.cache.blocks_needed)
        from ray_tpu._private import flight_recorder
        if flight_recorder.enabled() and \
                (plan.prefill is not None or plan.decode):
            flight_recorder.record(
                "llm_step",
                f"prefill={'1' if plan.prefill is not None else '0'} "
                f"decode={len(plan.decode)} "
                f"free={self.cache.free_block_count()}")
        if plan.prefill is not None:
            self._do_prefill(plan.prefill)
        elif plan.decode:
            self._do_decode(plan.decode)
        self._publish_metrics(plan)
        return plan.prefill is not None or bool(plan.decode)

    # ---------------------------------------------------------------- prefill
    def _do_prefill(self, seq: Sequence) -> None:
        try:
            self.cache.alloc_seq(seq.seq_id, seq.ctx_len)
        except NoFreeBlocks:
            # plan() checked free blocks, but be safe: requeue
            self.sched.waiting.appendleft(seq)
            return
        t0 = time.time()
        try:
            logits, ks, vs = self.runner.prefill(seq.prompt)
        except Exception as e:  # noqa: BLE001 - surface to the caller
            self.cache.free_seq(seq.seq_id)
            self._finish(seq, FAILED, f"prefill failed: {e!r}")
            return
        with self._lock:
            self.prefill_steps += 1
        self.cache.scatter_prefill(seq.seq_id,
                                   np.asarray(ks, np.float32),
                                   np.asarray(vs, np.float32),
                                   len(seq.prompt))
        # sampling step = tokens generated so far RELATIVE TO THE
        # ORIGINAL prompt, so a preemption re-prefill (k tokens folded
        # into the prompt) draws the same rng stream position as the
        # pressure-free run — seeded sampling stays reproducible
        tok = self.runner.sample(logits, seq.sampling, step=seq.generated)
        if seq.trace is not None:
            # per-sequence prefill span (explicit parent: the engine
            # loop thread never holds the request's context variable)
            tracing.emit_span("llm.prefill", seq.trace, t0,
                              time.time() - t0, cat="llm",
                              seq_id=seq.seq_id, tokens=len(seq.prompt),
                              model=self.cfg.model)
        self.sched.start_running(seq)
        self._emit(seq, tok)
        self._count_tokens(len(seq.prompt), phase="prefill")
        self._maybe_finish(seq)

    # ----------------------------------------------------------------- decode
    def _do_decode(self, seqs: List[Sequence]) -> None:
        slots = {}
        batch = list(seqs)
        for seq in list(batch):
            while True:
                if seq not in self.sched.running:
                    break        # preempted while making room for others
                try:
                    slots[seq.seq_id] = self.cache.append_slot(seq.seq_id)
                    break
                except NoFreeBlocks:
                    if not self._preempt_one(slots):
                        # unreachable: sched.running contains at least
                        # `seq` itself (checked at the loop top, same
                        # thread), so victim() always finds one — fail
                        # loudly rather than spin if that ever breaks
                        raise RuntimeError(
                            "no preemption victim with a growing "
                            "sequence running")
            # preemption may have evicted members of THIS batch
            batch = [s for s in batch if s in self.sched.running]
        if not batch:
            return
        maxb = self.cfg.max_blocks_per_seq
        tables = np.zeros((len(batch), maxb), np.int32)
        toks = np.zeros(len(batch), np.int32)
        poss = np.zeros(len(batch), np.int32)
        lens = np.zeros(len(batch), np.int32)
        for i, s in enumerate(batch):
            t = self.cache.table(s.seq_id)
            tables[i, :len(t)] = t
            # the token being processed is the last SAMPLED one — its KV
            # is not in the pool yet (this step writes it); both its
            # position and the valid pool length are ctx_len - 1
            toks[i] = s.output[-1] if s.output else s.prompt[-1]
            poss[i] = s.ctx_len - 1
            lens[i] = s.ctx_len - 1
        t0 = time.time()
        try:
            logits, ks, vs = self.runner.decode(toks, poss,
                                                self.cache.pool, tables,
                                                lens)
        except BaseException:
            # return every slot reserved for THIS step, or every later
            # append_slot is off by one and the cache silently corrupts
            for s in batch:
                ent = slots.get(s.seq_id)
                if ent is not None:
                    self.cache.rollback_slot(s.seq_id, ent[2])
            raise
        self.decode_steps += 1
        for i, s in enumerate(batch):
            blk, off, _grew = slots[s.seq_id]
            self.cache.write_token(blk, off,
                                   np.asarray(ks[:, i], np.float32),
                                   np.asarray(vs[:, i], np.float32))
            tok = self.runner.sample(logits[i], s.sampling,
                                     step=s.generated)
            self._emit(s, tok)
            self._maybe_finish(s)
        traced = next((s for s in batch if s.trace is not None), None)
        if traced is not None:
            # one span per decode ITERATION (the batch is the unit of
            # execution), parented to the first traced sequence in it
            tracing.emit_span("llm.decode_step", traced.trace, t0,
                              time.time() - t0, cat="llm",
                              batch=len(batch), seq_id=traced.seq_id,
                              model=self.cfg.model)
        self._count_tokens(len(batch), phase="decode")

    def _preempt_one(self, slots: Dict) -> bool:
        """Evict the scheduler's victim (latest arrival — possibly one
        that already reserved a slot this iteration, or even the
        sequence being grown); its entry in ``slots`` is invalidated so
        the caller's batch bookkeeping stays consistent."""
        victim = self.sched.victim()
        if victim is None:
            return False
        logger.info("preempting %s under cache pressure (ctx=%d)",
                    victim.seq_id, victim.ctx_len)
        from ray_tpu._private import flight_recorder
        if flight_recorder.enabled():
            flight_recorder.record(
                "llm_preempt", f"{victim.seq_id} ctx={victim.ctx_len}")
        if victim.trace is not None:
            tracing.emit_span("llm.preempt", victim.trace, time.time(),
                              0.0, cat="llm", seq_id=victim.seq_id,
                              ctx=victim.ctx_len)
        self.cache.free_seq(victim.seq_id)
        slots.pop(victim.seq_id, None)
        self.sched.preempt(victim)
        self.preemptions += 1
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_llm_preemptions_total").inc(
                tags={"model": self.cfg.model})
        return True

    # --------------------------------------------------- prefill/decode split
    def _ensure_export_plane(self):
        from ray_tpu._private.data_plane import DataPlaneServer
        from ray_tpu.serve.llm.kv_cache import reap_orphan_export_spools
        with self._lock:
            if self._export_server is not None:
                return self._export_server
        # build OUTSIDE the lock: the orphan sweep (rmtree of a dead
        # predecessor's spool), mkdtemp, and the listener bind are all
        # I/O — _lock is a leaf guarding handoff state and must never
        # be held across blocking work (§4c discipline)
        import tempfile
        base = "/dev/shm" if os.path.isdir("/dev/shm") else None
        reap_orphan_export_spools(base)
        # pid in the name so a SIGKILLed publisher's spool is reapable
        # by the next engine on the node, like the KV pool segments
        spool = tempfile.mkdtemp(
            prefix=f"rtpu_llm_export_{os.getpid()}_", dir=base)
        server = DataPlaneServer(spool, host="127.0.0.1",
                                 advertise_host="127.0.0.1")
        with self._lock:                # prefill_remote races are legal
            if self._export_server is None:
                self._export_spool = spool
                self._export_server = server
                return server
            winner = self._export_server
        server.stop()                   # lost the race: tear ours down
        import shutil
        shutil.rmtree(spool, ignore_errors=True)
        return winner

    def prefill_remote(self, prompt: List[int],
                       sampling: Optional[SamplingParams] = None) -> dict:
        """Run prefill here; publish the filled KV blocks on the data
        plane and return the manifest a decode engine ``attach()``es.

        Runs on the caller's thread (the engine loop keeps decoding its
        own batch meanwhile; cache alloc/free are thread-safe)."""
        from ray_tpu._private.data_plane import write_spool
        sampling = sampling or SamplingParams()
        if self._stop.is_set():
            raise RuntimeError("engine shut down")
        seq_id = "pf_" + uuid.uuid4().hex[:12]
        prompt = [int(t) for t in prompt]
        span = tracing.current_span()   # caller's thread context
        if span is not None and not span.sampled:
            span = None
        t0 = time.time()
        self.cache.alloc_seq(seq_id, len(prompt))
        try:
            logits, ks, vs = self.runner.prefill(prompt)
            with self._lock:
                self.prefill_steps += 1
            self.cache.scatter_prefill(seq_id, np.asarray(ks, np.float32),
                                       np.asarray(vs, np.float32),
                                       len(prompt))
            first = self.runner.sample(logits, sampling, step=0)
            srv = self._ensure_export_plane()
            oids = []
            for b in self.cache.table(seq_id):
                oid = f"llmkv_{seq_id}_{b}"
                write_spool(self._export_spool, oid,
                            self.cache.block_bytes(b))
                oids.append(oid)
            # bounded retention: exported manifests are consumed once
            # by the attaching decode engine; keep a window for late
            # attachers, evict beyond it so a long-lived prefill
            # replica cannot grow tmpfs without limit
            evict: List[str] = []
            with self._lock:
                self._exports.append(list(oids))
                while len(self._exports) > 64:
                    evict.extend(self._exports.popleft())
            for old in evict:
                srv.delete_local(old)
            self._count_tokens(len(prompt), phase="prefill")
            # the manifest carries the prefill-side SPAN (compact wire
            # form): attach() on the decode engine parents its tree to
            # it — the cross-process link between the two engines
            ctx = tracing.emit_span(
                "llm.prefill_remote", span, t0, time.time() - t0,
                cat="llm", tokens=len(prompt), blocks=len(oids),
                model=self.cfg.model) if span is not None else None
            return dict(addr=srv.advertise_addr, blocks=oids,
                        block_nbytes=self.cache.block_nbytes,
                        tokens=prompt, first_token=int(first),
                        model=self.cfg.model,
                        block_size=self.cfg.block_size,
                        trace=ctx.to_wire() if ctx is not None else None)
        except BaseException:
            if self._stop.is_set():
                # a shutdown racing this call closed the cache/export
                # plane under us: surface the contract error, not the
                # incidental TypeError/IO failure
                raise RuntimeError("engine shut down") from None
            raise
        finally:
            self.cache.free_seq(seq_id)

    def attach(self, manifest: dict,
               sampling: Optional[SamplingParams] = None) -> RequestStream:
        """Adopt a remotely-prefilled sequence: pull its KV blocks over
        the streamed data plane and continue decoding — no re-prefill."""
        from ray_tpu._private.data_plane import DataPlanePool
        if manifest["model"] != self.cfg.model:
            raise ValueError(f"manifest model {manifest['model']!r} != "
                             f"engine model {self.cfg.model!r}")
        if manifest["block_nbytes"] != self.cache.block_nbytes or \
                manifest["block_size"] != self.cfg.block_size:
            raise ValueError("KV block geometry mismatch")
        sampling = sampling or SamplingParams()
        # same admission contract submit() gets via IterationScheduler.add
        # — an attached sequence must not be able to outgrow the block
        # table width every decode program was compiled with
        if len(manifest["tokens"]) + sampling.max_tokens > \
                self.cfg.max_model_len:
            raise ValueError(
                f"manifest context {len(manifest['tokens'])} + "
                f"max_tokens {sampling.max_tokens} exceeds "
                f"max_model_len={self.cfg.max_model_len}")
        with self._lock:          # concurrent attach() races are legal
            if self._pull_pool is None:
                self._pull_pool = DataPlanePool()
            pool = self._pull_pool
        prompt = [int(t) for t in manifest["tokens"]]
        seq = Sequence(seq_id=uuid.uuid4().hex[:12], prompt=prompt,
                       sampling=sampling)
        # link the decode-side tree to the prefill-side one: the
        # manifest's span (prefill_remote on the other engine) parents
        # the attach span, which parents this sequence's decode spans.
        # Falls back to the caller's own span for untraced manifests.
        parent = tracing.SpanContext.from_wire(manifest.get("trace"),
                                               name="llm.prefill_remote")
        if parent is None:
            cur = tracing.current_span()
            parent = cur if cur is not None and cur.sampled else None
        t0 = time.time()
        self.cache.alloc_seq(seq.seq_id, len(prompt))
        tok = tracing.adopt(parent) if parent is not None else None
        try:
            # with the manifest span adopted, the block pulls' data.pull
            # spans (and their server-side serve_stream children on the
            # prefill engine) land inside the same tree
            table = self.cache.table(seq.seq_id)
            for b, oid in zip(table, manifest["blocks"]):
                raw = pool.pull(manifest["addr"], oid,
                                size=manifest["block_nbytes"])
                self.cache.load_block(b, raw)
        except BaseException:
            self.cache.free_seq(seq.seq_id)
            if self._stop.is_set():
                raise RuntimeError("engine shut down") from None
            raise
        finally:
            if tok is not None:
                tracing.restore(tok)
        if parent is not None:
            seq.trace = tracing.emit_span(
                "llm.attach", parent, t0, time.time() - t0, cat="llm",
                seq_id=seq.seq_id, blocks=len(manifest["blocks"]),
                tokens=len(prompt), model=self.cfg.model)
        q: queue.Queue = queue.Queue()
        released = False
        with self._lock:
            # same post-shutdown race submit() closes: a stream
            # registered after the drain would never be serviced
            if self._stop.is_set():
                released = True
            else:
                self._streams[seq.seq_id] = q
                self._attached.append((seq, manifest["first_token"]))
        if released:
            self.cache.free_seq(seq.seq_id)
            raise RuntimeError("engine shut down")
        self._wake.set()
        return RequestStream(seq.seq_id, q, self)

    def _drain_cancels(self) -> None:
        with self._lock:
            if not self._cancels:
                return
            cancelled = self._cancels
            self._cancels = set()
            for sid in cancelled:
                self._streams.pop(sid, None)    # nobody is reading
            self._inbox = deque(s for s in self._inbox
                                if s.seq_id not in cancelled)
            dropped = [it[0] for it in self._attached
                       if it[0].seq_id in cancelled]
            self._attached = deque(it for it in self._attached
                                   if it[0].seq_id not in cancelled)
        for seq in dropped:     # block free OUTSIDE _lock (leaf locks
            self.cache.free_seq(seq.seq_id)    # must never nest)
        for seq in [s for s in self.sched.running
                    if s.seq_id in cancelled]:
            self.cache.free_seq(seq.seq_id)
            self.sched.finish(seq, FINISHED)
        for seq in [s for s in list(self.sched.waiting)
                    if s.seq_id in cancelled]:
            self.sched.drop_waiting(seq)

    def _drain_attached(self) -> None:
        # honor the same max_num_seqs gate plan() applies to prefill
        # admission: adopting more sequences than the largest decode
        # batch bucket would make every later _do_decode un-compilable
        room = self.max_num_seqs_room()
        if room <= 0:
            return
        items = []
        with self._lock:
            while self._attached and len(items) < room:
                items.append(self._attached.popleft())
        for seq, first in items:
            self.sched.start_running(seq)
            self._emit(seq, int(first))
            self._maybe_finish(seq)

    def max_num_seqs_room(self) -> int:
        return self.cfg.max_num_seqs - len(self.sched.running)

    # ------------------------------------------------------------- completion
    def _emit(self, seq: Sequence, tok: int) -> None:
        now = time.monotonic()
        if seq.first_token_at is None:
            seq.first_token_at = now
            if GLOBAL_CONFIG.metrics_enabled:
                mcat.get("rtpu_llm_ttft_seconds").observe(
                    now - seq.arrival, tags={"model": self.cfg.model})
        seq.output.append(int(tok))
        self.tokens_out += 1
        with self._lock:
            q = self._streams.get(seq.seq_id)
        if q is not None:
            q.put(int(tok))

    def _maybe_finish(self, seq: Sequence) -> None:
        reason = seq.finish_reason()
        if reason is None:
            return
        self.cache.free_seq(seq.seq_id)
        self.sched.finish(seq, FINISHED)
        if GLOBAL_CONFIG.metrics_enabled and len(seq.output) > 1 and \
                seq.first_token_at is not None:
            tpot = (seq.finished_at - seq.first_token_at) / \
                (len(seq.output) - 1)
            mcat.get("rtpu_llm_tpot_seconds").observe(
                tpot, tags={"model": self.cfg.model})
        with self._lock:
            q = self._streams.pop(seq.seq_id, None)
        if q is not None:
            q.put((_DONE, reason))

    def _finish(self, seq: Sequence, state: str, err: str) -> None:
        with self._lock:
            self._finish_locked(seq, state, err)

    def _finish_locked(self, seq: Sequence, state: str, err: str) -> None:
        seq.state = state
        seq.error = err
        seq.finished_at = time.monotonic()
        q = self._streams.pop(seq.seq_id, None)
        if q is not None:
            q.put((_ERR, err))

    # ---------------------------------------------------------------- metrics
    def _count_tokens(self, n: int, phase: str) -> None:
        if GLOBAL_CONFIG.metrics_enabled:
            mcat.get("rtpu_llm_tokens_total").inc(
                n, tags={"model": self.cfg.model, "phase": phase})

    def _publish_metrics(self, plan: Plan) -> None:
        if not GLOBAL_CONFIG.metrics_enabled:
            return
        tags = {"model": self.cfg.model}
        running = len(self.sched.running)
        mcat.get("rtpu_llm_sequences").set(
            running, tags={**tags, "state": "running"})
        mcat.get("rtpu_llm_sequences").set(
            len(self.sched.waiting), tags={**tags, "state": "waiting"})
        free = self.cache.free_block_count()
        mcat.get("rtpu_llm_kv_blocks").set(
            self.cfg.num_blocks - free, tags={**tags, "state": "used"})
        mcat.get("rtpu_llm_kv_blocks").set(free,
                                           tags={**tags, "state": "free"})
        mcat.get("rtpu_llm_batch_occupancy").set(
            running / max(1, self.cfg.max_num_seqs), tags=tags)

    # ------------------------------------------------------------------ stats
    def stats(self) -> dict:
        return dict(prefill_steps=self.prefill_steps,
                    decode_steps=self.decode_steps,
                    preemptions=self.preemptions,
                    tokens_out=self.tokens_out,
                    running=len(self.sched.running),
                    waiting=len(self.sched.waiting),
                    blocks_free=self.cache.free_block_count(),
                    compiles=self.runner.compiles)
