"""Serve deployments around LLMEngine.

``llm_deployment(cfg)`` returns a bound-able Serve deployment whose
replicas each host a continuous-batching ``LLMEngine``: requests stream
tokens back through the existing Serve streaming-response path (the
replica returns a generator; the router pins continuation pulls to this
replica), many concurrent requests share one engine batch
(``max_ongoing_requests`` defaults well above the engine's
``max_num_seqs`` so the iteration scheduler — not the router — is the
batching authority), and model selection rides ``@serve.multiplexed``
(the router's model-affinity keeps a model's engine — weights, KV pool,
compiled programs — resident on the replicas that already serve it).

``naive_llm_deployment(cfg)`` is the A/B baseline ``llm_bench.py``
measures against: the same model runner and cache math, but classic
request-level serving — one request runs generation end-to-end before
the next starts (``max_ongoing_requests=1``).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.serve.llm.config import EngineConfig, SamplingParams


class _TokenStream:
    """Stream object handed to Serve: iterable (blocking) AND pollable.

    ``__serve_poll__`` is the replica ``stream_next`` fast path: it
    waits only for the FIRST ready token (bounded), then drains what is
    already queued — a pending request never parks a replica actor
    thread for a whole decode-steps-worth of production, and the first
    token reaches the client the moment it is sampled.  ``close()``
    (stream cancel / abandoned-stream reap) cancels the sequence so the
    engine frees its KV blocks instead of decoding for a dead client."""

    def __init__(self, stream):
        self._stream = stream
        self._it = iter(stream)

    def __iter__(self):
        return self

    def __next__(self):
        return f"{next(self._it)}\n"

    def __serve_poll__(self, max_chunks: int):
        toks, done = self._stream.poll(max_items=max_chunks, timeout=0.2)
        return [f"{t}\n" for t in toks], done

    def close(self):
        self._stream.cancel()


def _sampling_from(req: dict) -> SamplingParams:
    return SamplingParams(
        max_tokens=int(req.get("max_tokens", 16)),
        temperature=float(req.get("temperature", 0.0)),
        top_k=int(req.get("top_k", 0)),
        stop_token=(None if req.get("stop_token") is None
                    else int(req["stop_token"])),
        seed=int(req.get("seed", 0)))


def llm_deployment(cfg: EngineConfig, *, num_replicas: int = 1,
                   max_ongoing_requests: int = 64,
                   name: str = "LLMServer"):
    """Continuous-batching deployment.  Request payload (dict or HTTP
    JSON body): ``{"prompt": [ids...], "max_tokens": N, ...}`` →
    streamed token ids."""
    from ray_tpu import serve

    @serve.deployment(name=name, num_replicas=num_replicas,
                      max_ongoing_requests=max_ongoing_requests)
    class LLMServer:
        def __init__(self, engine_cfg: Optional[EngineConfig] = None):
            self._cfg = engine_cfg or cfg

        @serve.multiplexed(max_num_models_per_replica=2)
        async def _engine_for(self, model_id: str):
            import asyncio
            from dataclasses import replace

            from ray_tpu.serve.llm.engine import LLMEngine
            ecfg = self._cfg if model_id in ("", self._cfg.model) else \
                replace(self._cfg, model=model_id)
            eng = LLMEngine(ecfg)

            # engines hold a KV pool segment + an engine thread: the mux
            # LRU must tear an evicted engine down, not just drop it.
            # Async + offloaded: shutdown joins the engine thread (up to
            # 10s) and must not stall the replica's event loop mid-evict.
            async def _unload(eng=eng):
                await asyncio.get_running_loop().run_in_executor(
                    None, eng.shutdown)

            eng.__serve_unload__ = _unload
            return eng

        async def __call__(self, request):
            from ray_tpu.serve.http_util import Request, StreamingResponse
            if isinstance(request, Request):       # HTTP ingress path
                req = request.json()
            else:
                req = dict(request)
            from ray_tpu.serve.multiplex import get_multiplexed_model_id
            engine = await self._engine_for(
                get_multiplexed_model_id() or self._cfg.model)
            stream = engine.submit([int(t) for t in req["prompt"]],
                                   _sampling_from(req))
            # pull_chunks caps a poll's DRAIN, it is not a fill quota:
            # the first token still returns the moment it exists
            return StreamingResponse(_TokenStream(stream),
                                     content_type="text/plain",
                                     pull_chunks=8)

        async def engine_stats(self) -> dict:
            import os as _os
            engine = await self._engine_for(self._cfg.model)
            return dict(engine.stats(), pid=_os.getpid(),
                        kv_segment=engine.cache.segment_path)

        def shutdown(self):
            """Serve graceful-drain hook (replica prepare_shutdown):
            tear down every engine the mux LRU holds (found by type,
            not by the wrapper's private attribute name)."""
            from ray_tpu.serve.multiplex import _MultiplexWrapper
            for v in list(vars(self).values()):
                if not isinstance(v, _MultiplexWrapper):
                    continue
                for eng in v.pop_all():
                    try:
                        eng.shutdown()
                    except Exception:  # noqa: BLE001 - best-effort drain
                        pass

    return LLMServer


def naive_llm_deployment(cfg: EngineConfig, *, num_replicas: int = 1,
                         name: str = "NaiveLLMServer"):
    """Request-level baseline: whole-request generation, one at a time
    per replica — what Serve offered before this subsystem (per-request
    batching only), measured by ``llm_bench --ab``."""
    from ray_tpu import serve

    @serve.deployment(name=name, num_replicas=num_replicas,
                      max_ongoing_requests=1)
    class NaiveLLMServer:
        def __init__(self, engine_cfg: Optional[EngineConfig] = None):
            from ray_tpu.serve.llm.engine import LLMEngine
            # same engine/runner/cache code path, driven synchronously
            # one request at a time (the engine batch never exceeds 1)
            self._engine = LLMEngine(engine_cfg or cfg)

        def __call__(self, request):
            from ray_tpu.serve.http_util import Request, StreamingResponse
            if isinstance(request, Request):
                req = request.json()
            else:
                req = dict(request)
            toks = self._engine.generate([int(t) for t in req["prompt"]],
                                         _sampling_from(req))

            def tokens():
                for tok in toks:
                    yield f"{tok}\n"

            return StreamingResponse(tokens(), content_type="text/plain")

        def engine_stats(self) -> dict:
            return self._engine.stats()

        def shutdown(self):
            self._engine.shutdown()

    return NaiveLLMServer
