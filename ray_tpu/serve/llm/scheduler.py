"""Iteration-level (continuous) batching scheduler.

Reference design: Orca (Yu et al., OSDI '22) — scheduling decisions are
made every model iteration, not per request.  Each call to ``plan()``
looks at the waiting and running sets and decides what THIS step runs:

- **prefill** of the oldest admissible waiting sequence (one per step:
  interleaving a single prefill between decode steps bounds the decode
  stall — TPOT — that a long prompt would otherwise inject), admitted
  only if a decode batch slot AND enough KV blocks are free;
- **decode** of every running sequence (token-budget = batch bucket cap);
- **preemption** under cache pressure: when a running sequence cannot
  get its next block, the LOWEST-priority running sequence (latest
  arrival) is evicted — its blocks are freed and it re-queues at the
  FRONT of the waiting line for re-prefill with its tokens so far
  (vLLM's recompute-style preemption).

The scheduler owns no locks: the engine calls it only from the engine
loop thread; queues crossed by callers are the engine's.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional

from ray_tpu.serve.llm.config import SamplingParams

WAITING, RUNNING, FINISHED, FAILED = ("waiting", "running", "finished",
                                      "failed")


@dataclass
class Sequence:
    """One request's generation state inside the engine."""

    seq_id: str
    prompt: List[int]
    sampling: SamplingParams
    arrival: float = field(default_factory=time.monotonic)
    state: str = WAITING
    output: List[int] = field(default_factory=list)
    # timing for TTFT/TPOT accounting (engine fills these in)
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    preemptions: int = 0
    error: Optional[str] = None
    # preemption folds generated tokens into the prompt for re-prefill;
    # the generation budget stays relative to the ORIGINAL prompt
    orig_len: int = 0
    # request's span context (tracing.SpanContext | None): captured at
    # submit()/attach() on the caller's thread; the engine loop parents
    # its per-sequence prefill/decode/preempt spans to it
    trace: Optional[object] = None

    def __post_init__(self):
        if not self.orig_len:
            self.orig_len = len(self.prompt)

    @property
    def ctx_len(self) -> int:
        return len(self.prompt) + len(self.output)

    @property
    def generated(self) -> int:
        return self.ctx_len - self.orig_len

    def finish_reason(self) -> Optional[str]:
        sp = self.sampling
        if self.generated >= sp.max_tokens:
            return "length"
        if sp.stop_token is not None and self.output and \
                self.output[-1] == sp.stop_token:
            return "stop"
        return None


@dataclass
class Plan:
    """What one engine iteration executes."""

    prefill: Optional[Sequence] = None
    decode: List[Sequence] = field(default_factory=list)


class IterationScheduler:
    def __init__(self, max_num_seqs: int, max_prefill_tokens: int,
                 max_model_len: int):
        self.max_num_seqs = max_num_seqs
        self.max_prefill_tokens = max_prefill_tokens
        self.max_model_len = max_model_len
        self.waiting: Deque[Sequence] = deque()
        self.running: List[Sequence] = []

    # ------------------------------------------------------------- lifecycle
    def add(self, seq: Sequence) -> None:
        if len(seq.prompt) > self.max_prefill_tokens:
            raise ValueError(
                f"prompt of {len(seq.prompt)} tokens exceeds "
                f"max_prefill_tokens={self.max_prefill_tokens}")
        if len(seq.prompt) + seq.sampling.max_tokens > self.max_model_len:
            raise ValueError(
                f"prompt+max_tokens {len(seq.prompt)}+"
                f"{seq.sampling.max_tokens} exceeds "
                f"max_model_len={self.max_model_len}")
        self.waiting.append(seq)

    def plan(self, blocks_free: int, blocks_needed_fn) -> Plan:
        """Decide this iteration.  ``blocks_needed_fn(n_tokens)`` maps a
        context length to its block cost (cache geometry lives there)."""
        p = Plan()
        if self.waiting and len(self.running) < self.max_num_seqs:
            head = self.waiting[0]
            # +1: room for the first decode step's block growth so a
            # just-admitted sequence can't immediately trigger preemption
            if blocks_needed_fn(head.ctx_len) + 1 <= blocks_free:
                p.prefill = self.waiting.popleft()
        # decode everything running (the batch bucket pads the rest)
        p.decode = list(self.running)
        return p

    def victim(self) -> Optional[Sequence]:
        """Lowest-priority running sequence = latest arrival."""
        if not self.running:
            return None
        return max(self.running, key=lambda s: s.arrival)

    def preempt(self, seq: Sequence) -> None:
        """Evict: back to the FRONT of the waiting line, prompt extended
        with everything generated so far (re-prefill resumes exactly)."""
        self.running.remove(seq)
        seq.prompt = seq.prompt + seq.output
        seq.output = []
        seq.state = WAITING
        seq.preemptions += 1
        self.waiting.appendleft(seq)

    def start_running(self, seq: Sequence) -> None:
        seq.state = RUNNING
        self.running.append(seq)

    def finish(self, seq: Sequence, state: str = FINISHED) -> None:
        if seq in self.running:
            self.running.remove(seq)
        seq.state = state
        seq.finished_at = time.monotonic()

    def drop_waiting(self, seq: Sequence) -> None:
        try:
            self.waiting.remove(seq)
        except ValueError:
            pass

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)
