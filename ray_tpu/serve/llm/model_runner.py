"""Bucketed JAX prefill/decode execution for the LLM engine.

Shape discipline: XLA compiles one program per distinct input shape, so
an engine seeing arbitrary prompt lengths and batch sizes would
recompile forever.  Every call here is padded up to a configured bucket
(``EngineConfig.prefill_len_buckets`` / ``decode_batch_buckets``) and
the block-table width is fixed at ``max_blocks_per_seq`` — the total
program count is bounded by ``len(prefill_buckets) +
len(decode_buckets)`` for the engine's life (SURVEY.md §7.3: replica
cold starts are XLA compiles; bounding them is the TPU-serving
equivalent of connection pooling).

The runner is model-family-agnostic: ``models/gpt2.py`` and
``models/llama.py`` each export ``forward_prefill`` / ``forward_decode``
(the decode step reads the paged pool through
``ops/paged_attention.py``); sampling (greedy / temperature / top-k)
happens host-side on the (B, V) logits.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import numpy as np

from ray_tpu._private import rtlog
from ray_tpu._private.xla_watchdog import compile_budget
from ray_tpu.serve.llm.config import EngineConfig, SamplingParams, \
    resolve_model

logger = rtlog.get("serve.llm.runner")


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} exceeds largest bucket {buckets[-1]}")


class ModelRunner:
    """Owns params + the jitted, bucketed prefill/decode programs."""

    def __init__(self, cfg: EngineConfig, params=None):
        import jax

        self.cfg = cfg
        self.mod, self.mcfg = resolve_model(cfg)
        self.weights_key: str = ""      # set when the shm plane is used
        if params is None:
            params = self._load_params()
        self.params = params
        self.n_layer = self.mcfg.n_layer
        self.n_kv = getattr(self.mcfg, "n_kv_head", self.mcfg.n_head)
        self.head_dim = self.mcfg.head_dim
        self.vocab = self.mcfg.vocab_size
        self._prefill = jax.jit(partial(self.mod.forward_prefill,
                                        cfg=self.mcfg))
        self._decode = jax.jit(partial(self.mod.forward_decode,
                                       cfg=self.mcfg))
        self.compiles = 0          # observability: distinct programs built
        self._shapes_seen: set = set()
        # XLA watchdog step regions (DESIGN.md §4q): one compile per
        # bucket for the runner's life, zero host transfers inside the
        # dispatch.  The post-dispatch np.asarray pulls are designed
        # syncs and sit OUTSIDE the regions.
        self._prefill_budget = compile_budget(
            "llm.prefill", len(cfg.prefill_len_buckets))
        self._decode_budget = compile_budget(
            "llm.decode", len(cfg.decode_batch_buckets))

    def _load_params(self):
        import jax
        init = partial(self.mod.init_params,
                       jax.random.key(self.cfg.seed), self.mcfg)
        if self.cfg.share_weights:
            from ray_tpu.serve.llm import weights
            self.weights_key = f"{self.cfg.model_key()}_s{self.cfg.seed}"
            return weights.publish_or_attach(self.weights_key, init)
        return init()

    # ---------------------------------------------------------------- prefill
    def prefill(self, token_ids) -> Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]:
        """One prompt → (last-position logits (V,), k, v (L, T, KV, D)).

        The prompt is padded to its length bucket; KV for pad positions
        is garbage and never referenced (the block table fill stops at
        the true length)."""
        import jax.numpy as jnp
        n = len(token_ids)
        tb = _bucket(n, self.cfg.prefill_len_buckets)
        self._note_shape(("prefill", tb))
        toks = np.zeros((1, tb), np.int32)
        toks[0, :n] = token_ids
        # last_pos is TRACED (one compile per bucket, not per length);
        # only the last real position's (1, V) logits come back to host
        last_pos = jnp.int32(n - 1)
        with self._prefill_budget:
            logits, ks, vs = self._prefill(self.params, toks,
                                           last_pos=last_pos)
        logits = np.asarray(logits)[0]                           # (V,)
        ks = np.asarray(ks)[:, 0]                                # (L,T,KV,D)
        vs = np.asarray(vs)[:, 0]
        return logits, ks, vs

    # ----------------------------------------------------------------- decode
    def decode(self, tokens: np.ndarray, positions: np.ndarray,
               kv_pool: np.ndarray, block_tables: np.ndarray,
               ctx_lens: np.ndarray) -> Tuple[np.ndarray, np.ndarray,
                                              np.ndarray]:
        """One iteration over a batch of sequences.

        tokens/positions/ctx_lens (B,); block_tables (B, MAXB);
        kv_pool — the cache's shm-backed ndarray, passed whole (the
        device copy is the CPU rig's stand-in for the pool living in
        HBM).  Returns (logits (B, V), new_k, new_v (L, B, KV, D));
        only the first B rows are real after bucket padding.
        """
        b = len(tokens)
        bb = _bucket(b, self.cfg.decode_batch_buckets)
        self._note_shape(("decode", bb))
        pad = bb - b
        if pad:
            tokens = np.concatenate([tokens, np.zeros(pad, np.int32)])
            positions = np.concatenate([positions,
                                        np.zeros(pad, np.int32)])
            ctx_lens = np.concatenate([ctx_lens, np.zeros(pad, np.int32)])
            block_tables = np.concatenate(
                [block_tables, np.zeros((pad, block_tables.shape[1]),
                                        np.int32)])
        with self._decode_budget:
            logits, ks, vs = self._decode(self.params, tokens,
                                          positions, kv_pool,
                                          block_tables, ctx_lens)
        return (np.asarray(logits)[:b], np.asarray(ks)[:, :b],
                np.asarray(vs)[:, :b])

    def _note_shape(self, key) -> None:
        if key not in self._shapes_seen:
            self._shapes_seen.add(key)
            self.compiles += 1
            logger.info("compiling %s program (total %d)",
                        key, self.compiles)

    # --------------------------------------------------------------- sampling
    @staticmethod
    def sample(logits: np.ndarray, sp: SamplingParams,
               step: int) -> int:
        """Host-side sampling of one token from (V,) logits."""
        if sp.temperature <= 0.0:
            return int(np.argmax(logits))
        x = logits.astype(np.float64) / sp.temperature
        if sp.top_k:
            kth = np.partition(x, -sp.top_k)[-sp.top_k]
            x = np.where(x < kth, -np.inf, x)
        x -= x.max()
        p = np.exp(x)
        p /= p.sum()
        rng = np.random.default_rng((sp.seed, step))
        return int(rng.choice(len(p), p=p))
