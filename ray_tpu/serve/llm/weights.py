"""Model-weight sharing across same-node replicas via the shm plane.

Every LLM replica on a node needs the same parameter pytree.  Loading it
per replica costs init time and N× host memory; instead the first
replica to arrive publishes the flattened parameters into ONE /dev/shm
segment (the same plane the KV pool and object store use) and later
replicas attach read-only — ``np.frombuffer`` views over the shared
mmap, zero-copy on the host side (``jnp.asarray`` copies onto device;
on the CPU rig that copy IS the only copy).

Publication protocol (crash-safe, single-writer):

- segment ``rtpu_llmw_<key>.<publisher_pid>`` holds header (json: leaf
  shapes/dtypes/offsets) + raw leaf bytes; the pid in the name makes a
  SIGKILLed publisher's segment recognizably orphaned, the same
  discipline the KV pool segments use (``kv_cache.py``);
- writers race on an O_EXCL ``.lock`` sentinel; the loser polls for a
  live publisher's ``.ready`` sentinel.  A writer that dies mid-publish
  leaves no ``.ready``; a stale lock (dead pid) is broken by rename
  (single winner); dead publishers' segments are reaped by
  :func:`reap_orphans` at every engine boot.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable

import numpy as np

from ray_tpu._private import rtlog
from ray_tpu._private.shm_store import _SHM_DIR
from ray_tpu.serve.llm.kv_cache import _pid_alive

logger = rtlog.get("serve.llm.weights")

_HDR_LEN_BYTES = 8


def _lock_path(key: str) -> str:
    return str(_SHM_DIR / f"rtpu_llmw_{key}.lock")


def _seg_path(key: str, pid: int) -> str:
    return str(_SHM_DIR / f"rtpu_llmw_{key}.{pid}")


def _parse_pid(name: str):
    core = name[:-len(".ready")] if name.endswith(".ready") else name
    if core.endswith(".lock") or ".stale." in core:
        return None
    try:
        return int(core.rsplit(".", 1)[1])
    except (IndexError, ValueError):
        return None


def _live_segment(key: str):
    """A live publisher's segment base for ``key`` (reaping dead ones)."""
    prefix = f"rtpu_llmw_{key}."
    try:
        names = os.listdir(_SHM_DIR)
    except OSError:
        return None
    for name in names:
        if not (name.startswith(prefix) and name.endswith(".ready")):
            continue
        pid = _parse_pid(name)
        if pid is None:
            continue
        base = str(_SHM_DIR / name[:-len(".ready")])
        if _pid_alive(pid):
            return base
        for p in (str(_SHM_DIR / name), base):
            try:
                os.unlink(p)
            except OSError:
                pass
    return None


def reap_orphans() -> int:
    """Unlink weight segments whose publisher pid is dead (engine boot
    sweep — a SIGKILLed replica cannot release() its own)."""
    n = 0
    try:
        names = os.listdir(_SHM_DIR)
    except OSError:
        return n
    for name in names:
        if not name.startswith("rtpu_llmw_"):
            continue
        pid = _parse_pid(name)
        if pid is None or pid == os.getpid() or _pid_alive(pid):
            continue
        try:
            os.unlink(_SHM_DIR / name)
            n += 1
        except OSError:
            pass
    if n:
        logger.info("reaped %d orphaned weight segment file(s)", n)
    return n


def _flatten(params: Any):
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(params)
    return [np.asarray(x) for x in leaves], treedef


def release(key: str) -> None:
    """Unlink the published segment for ``key`` (engine shutdown).

    Safe at any time: attachers copy the leaves onto the device and
    close their mmap before returning, so nothing references the file
    after publish_or_attach returns — the segment is purely a cache.  A
    concurrent attacher racing the unlink sees FileNotFoundError and
    falls back to a private init.  Unlinks only THIS process's
    published segment (attachers have nothing to release); segments of
    SIGKILLed publishers are swept by :func:`reap_orphans`."""
    base = _seg_path(key, os.getpid())
    for p in (base + ".ready", base):
        try:
            os.unlink(p)
        except OSError:
            pass


def publish_or_attach(key: str, init_fn: Callable[[], Any],
                      timeout_s: float = 120.0) -> Any:
    """Return the param pytree for ``key``, shared through /dev/shm.

    First caller on the node runs ``init_fn`` and publishes; every other
    caller attaches to the published bytes (host-side zero-copy).  On
    any shm failure the caller falls back to a private ``init_fn()``.
    """
    import jax
    lock = _lock_path(key)
    deadline = time.monotonic() + timeout_s
    while True:
        live = _live_segment(key)
        if live is not None:
            try:
                return _attach(live, init_fn)
            except Exception:  # noqa: BLE001 - corrupt/raced segment
                logger.exception("attach to %s failed; loading privately",
                                 live)
                return init_fn()
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o600)
        except FileExistsError:
            # a peer is publishing; break a dead publisher's stale lock.
            # Break-by-RENAME, not unlink: rename succeeds for exactly
            # one racer (the second gets ENOENT), so two waiters can
            # never both "break" and end up publishing concurrently —
            # the loser of the rename just re-enters the O_EXCL race.
            if _lock_stale(lock):
                stale = f"{lock}.stale.{os.getpid()}"
                try:
                    os.rename(lock, stale)
                    os.unlink(stale)
                except OSError:
                    pass
                continue
            if time.monotonic() > deadline:
                logger.warning("weights publish wait timed out for %s; "
                               "loading privately", key)
                return init_fn()
            time.sleep(0.05)
            continue
        try:
            os.write(fd, str(os.getpid()).encode())
        finally:
            os.close(fd)
        params = None
        base = _seg_path(key, os.getpid())
        try:
            params = init_fn()
            _publish(base, base + ".ready", params)
        except Exception:  # noqa: BLE001 - publish best-effort
            if params is None:
                raise      # the model load itself failed: surface it
            logger.exception("weights publish for %s failed; continuing "
                             "with private params", key)
            try:
                os.unlink(base)
            except OSError:
                pass
        finally:
            try:
                os.unlink(lock)
            except OSError:
                pass
        return params


def _lock_stale(lock: str) -> bool:
    try:
        with open(lock, "rb") as f:
            pid = int(f.read().decode() or "0")
    except (OSError, ValueError):
        return False
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return True
    except PermissionError:
        return False
    return False


def _publish(base: str, ready: str, params: Any) -> None:
    leaves, _ = _flatten(params)
    metas, off = [], 0
    for a in leaves:
        metas.append(dict(shape=list(a.shape), dtype=str(a.dtype),
                          offset=off, nbytes=a.nbytes))
        off += a.nbytes
    hdr = json.dumps(metas).encode()
    # pid-unique temp: even if lock-breaking ever admitted two
    # publishers, they cannot tear each other's bytes — os.replace
    # promotes whichever finished last, atomically
    tmp = f"{base}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(len(hdr).to_bytes(_HDR_LEN_BYTES, "little"))
        f.write(hdr)
        for a in leaves:
            f.write(np.ascontiguousarray(a).tobytes())
    os.replace(tmp, base)
    with open(ready, "wb") as f:
        f.write(b"1")
    logger.info("published %d weight leaves (%.1f MB) to %s",
                len(leaves), off / 1e6, base)


def _attach(base: str, init_fn: Callable[[], Any]) -> Any:
    """Map the published segment and rebuild the pytree structure from a
    throwaway abstract init (shapes only, no device work)."""
    import jax
    import mmap as _mmap

    fd = os.open(base, os.O_RDONLY)
    try:
        size = os.fstat(fd).st_size
        mm = _mmap.mmap(fd, size, prot=_mmap.PROT_READ)
    finally:
        os.close(fd)
    hdr_len = int.from_bytes(mm[:_HDR_LEN_BYTES], "little")
    metas = json.loads(mm[_HDR_LEN_BYTES:_HDR_LEN_BYTES + hdr_len])
    body = _HDR_LEN_BYTES + hdr_len
    shapes = jax.eval_shape(init_fn)
    leaves_s, treedef = jax.tree_util.tree_flatten(shapes)
    if len(leaves_s) != len(metas):
        raise ValueError("published leaf count mismatch")
    buf = memoryview(mm)
    try:
        leaves = []
        for m in metas:
            a = np.frombuffer(buf, dtype=np.dtype(m["dtype"]),
                              count=int(np.prod(m["shape"]) or 1),
                              offset=body + m["offset"]).reshape(m["shape"])
            # jnp.asarray copies onto the device buffer, so the mmap can
            # close before returning (no dangling shared pages to leak)
            leaves.append(jax.numpy.asarray(a))
            del a
    finally:
        buf.release()
        try:
            mm.close()
        except BufferError:  # pragma: no cover - view still pinned
            pass
    logger.info("attached %d weight leaves from %s", len(leaves), base)
    return jax.tree_util.tree_unflatten(treedef, leaves)
