"""serve.llm — continuous-batching TPU inference engine (DESIGN.md §4g).

The two mechanisms that made production LLM serving viable, built on the
machinery this framework already has:

- **iteration-level (continuous) scheduling** per Orca (Yu et al.,
  OSDI '22): the batch is re-formed every decode step — new requests'
  prefills interleave with running decodes, finished sequences leave
  immediately, and the lowest-priority sequence is preempted (blocks
  freed, re-prefilled later) under cache pressure.
- **paged KV cache** per PagedAttention (Kwon et al., SOSP '23): the KV
  cache is fixed-size blocks in a shared-memory pool with a block table
  per sequence (``ops/paged_attention.py``), so memory is allocated in
  block grains, prefilled cache is exported/attached between replicas
  over the PR-4 streamed data plane instead of recomputed, and model
  weights are shared across same-node replicas through the same shm
  plane (``serve/llm/weights.py``).

Entry points::

    from ray_tpu.serve import llm
    eng = llm.LLMEngine(llm.EngineConfig(model="gpt2:tiny"))
    for tok in eng.submit([1, 2, 3], llm.SamplingParams(max_tokens=16)):
        ...

    app = llm.llm_deployment(llm.EngineConfig(model="gpt2:tiny")).bind()
    handle = serve.run(app)          # streaming tokens per request
"""

from ray_tpu.serve.llm.config import EngineConfig, SamplingParams  # noqa: F401
from ray_tpu.serve.llm.engine import LLMEngine  # noqa: F401
from ray_tpu.serve.llm.deployment import (  # noqa: F401
    llm_deployment, naive_llm_deployment,
)

__all__ = ["EngineConfig", "SamplingParams", "LLMEngine",
           "llm_deployment", "naive_llm_deployment"]
