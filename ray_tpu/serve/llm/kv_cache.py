"""Paged KV cache: a shm-backed block pool + per-sequence block tables.

Layout (PagedAttention, Kwon et al. SOSP '23): the cache is ONE
shared-memory segment (created through ``ShmObjectStore`` so it rides
the same /dev/shm naming, accounting, and zero-copy mmap semantics as
every other object) viewed as::

    pool[num_blocks, n_layer, 2, block_size, n_kv, head_dim]

Block-major: block ``i`` is a contiguous byte range — one ``tobytes()``
slice is a complete, self-describing transfer unit for the data-plane
export path (``engine.export_seq``), and the whole pool is what the
bucketed decode step reads through the block table
(``ops/paged_attention.py``).

The allocator hands out block indices (free list), tracks a block table
and a refcount per sequence, and frees in block grains — preemption
under cache pressure returns exactly the preempted sequence's blocks.
Shared blocks (an attached sequence re-exported, future prefix caching)
are refcounted: ``free_seq`` returns a block to the free list only at
refcount zero.

Crash hygiene: /dev/shm files outlive a SIGKILLed replica.  Segment
names embed the owning pid; ``reap_orphan_segments()`` unlinks segments
whose owner is gone — called at engine boot (each new engine sweeps its
predecessors' wreckage) and by the chaos suite's assertions.
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Dict, List, Optional

import numpy as np

from ray_tpu._private import rtlog
from ray_tpu._private.shm_store import (ShmObjectStore, _seg_path,
                                        _SHM_DIR, _PREFIX)
from ray_tpu.exceptions import ObjectStoreFullError

logger = rtlog.get("serve.llm.kv")

_POOL_TAG = "llmkv"


class NoFreeBlocks(Exception):
    """Allocation failed: the pool is exhausted (caller should preempt)."""


def pool_segment_name(pid: int, nonce: str) -> str:
    return f"{_POOL_TAG}_{pid}_{nonce}"


def reap_orphan_segments() -> List[str]:
    """Unlink llmkv pool segments whose owning pid is dead.

    A SIGKILLed replica cannot unlink its own segment; the file (and its
    tmpfs pages) would leak until reboot.  Any engine boot — and the
    chaos suite — sweeps them by the pid baked into the name."""
    reaped = []
    try:
        names = os.listdir(_SHM_DIR)
    except OSError:
        return reaped
    for name in names:
        if not name.startswith(f"{_PREFIX}{_POOL_TAG}_"):
            continue
        try:
            pid = int(name.split("_")[2])
        except (IndexError, ValueError):
            continue
        if pid == os.getpid() or _pid_alive(pid):
            continue
        try:
            os.unlink(_SHM_DIR / name)
            reaped.append(name)
        except OSError:
            pass
    if reaped:
        logger.info("reaped %d orphaned KV pool segment(s): %s",
                    len(reaped), reaped)
    return reaped


def reap_orphan_export_spools(base) -> List[str]:
    """Remove rtpu_llm_export_<pid>_* spool dirs whose owner is dead
    (the data-plane export half of :func:`reap_orphan_segments`)."""
    import shutil
    reaped = []
    if not base:
        return reaped
    try:
        names = os.listdir(base)
    except OSError:
        return reaped
    for name in names:
        if not name.startswith("rtpu_llm_export_"):
            continue
        try:
            pid = int(name.split("_")[3])
        except (IndexError, ValueError):
            continue
        if pid == os.getpid() or _pid_alive(pid):
            continue
        shutil.rmtree(os.path.join(base, name), ignore_errors=True)
        reaped.append(name)
    if reaped:
        logger.info("reaped %d orphaned export spool(s): %s",
                    len(reaped), reaped)
    return reaped


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


class PagedKVCache:
    """Block pool + tables + refcounts for one engine instance."""

    def __init__(self, num_blocks: int, n_layer: int, block_size: int,
                 n_kv: int, head_dim: int, dtype=np.float32):
        self.num_blocks = num_blocks
        self.block_shape = (n_layer, 2, block_size, n_kv, head_dim)
        self.block_size = block_size
        self.dtype = np.dtype(dtype)
        self.block_nbytes = int(np.prod(self.block_shape)) * \
            self.dtype.itemsize
        nbytes = self.block_nbytes * num_blocks
        self._seg_name = pool_segment_name(os.getpid(), uuid.uuid4().hex[:8])
        # ShmObjectStore.create gives the O_EXCL + rollback discipline and
        # capacity accounting for free; the pool stays "unsealed" (mutable)
        # for the engine's whole life and is deleted at close().
        self._store = ShmObjectStore(capacity_bytes=nbytes + 1)
        view, handle = self._store.create(self._seg_name, nbytes)
        self._view = view
        self._mm = handle
        self.pool = np.frombuffer(view, dtype=self.dtype).reshape(
            (num_blocks,) + self.block_shape)
        self._lock = threading.Lock()
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))  # guarded by: _lock
        self._tables: Dict[str, List[int]] = {}                      # guarded by: _lock
        self._fill: Dict[str, int] = {}                              # guarded by: _lock
        self._ref: Dict[int, int] = {}                               # guarded by: _lock
        self._closed = False                                         # guarded by: _lock

    # ------------------------------------------------------------ allocation
    def blocks_needed(self, n_tokens: int) -> int:
        return max(1, -(-n_tokens // self.block_size))

    def free_block_count(self) -> int:
        with self._lock:
            return len(self._free)

    def used_block_count(self) -> int:
        with self._lock:
            return self.num_blocks - len(self._free)

    def can_alloc(self, n_blocks: int) -> bool:
        with self._lock:
            return len(self._free) >= n_blocks

    def alloc_seq(self, seq_id: str, n_tokens: int) -> List[int]:
        """Allocate blocks for ``n_tokens`` of context; table starts full
        to ``n_tokens`` (prefill scatters into them immediately)."""
        n = self.blocks_needed(n_tokens)
        with self._lock:
            if seq_id in self._tables:
                raise ValueError(f"sequence {seq_id!r} already allocated")
            if len(self._free) < n:
                raise NoFreeBlocks(
                    f"need {n} blocks, {len(self._free)} free")
            blocks = [self._free.pop() for _ in range(n)]
            for b in blocks:
                self._ref[b] = 1
            self._tables[seq_id] = blocks
            self._fill[seq_id] = n_tokens
        return blocks

    def append_slot(self, seq_id: str) -> tuple:
        """Reserve the next token slot for ``seq_id``.

        Returns (block_id, offset_in_block, grew); grows the table by
        one block at a block boundary (``grew`` True).  Raises
        NoFreeBlocks under cache pressure — the scheduler's preemption
        trigger.  A reservation whose decode step then fails must be
        returned with :meth:`rollback_slot` or every later slot is off
        by one."""
        with self._lock:
            fill = self._fill[seq_id]
            table = self._tables[seq_id]
            blk_i, off = divmod(fill, self.block_size)
            grew = False
            if blk_i == len(table):
                if not self._free:
                    raise NoFreeBlocks(f"pool exhausted growing {seq_id!r}")
                b = self._free.pop()
                self._ref[b] = 1
                table.append(b)
                grew = True
            self._fill[seq_id] = fill + 1
            return table[blk_i], off, grew

    def rollback_slot(self, seq_id: str, grew: bool) -> None:
        """Undo one :meth:`append_slot` reservation (failed decode step)."""
        with self._lock:
            if seq_id not in self._fill:
                return                     # freed/preempted meanwhile
            self._fill[seq_id] -= 1
            if grew:
                b = self._tables[seq_id].pop()
                self._ref[b] -= 1
                if self._ref[b] == 0:
                    del self._ref[b]
                    self._free.append(b)

    def free_seq(self, seq_id: str) -> int:
        """Release a sequence's blocks (refcounted); returns #freed."""
        with self._lock:
            blocks = self._tables.pop(seq_id, None)
            self._fill.pop(seq_id, None)
            if not blocks:
                return 0
            freed = 0
            for b in blocks:
                self._ref[b] -= 1
                if self._ref[b] == 0:
                    del self._ref[b]
                    self._free.append(b)
                    freed += 1
            return freed

    def fork_seq(self, seq_id: str, new_seq_id: str) -> None:
        """Share a sequence's blocks with a new id (refcount bump) —
        the prefix-sharing/export primitive."""
        with self._lock:
            blocks = list(self._tables[seq_id])
            for b in blocks:
                self._ref[b] += 1
            self._tables[new_seq_id] = blocks
            self._fill[new_seq_id] = self._fill[seq_id]

    # ------------------------------------------------------------- accessors
    def table(self, seq_id: str) -> List[int]:
        with self._lock:
            return list(self._tables[seq_id])

    def fill(self, seq_id: str) -> int:
        with self._lock:
            return self._fill[seq_id]

    def has_seq(self, seq_id: str) -> bool:
        with self._lock:
            return seq_id in self._tables

    def seq_ids(self) -> List[str]:
        with self._lock:
            return list(self._tables)

    # ------------------------------------------------------- block transfer
    def block_bytes(self, block_id: int) -> bytes:
        """One block's contiguous bytes (the data-plane export unit)."""
        return self.pool[block_id].tobytes()

    def load_block(self, block_id: int, raw) -> None:
        np.copyto(self.pool[block_id],
                  np.frombuffer(raw, dtype=self.dtype).reshape(
                      self.block_shape))

    def scatter_prefill(self, seq_id: str, ks: np.ndarray,
                        vs: np.ndarray, n_tokens: int) -> None:
        """Write prefill KV (L, T_pad, KV, D) into the seq's blocks
        (only the first ``n_tokens`` positions are real)."""
        table = self.table(seq_id)
        bs = self.block_size
        for i, b in enumerate(table):
            lo = i * bs
            hi = min(n_tokens, lo + bs)
            if hi <= lo:
                break
            self.pool[b, :, 0, :hi - lo] = ks[:, lo:hi]
            self.pool[b, :, 1, :hi - lo] = vs[:, lo:hi]

    def write_token(self, block_id: int, offset: int, k: np.ndarray,
                    v: np.ndarray) -> None:
        """Write one decoded token's (L, KV, D) K/V into its slot."""
        self.pool[block_id, :, 0, offset] = k
        self.pool[block_id, :, 1, offset] = v

    # -------------------------------------------------------------- teardown
    def close(self) -> None:
        """Unmap and unlink the pool segment (idempotent)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self.pool = None   # drop the ndarray ref before releasing its buffer
        try:
            self._view.release()
        except (BufferError, ValueError):
            pass
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass
        self._store.delete_object(self._seg_name)

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass

    @property
    def segment_path(self) -> str:
        return str(_seg_path(self._seg_name))
