"""gRPC proxy actor: binary ingress beside the HTTP proxy.

Reference: ``python/ray/serve/_private/grpc_util.py`` + the Serve 2.x
gRPC proxy — a second ingress for latency-sensitive callers (binary
framing, HTTP/2 multiplexing, no JSON coercion).  The reference requires
user-compiled protobuf servicers; this proxy instead registers a
GENERIC handler that accepts ANY unary-unary method, so callers need no
proto toolchain:

- the request payload is raw bytes, handed to the deployment as-is
  (codec=``bytes``) or unpickled first (metadata ``serve-codec:
  pickle``).  The pickle codec executes arbitrary code on load, so it
  is DISABLED unless the server opts in with
  ``gRPCOptions(allow_pickle=True)`` — only for trusted callers;
- the target application is named by the ``application`` metadata key
  (reference contract) — absent, the method path's service name is
  tried as an app name, then the lone app wins;
- the called deployment method is the final path segment (``/Pkg.Svc/
  Predict`` → ``Predict``) when the ingress class defines it, else
  ``__call__``;
- ``multiplexed_model_id`` metadata routes model-affine (multiplex.py).

Start it with ``serve.start(grpc_options=gRPCOptions(port=...))`` or by
passing ``grpc_options`` to ``serve.run``.
"""

from __future__ import annotations

import pickle
import threading
import time
from concurrent import futures
from typing import Optional

import ray_tpu
from ray_tpu._private import rtlog
from ray_tpu.serve.handle import DeploymentHandle, get_controller

logger = rtlog.get("serve.grpc")


class GrpcProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 request_timeout_s: float = 120.0, max_workers: int = 32,
                 allow_pickle: bool = False):
        import grpc

        self._controller = get_controller()
        self._timeout = request_timeout_s
        self._allow_pickle = allow_pickle
        # 1s-TTL caches (same pattern as the HTTP proxy's route table):
        # the hot path must not pay a controller RPC per request
        self._apps: dict = {}
        self._apps_ts = 0.0
        self._methods: dict = {}      # (dep_key, version, name) -> bool
        self._cache_lock = threading.Lock()
        proxy = self

        class _Generic(grpc.GenericRpcHandler):
            def service(self, call_details):
                method = call_details.method
                meta = {k: v for k, v in
                        (call_details.invocation_metadata or ())}

                def unary(request: bytes, context):
                    return proxy._handle(method, meta, request, context)

                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=None,    # raw bytes in
                    response_serializer=None)     # raw bytes out

        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        self._server.add_generic_rpc_handlers((_Generic(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host
        self._server.start()
        ray_tpu.get(self._controller.set_grpc_address.remote(
            self.host, self.port))
        logger.info("grpc proxy listening on %s:%d", host, self.port)

    def address(self) -> tuple:
        return (self.host, self.port)

    def get_allow_pickle(self) -> bool:
        return self._allow_pickle

    # ---------------------------------------------------------------- routing
    def _apps_cached(self) -> dict:
        if time.monotonic() - self._apps_ts > 1.0:
            # cold start (empty map) blocks ALL callers on the first
            # fetch — a non-blocking loser returning {} would abort a
            # deployed app's request with a spurious NOT_FOUND; once
            # warm, losers serve the stale map without waiting
            if self._cache_lock.acquire(blocking=not self._apps):
                try:
                    self._apps = ray_tpu.get(
                        self._controller.list_app_ingress.remote(),
                        timeout=10)
                    self._apps_ts = time.monotonic()
                except Exception:  # noqa: BLE001 - keep the stale map
                    pass
                finally:
                    self._cache_lock.release()
            elif not self._apps:
                # lost the cold-start race: wait for the winner's fetch
                with self._cache_lock:
                    pass
        return self._apps

    def _resolve(self, method: str, meta: dict) -> Optional[str]:
        """(method path, metadata) → ingress dep_key."""
        apps = self._apps_cached()
        if not apps:
            return None
        app = meta.get("application")
        if app is None and "/" in method:
            svc = method.rsplit("/", 2)[-2]        # "Pkg.Svc"
            tail = svc.rsplit(".", 1)[-1]
            if tail in apps:
                app = tail
        if app is None and len(apps) == 1:
            app = next(iter(apps))
        dep = apps.get(app or "")
        return f"{app}#{dep}" if dep else None

    def _handle(self, method: str, meta: dict, request: bytes, context):
        import grpc
        dep_key = self._resolve(method, meta)
        if dep_key is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"no application for {method!r} "
                          f"(set 'application' metadata)")
        codec = meta.get("serve-codec", "bytes")
        if codec == "pickle" and not self._allow_pickle:
            # pickle.loads on caller-supplied bytes is code execution;
            # require the server-side opt-in (gRPCOptions.allow_pickle)
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "the pickle codec is disabled on this proxy; start serve "
                "with gRPCOptions(allow_pickle=True) to enable it for "
                "trusted callers")
        try:
            payload = pickle.loads(request) if codec == "pickle" else request
        except Exception as e:  # noqa: BLE001
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"bad {codec} payload: {e}")
        call = method.rsplit("/", 1)[-1] or "__call__"
        handle = DeploymentHandle(dep_key)
        router = handle._router()
        target = call if self._dep_has_method(router, call) else "__call__"
        try:
            # request_timeout_s bounds BOTH phases (replica assignment +
            # result wait), matching the HTTP proxy's contract
            start = time.monotonic()
            resp = router.assign(
                target, (payload,), {}, timeout_s=self._timeout,
                multiplexed_model_id=meta.get("multiplexed_model_id", ""))
            remaining = max(0.1, self._timeout -
                            (time.monotonic() - start))
            # raw value, NOT resp.result(): result() turns a stream
            # marker into a live generator, which a unary response
            # cannot carry — we need the marker to reject + cancel
            result = ray_tpu.get(resp._to_object_ref(),
                                 timeout=remaining)
        except ray_tpu.exceptions.RayServeError as e:
            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        except Exception as e:  # noqa: BLE001 - user code raised
            context.abort(grpc.StatusCode.INTERNAL, str(e)[:500])
        if isinstance(result, dict) and "__serve_stream__" in result:
            # streaming deployments need a pull loop against the owning
            # replica; unary gRPC has nowhere to put it — reject cleanly
            # (and free the replica-side generator entry) instead of
            # leaking the stream until the idle reap
            handle = None
            with router._lock:
                handle = router._replicas.get(resp._replica_tag)
            if handle is not None:
                try:
                    handle.stream_cancel.remote(result["__serve_stream__"])
                except Exception:  # noqa: BLE001 - replica may be gone
                    pass
            context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "streaming deployments are not supported over the unary "
                "gRPC ingress; use the HTTP proxy or a handle")
        if codec == "pickle":
            try:
                return pickle.dumps(result)
            except Exception as e:  # noqa: BLE001 - unpicklable result
                context.abort(
                    grpc.StatusCode.INTERNAL,
                    f"result of type {type(result).__name__} is not "
                    f"picklable: {str(e)[:200]}")
        if isinstance(result, (bytes, bytearray, memoryview)):
            return bytes(result)
        if isinstance(result, str):
            return result.encode()
        # structured result over the bytes codec: JSON, matching the
        # HTTP proxy's coercion (numpy results need the pickle codec)
        import json
        try:
            return json.dumps(result).encode()
        except TypeError as e:
            context.abort(
                grpc.StatusCode.INTERNAL,
                f"result of type {type(result).__name__} is not JSON-"
                f"serializable over the bytes codec ({e}); use metadata "
                f"serve-codec=pickle or return bytes/str")

    def _dep_has_method(self, router, name: str) -> bool:
        if name in ("", "__call__"):
            return False
        # keyed by the router's deployment VERSION so a redeploy that
        # adds/removes the method is picked up (the router refreshes its
        # version from the controller every report interval)
        key = (router.dep_key, router._version, name)
        with self._cache_lock:
            if key in self._methods:
                return self._methods[key]
        has = bool(ray_tpu.get(
            self._controller.ingress_has_method.remote(router.dep_key,
                                                       name)))
        with self._cache_lock:
            if len(self._methods) > 4096:
                self._methods.clear()
            self._methods[key] = has
        return has

    def shutdown(self) -> bool:
        self._server.stop(grace=0.5)
        return True
