"""HTTP request/response types handed to Serve ingress deployments.

Reference: Ray Serve hands Starlette ``Request`` objects to ingress
replicas (``python/ray/serve/_private/http_util.py``).  This framework has
no ASGI dependency; the proxy parses HTTP itself and passes this small
picklable ``Request`` to the ingress replica over the actor plane.
"""

from __future__ import annotations

import dataclasses
import json as _json
from typing import Dict, Optional
from urllib.parse import parse_qsl, urlsplit


@dataclasses.dataclass
class Request:
    method: str
    path: str                       # path with the route prefix stripped
    raw_path: str                   # full path as received
    query_params: Dict[str, str]
    headers: Dict[str, str]
    body: bytes = b""

    def json(self):
        return _json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode("utf-8", errors="replace")

    @classmethod
    def from_parts(cls, method: str, target: str, headers: Dict[str, str],
                   body: bytes, route_prefix: str) -> "Request":
        parts = urlsplit(target)
        path = parts.path
        stripped = path[len(route_prefix):] if (
            route_prefix != "/" and path.startswith(route_prefix)) else path
        if not stripped.startswith("/"):
            stripped = "/" + stripped
        return cls(method=method.upper(), path=stripped, raw_path=path,
                   query_params=dict(parse_qsl(parts.query)),
                   headers={k.lower(): v for k, v in headers.items()},
                   body=body)


def match_route(path: str, routes: Dict[str, object]):
    """Longest-prefix route match → (prefix, value) or None.

    Shared by the HTTP proxy's route table and DAGDriver so prefix
    semantics (exact match, or prefix + "/" boundary, "/" catches all)
    can never diverge between the two dispatchers."""
    best = None
    for prefix, value in routes.items():
        if prefix == "/" or path == prefix or path.startswith(
                prefix if prefix.endswith("/") else prefix + "/"):
            if best is None or len(prefix) > len(best[0]):
                best = (prefix, value)
    return best


@dataclasses.dataclass
class Response:
    """Explicit response; any other return value is coerced (see coerce)."""

    body: object = b""
    status_code: int = 200
    content_type: Optional[str] = None

    def encode(self) -> "Response":
        if isinstance(self.body, bytes):
            ct = self.content_type or "application/octet-stream"
            return Response(self.body, self.status_code, ct)
        if isinstance(self.body, str):
            ct = self.content_type or "text/plain; charset=utf-8"
            return Response(self.body.encode(), self.status_code, ct)
        return Response(_json.dumps(self.body).encode(), self.status_code,
                        self.content_type or "application/json")


class StreamingResponse:
    """Incremental response (reference: ``StreamingResponse``): ``content``
    is any iterable/generator; chunks reach the client as produced —
    HTTP clients via chunked transfer encoding, handle callers as a
    generator from ``DeploymentResponse.result()``.

    ``pull_chunks`` caps the chunks one continuation pull returns.  For
    plain iterators each pull blocks until that many chunks (or the
    end), so 16 amortizes round trips for bulk streams.  Producer-paced
    streams should implement ``__serve_poll__(max_chunks)`` on the
    content object instead (see ``Replica.stream_next``): a poll
    returns whatever is READY — first chunk the moment it exists,
    never parking a replica thread until ``pull_chunks`` items have
    been produced — and ``pull_chunks`` only bounds the drain."""

    def __init__(self, content, content_type: str = "text/plain",
                 status_code: int = 200, pull_chunks: int = 16):
        self.content = content
        self.content_type = content_type
        self.status_code = status_code
        self.pull_chunks = max(1, int(pull_chunks))


def encode_chunk(chunk: object) -> bytes:
    if isinstance(chunk, bytes):
        return chunk
    if isinstance(chunk, str):
        return chunk.encode()
    return _json.dumps(chunk).encode()


def coerce_response(value: object) -> Response:
    if isinstance(value, Response):
        return value.encode()
    return Response(value).encode()
