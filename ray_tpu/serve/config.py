"""Serve configuration types.

Reference: ``python/ray/serve/config.py`` (SURVEY.md §2.5, §3.6) —
``AutoscalingConfig`` (min/max replicas, target ongoing requests per
replica, up/downscale delays), HTTP options, deployment options.

TPU note (SURVEY.md §7.3 "Serve cold starts on TPU"): replica startup may
include minutes of XLA compilation, so the autoscaler defaults are
deliberately sticky (long downscale delay) and replicas warm their model in
``__init__`` so a replica is only marked ready once it can serve.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class AutoscalingConfig:
    """Replica autoscaling policy for one deployment.

    ``target_ongoing_requests`` is the per-replica load the autoscaler
    steers toward: desired = ceil(total_ongoing / target).
    """

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    initial_replicas: Optional[int] = None
    upscale_delay_s: float = 30.0
    downscale_delay_s: float = 600.0
    metrics_interval_s: float = 1.0

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < max(1, self.min_replicas):
            raise ValueError("need 0 <= min_replicas <= max_replicas (>=1)")
        if self.target_ongoing_requests <= 0:
            raise ValueError("target_ongoing_requests must be > 0")


@dataclasses.dataclass
class HTTPOptions:
    host: str = "127.0.0.1"
    port: int = 8000
    request_timeout_s: float = 120.0


@dataclasses.dataclass
class gRPCOptions:  # noqa: N801 - reference-parity name
    """Binary ingress options (reference: ``serve.config.gRPCOptions``).

    The reference takes ``grpc_servicer_functions`` (compiled proto
    servicers); this proxy serves a GENERIC unary-unary handler instead
    (any method path, raw-bytes payloads, app selection via
    ``application`` metadata) so no proto toolchain is required — see
    ``_grpc_proxy.py``."""

    host: str = "127.0.0.1"
    port: int = 0
    request_timeout_s: float = 120.0
    # The ``serve-codec: pickle`` metadata deserializes attacker-supplied
    # bytes with pickle — arbitrary code execution for anyone who can reach
    # the port.  It therefore requires an explicit server-side opt-in; only
    # enable it when every possible caller is trusted (e.g. in-cluster
    # callers on a private network).
    allow_pickle: bool = False


@dataclasses.dataclass
class DeploymentConfig:
    """Resolved per-deployment options stored by the controller."""

    num_replicas: int = 1
    max_ongoing_requests: int = 8
    autoscaling_config: Optional[AutoscalingConfig] = None
    ray_actor_options: Optional[dict] = None
    graceful_shutdown_wait_s: float = 2.0
    health_check_period_s: float = 5.0

    def initial_target(self) -> int:
        ac = self.autoscaling_config
        if ac is None:
            return self.num_replicas
        if ac.initial_replicas is not None:
            return ac.initial_replicas
        return max(1, ac.min_replicas)
