"""DAGDriver: multi-route HTTP dispatch over a deployment graph.

Reference: ``python/ray/serve/drivers.py::DAGDriver`` (the Serve 2.x
graph-build API's ingress node): one driver deployment fronts several
bound sub-graphs, routing by path prefix —

    serve.run(DAGDriver.bind({"/a": ModelA.bind(), "/b": ModelB.bind()}))

Each value is an ordinary bound Application node, so the whole dict is
one composed graph (the controller deploys every referenced deployment;
the driver holds child handles).  HTTP requests dispatch to the child
whose route prefix matches the longest; non-HTTP callers can use
``predict(route, *args)`` through a handle, matching the reference's
``DAGDriver.predict`` contract.
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.serve.deployment import Deployment
from ray_tpu.serve.http_util import Request, Response, match_route

# HTTP dispatch bound: matches the proxy's request_timeout_s default so
# a hung child cannot pin a driver replica slot forever
_CHILD_TIMEOUT_S = 120.0


def _norm_prefix(prefix: str) -> str:
    if not prefix.startswith("/"):
        prefix = "/" + prefix
    return prefix.rstrip("/") or "/"


def _validate_route_table(route_table: Any) -> None:
    """Raises at BIND time (driver side) — a replica-side failure would
    only surface as an opaque not-ready deploy timeout."""
    if not isinstance(route_table, dict) or not route_table:
        raise TypeError(
            "DAGDriver.bind takes {route_prefix: bound_app} (a "
            "non-empty dict)")
    seen: Dict[str, str] = {}
    for p in route_table:
        norm = _norm_prefix(p)
        if norm in seen:
            # silent last-wins would deploy the earlier sub-graph but
            # leave it unroutable — fail loudly instead
            raise ValueError(
                f"DAGDriver route prefixes collide after normalization: "
                f"{seen[norm]!r} and {p!r} -> {norm!r}")
        seen[norm] = p


class _DAGDriverImpl:
    """Route-table ingress over child deployment handles."""

    def __init__(self, route_table: Dict[str, Any]):
        _validate_route_table(route_table)  # defense in depth
        # init args arrive with Application nodes already resolved to
        # DeploymentHandles (HandleMarker resolution in the replica)
        self._routes = {_norm_prefix(p): h
                        for p, h in route_table.items()}

    def _match(self, path: str):
        return match_route(path, self._routes)

    def __call__(self, request):
        if not isinstance(request, Request):
            raise TypeError(
                "DAGDriver routes HTTP requests; use .predict(route, *args)"
                " for handle calls")
        m = self._match(request.path)
        if m is None:
            return Response(
                body={"error": f"no DAG route for {request.path}"},
                status_code=404, content_type="application/json")
        prefix, handle = m
        # strip the matched prefix so children see their own sub-path
        sub = request.path[len(prefix):] if prefix != "/" else request.path
        child_req = Request(
            method=request.method, path=sub or "/",
            raw_path=request.raw_path, query_params=request.query_params,
            headers=request.headers, body=request.body)
        return handle.remote(child_req).result(timeout_s=_CHILD_TIMEOUT_S)

    def predict(self, route: str, *args: Any, **kwargs: Any) -> Any:
        """Reference contract: invoke the sub-graph registered at
        ``route`` with raw arguments (non-HTTP path)."""
        m = self._routes.get(_norm_prefix(route))
        if m is None:
            raise KeyError(f"no DAG route {route!r} "
                           f"(have {sorted(self._routes)})")
        return m.remote(*args, **kwargs).result(timeout_s=_CHILD_TIMEOUT_S)


class _DAGDriverDeployment(Deployment):
    """Bind-time validation wrapper: route-table mistakes surface as an
    immediate ValueError/TypeError at ``DAGDriver.bind(...)`` instead of
    a replica-crash → opaque not-ready deploy timeout."""

    def bind(self, *args: Any, **kwargs: Any):
        table = args[0] if args else kwargs.get("route_table")
        _validate_route_table(table)
        return super().bind(*args, **kwargs)


DAGDriver = _DAGDriverDeployment(_DAGDriverImpl, name="DAGDriver")
