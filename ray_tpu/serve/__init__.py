"""ray_tpu.serve — online model serving on TPU actors.

Reference: ``python/ray/serve/`` (SURVEY.md §2.5, §3.6): controller actor
(deployment FSM + autoscaler), HTTP proxy, power-of-two-choices routing,
replica actors with bounded ongoing requests, deployment handles for model
composition, ``@serve.batch`` for request batching.

TPU-first design points:
- replicas warm (build + compile) their model in ``__init__`` and are only
  routed to once ready — XLA cold-compile never happens on the request path;
- ``@serve.batch`` turns request streams into MXU-sized batches;
- the autoscaler's downscale delay is sticky by default because replica
  startup can include minutes of compilation (SURVEY.md §7.3).
"""

from ray_tpu.serve.api import (  # noqa: F401
    delete, get_app_handle, get_deployment_handle, get_grpc_address,
    get_http_address, run, shutdown, start, status,
)
from ray_tpu.serve.batching import batch  # noqa: F401
from ray_tpu.serve.config import (AutoscalingConfig, HTTPOptions,  # noqa: F401
                                  gRPCOptions)
from ray_tpu.serve.multiplex import (  # noqa: F401
    get_multiplexed_model_id, multiplexed,
)
from ray_tpu.serve.deployment import Application, Deployment, deployment  # noqa: F401
from ray_tpu.serve.drivers import DAGDriver  # noqa: F401
from ray_tpu.serve.ingress import HTTPApp, ingress  # noqa: F401
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse  # noqa: F401
from ray_tpu.serve.http_util import (Request, Response,  # noqa: F401
                                     StreamingResponse)

__all__ = [
    "deployment", "run", "start", "shutdown", "status", "delete",
    "get_app_handle", "get_deployment_handle", "get_http_address",
    "get_grpc_address", "batch", "AutoscalingConfig", "HTTPOptions",
    "gRPCOptions", "Application", "StreamingResponse",
    "multiplexed", "get_multiplexed_model_id",
    "Deployment", "DeploymentHandle", "DeploymentResponse",
    "Request", "Response",
]
