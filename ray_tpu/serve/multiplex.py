"""Model multiplexing: many models behind one deployment.

Reference: ``python/ray/serve/multiplex.py`` +
``api.get_multiplexed_model_id`` — one replica pool serves MANY model
checkpoints; each request names a model id, replicas hold an LRU of
loaded models, and the router prefers replicas that already hold the
requested model (so a hot model stays compiled+resident on some replica
instead of being reloaded per request).

TPU note: model load on a TPU replica can include minutes of XLA
compile, which is exactly why affinity routing and LRU retention matter
more here than on CPU serving stacks.

Usage::

    @serve.deployment
    class Multi:
        @serve.multiplexed(max_num_models_per_replica=3)
        async def get_model(self, model_id: str):
            return load_checkpoint(model_id)       # slow: runs on miss

        async def __call__(self, x):
            model = await self.get_model(
                serve.get_multiplexed_model_id())
            return model(x)

    handle.options(multiplexed_model_id="ckpt-7").remote(x)
    # HTTP: curl -H "serve_multiplexed_model_id: ckpt-7" ...
"""

from __future__ import annotations

import asyncio
import collections
import contextvars
import functools
import inspect
import threading
from typing import Any, Callable, Optional

_current_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")

_wrappers_lock = threading.Lock()    # guards lazy per-instance creation


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id of the CURRENT request
    (reference: ``serve.get_multiplexed_model_id``)."""
    return _current_model_id.get()


def _set_model_id(model_id: str):
    return _current_model_id.set(model_id)


class _MultiplexWrapper:
    """Per-(replica, method) LRU of model_id → loaded model."""

    def __init__(self, fn: Callable, max_models: int):
        self.fn = fn
        self.max_models = max_models
        self._models: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._locks: dict = {}          # model_id -> asyncio.Lock
        self._global_lock = threading.Lock()

    def model_ids(self) -> list:
        with self._global_lock:
            return list(self._models)

    def pop_all(self) -> list:
        """Drain the LRU (replica teardown): returns the loaded models;
        the caller runs their unload hooks — kept sync-callable because
        graceful drain runs outside the replica's asyncio loop."""
        with self._global_lock:
            models = list(self._models.values())
            self._models.clear()
            return models

    async def load(self, owner, model_id: Optional[str]) -> Any:
        if model_id is None:
            model_id = get_multiplexed_model_id()
        if not model_id:
            raise ValueError(
                "no multiplexed model id: pass one explicitly or route the "
                "request with handle.options(multiplexed_model_id=...)")
        with self._global_lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
            lock = self._locks.setdefault(model_id, asyncio.Lock())
        async with lock:
            with self._global_lock:
                if model_id in self._models:   # loaded while we waited
                    self._models.move_to_end(model_id)
                    return self._models[model_id]
            if inspect.iscoroutinefunction(self.fn):
                model = await self.fn(owner, model_id)
            else:
                model = self.fn(owner, model_id)
            with self._global_lock:
                self._models[model_id] = model
                evicted = []
                while len(self._models) > self.max_models:
                    evicted.append(self._models.popitem(last=False))
            for _mid, old in evicted:
                # reference behavior: call __del__ via dropping the ref;
                # honor an explicit __serve_unload__/unload hook if present
                hook = getattr(old, "__serve_unload__",
                               getattr(old, "unload", None))
                if callable(hook):
                    try:
                        res = hook()
                        if inspect.isawaitable(res):
                            await res
                    except Exception:  # noqa: BLE001 - best-effort unload
                        pass
            return model


def _lazy_wrapper(owner: Any, attr: str, fn: Callable,
                  max_models: int) -> "_MultiplexWrapper":
    """Get-or-create the per-instance LRU wrapper (replica side)."""
    wrapper = getattr(owner, attr, None)
    if wrapper is None:
        with _wrappers_lock:
            wrapper = getattr(owner, attr, None)
            if wrapper is None:
                wrapper = _MultiplexWrapper(fn, max_models)
                setattr(owner, attr, wrapper)
    return wrapper


def multiplexed(_fn: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator for the model-loading method of a deployment
    (reference: ``serve.multiplexed``).

    The LRU wrapper is created LAZILY on the instance at first use: the
    deployment class is cloudpickled to replicas, and a decoration-time
    wrapper (locks, loaded models) must not ride along."""

    def wrap(fn: Callable) -> Callable:
        attr = f"__serve_mux_{fn.__name__}"
        max_models = max_num_models_per_replica

        @functools.wraps(fn)
        async def load(self, model_id: Optional[str] = None):
            # call-time import: a module-global reference here would get
            # cloudpickled BY VALUE with the deployment class (locks are
            # unpicklable); an import resolves by name on the replica
            from ray_tpu.serve.multiplex import _lazy_wrapper
            wrapper = _lazy_wrapper(self, attr, fn, max_models)
            return await wrapper.load(self, model_id)

        load.__serve_multiplexed__ = True
        return load

    if _fn is not None:
        return wrap(_fn)
    return wrap
