"""``@serve.batch`` — opportunistic request batching inside a replica.

Reference: ``python/ray/serve/batching.py``.  On TPU this is the main lever
for MXU utilization: individual requests are gathered (up to
``max_batch_size`` or ``batch_wait_timeout_s``) and the wrapped method is
invoked once with the list of inputs; results are scattered back.

The wrapped method must be ``async def method(self, items: List[T]) ->
List[R]`` and is called on the replica's asyncio loop, so batching works
with the actor's thread-pool concurrency (each blocked caller thread awaits
its future on the shared loop).
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, List, Optional


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._timeout = timeout_s
        self._queue: "asyncio.Queue" = asyncio.Queue()
        self._flusher: Optional[asyncio.Task] = None

    async def submit(self, owner: Any, item: Any):
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        await self._queue.put((owner, item, fut))
        if self._flusher is None or self._flusher.done():
            self._flusher = loop.create_task(self._flush_loop())
        return await fut

    async def _flush_loop(self):
        while not self._queue.empty():
            owner, item, fut = await self._queue.get()
            batch = [(owner, item, fut)]
            deadline = asyncio.get_running_loop().time() + self._timeout
            while len(batch) < self._max:
                remaining = deadline - asyncio.get_running_loop().time()
                if remaining <= 0:
                    break
                try:
                    batch.append(await asyncio.wait_for(
                        self._queue.get(), timeout=remaining))
                except asyncio.TimeoutError:
                    break
            items: List[Any] = [b[1] for b in batch]
            try:
                results = await self._fn(batch[0][0], items)
                if len(results) != len(items):
                    raise ValueError(
                        f"@serve.batch function returned {len(results)} "
                        f"results for a batch of {len(items)}")
                for (_, _, f), r in zip(batch, results):
                    if not f.done():
                        f.set_result(r)
            except Exception as e:  # noqa: BLE001 - scatter the failure
                for _, _, f in batch:
                    if not f.done():
                        f.set_exception(e)


def batch(_func=None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: ``async def m(self, items: list) -> list`` → per-item calls."""

    def decorator(fn):
        if not asyncio.iscoroutinefunction(fn):
            raise TypeError("@serve.batch requires an async def method")
        attr = f"__serve_batch_queue_{fn.__name__}"

        @functools.wraps(fn)
        async def wrapper(self, item):
            q = getattr(self, attr, None)
            if q is None:
                q = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)
                setattr(self, attr, q)
            return await q.submit(self, item)

        wrapper.__serve_is_batched__ = True
        return wrapper

    if _func is not None:
        return decorator(_func)
    return decorator
