"""Replica actor: hosts one copy of a deployment's user class.

Reference: ``python/ray/serve/_private/replica.py`` (SURVEY.md §3.6) — the
replica wraps the user callable, runs requests with bounded concurrency
(the actor's ``max_concurrency`` = the deployment's
``max_ongoing_requests``; excess calls queue at the actor mailbox), and
owns an asyncio loop so async user methods and ``@serve.batch`` work.

TPU note: model construction (and therefore XLA compilation) happens in
``__init__`` — the controller only marks a replica ready once ``__init__``
returned, so traffic never hits a cold, uncompiled replica (SURVEY.md §7.3).
"""

from __future__ import annotations

import asyncio
import inspect
import threading
from typing import Any, Dict, Tuple


class HandleMarker:
    """Placeholder in init args for a bound sub-deployment (composition)."""

    def __init__(self, dep_key: str):
        self.dep_key = dep_key

    def __repr__(self):
        return f"HandleMarker({self.dep_key})"


def _resolve_markers(obj: Any) -> Any:
    from ray_tpu.serve.handle import DeploymentHandle
    if isinstance(obj, HandleMarker):
        return DeploymentHandle(obj.dep_key)
    if isinstance(obj, list):
        return [_resolve_markers(o) for o in obj]
    if isinstance(obj, tuple):
        return tuple(_resolve_markers(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _resolve_markers(v) for k, v in obj.items()}
    return obj


class Replica:
    def __init__(self, dep_key: str, replica_tag: str, user_cls: type,
                 init_args: Tuple, init_kwargs: Dict):
        self._dep_key = dep_key
        self._replica_tag = replica_tag
        self._loop = asyncio.new_event_loop()
        threading.Thread(target=self._loop.run_forever,
                         name="replica-asyncio", daemon=True).start()
        init_args = _resolve_markers(tuple(init_args))
        init_kwargs = _resolve_markers(dict(init_kwargs))
        self._streams: Dict[str, Tuple[Any, float]] = {}
        self._streams_lock = threading.Lock()
        self._instance = user_cls(*init_args, **init_kwargs)

    def handle_request(self, method: str, args: Tuple, kwargs: Dict):
        import ray_tpu
        from ray_tpu._private.object_ref import ObjectRef

        # Chained DeploymentResponses arrive as ObjectRefs inside the args
        # tuple — possibly nested in containers (the worker only
        # auto-resolves TOP-level task args); resolve them all here so
        # composed deployments see values, not refs.
        def resolve(o):
            if isinstance(o, ObjectRef):
                return ray_tpu.get(o)
            if isinstance(o, list):
                return [resolve(x) for x in o]
            if isinstance(o, tuple):
                return tuple(resolve(x) for x in o)
            if isinstance(o, dict):
                return {k: resolve(v) for k, v in o.items()}
            return o

        args = tuple(resolve(a) for a in args)
        kwargs = {k: resolve(v) for k, v in kwargs.items()}
        m = getattr(self._instance, method)
        if inspect.iscoroutinefunction(m):
            fut = asyncio.run_coroutine_threadsafe(
                m(*args, **kwargs), self._loop)
            result = fut.result()
        else:
            result = m(*args, **kwargs)
        return self._maybe_register_stream(result)

    # ------------------------------------------------------------ streaming
    def _maybe_register_stream(self, result: Any):
        """Generators / StreamingResponse stay replica-side; the caller
        gets a marker and pulls chunks via ``stream_next`` (the router
        pins continuations to THIS replica)."""
        from ray_tpu.serve.http_util import StreamingResponse
        status, ctype, it = 200, "text/plain", None
        if isinstance(result, StreamingResponse):
            status, ctype = result.status_code, result.content_type
            it = (self._drive_asyncgen(result.content)
                  if inspect.isasyncgen(result.content)
                  else iter(result.content))
        elif inspect.isgenerator(result):
            it = result
        elif inspect.isasyncgen(result):
            it = self._drive_asyncgen(result)
        if it is None:
            return result
        import time as _time
        import uuid
        sid = uuid.uuid4().hex
        with self._streams_lock:
            # reap streams abandoned by disconnected clients
            now = _time.time()
            for old in [s for s, (_, ts) in self._streams.items()
                        if now - ts > 600]:
                del self._streams[old]
            self._streams[sid] = (it, now)
        return {"__serve_stream__": sid, "status": status,
                "content_type": ctype}

    def _drive_asyncgen(self, agen):
        while True:
            fut = asyncio.run_coroutine_threadsafe(agen.__anext__(),
                                                   self._loop)
            try:
                yield fut.result()
            except StopAsyncIteration:
                return

    def stream_next(self, sid: str, max_chunks: int = 16):
        """Pull up to ``max_chunks`` items; returns (chunks, done)."""
        import time as _time
        with self._streams_lock:
            entry = self._streams.get(sid)
        if entry is None:
            return [], True
        it = entry[0]
        chunks, done = [], False
        for _ in range(max_chunks):
            try:
                chunks.append(next(it))
            except StopIteration:
                done = True
                break
        with self._streams_lock:
            if done:
                self._streams.pop(sid, None)
            elif sid in self._streams:
                self._streams[sid] = (it, _time.time())
        return chunks, done

    def check_health(self) -> bool:
        chk = getattr(self._instance, "check_health", None)
        if chk is not None:
            chk()
        return True

    def prepare_shutdown(self) -> bool:
        """Graceful drain hook: user ``__del__``-style cleanup before kill."""
        hook = getattr(self._instance, "shutdown", None)
        if callable(hook):
            try:
                hook()
            except Exception:  # noqa: BLE001 - best-effort drain
                pass
        return True
