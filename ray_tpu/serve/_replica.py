"""Replica actor: hosts one copy of a deployment's user class.

Reference: ``python/ray/serve/_private/replica.py`` (SURVEY.md §3.6) — the
replica wraps the user callable, runs requests with bounded concurrency
(the actor's ``max_concurrency`` = the deployment's
``max_ongoing_requests``; excess calls queue at the actor mailbox), and
owns an asyncio loop so async user methods and ``@serve.batch`` work.

TPU note: model construction (and therefore XLA compilation) happens in
``__init__`` — the controller only marks a replica ready once ``__init__``
returned, so traffic never hits a cold, uncompiled replica (SURVEY.md §7.3).
"""

from __future__ import annotations

import asyncio
import inspect
import threading
from typing import Any, Dict, Tuple


class HandleMarker:
    """Placeholder in init args for a bound sub-deployment (composition)."""

    def __init__(self, dep_key: str):
        self.dep_key = dep_key

    def __repr__(self):
        return f"HandleMarker({self.dep_key})"


def _resolve_markers(obj: Any) -> Any:
    from ray_tpu.serve.handle import DeploymentHandle
    if isinstance(obj, HandleMarker):
        return DeploymentHandle(obj.dep_key)
    if isinstance(obj, list):
        return [_resolve_markers(o) for o in obj]
    if isinstance(obj, tuple):
        return tuple(_resolve_markers(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _resolve_markers(v) for k, v in obj.items()}
    return obj


class Replica:
    def __init__(self, dep_key: str, replica_tag: str, user_cls: type,
                 init_args: Tuple, init_kwargs: Dict):
        self._dep_key = dep_key
        self._replica_tag = replica_tag
        self._loop = asyncio.new_event_loop()
        threading.Thread(target=self._loop.run_forever,
                         name="replica-asyncio", daemon=True).start()
        init_args = _resolve_markers(tuple(init_args))
        init_kwargs = _resolve_markers(dict(init_kwargs))
        self._instance = user_cls(*init_args, **init_kwargs)

    def handle_request(self, method: str, args: Tuple, kwargs: Dict):
        import ray_tpu
        from ray_tpu._private.object_ref import ObjectRef

        # Chained DeploymentResponses arrive as ObjectRefs inside the args
        # tuple — possibly nested in containers (the worker only
        # auto-resolves TOP-level task args); resolve them all here so
        # composed deployments see values, not refs.
        def resolve(o):
            if isinstance(o, ObjectRef):
                return ray_tpu.get(o)
            if isinstance(o, list):
                return [resolve(x) for x in o]
            if isinstance(o, tuple):
                return tuple(resolve(x) for x in o)
            if isinstance(o, dict):
                return {k: resolve(v) for k, v in o.items()}
            return o

        args = tuple(resolve(a) for a in args)
        kwargs = {k: resolve(v) for k, v in kwargs.items()}
        m = getattr(self._instance, method)
        if inspect.iscoroutinefunction(m):
            fut = asyncio.run_coroutine_threadsafe(
                m(*args, **kwargs), self._loop)
            return fut.result()
        return m(*args, **kwargs)

    def check_health(self) -> bool:
        chk = getattr(self._instance, "check_health", None)
        if chk is not None:
            chk()
        return True

    def prepare_shutdown(self) -> bool:
        """Graceful drain hook: user ``__del__``-style cleanup before kill."""
        hook = getattr(self._instance, "shutdown", None)
        if callable(hook):
            try:
                hook()
            except Exception:  # noqa: BLE001 - best-effort drain
                pass
        return True
