"""Replica actor: hosts one copy of a deployment's user class.

Reference: ``python/ray/serve/_private/replica.py`` (SURVEY.md §3.6) — the
replica wraps the user callable, runs requests with bounded concurrency
(the actor's ``max_concurrency`` = the deployment's
``max_ongoing_requests``; excess calls queue at the actor mailbox), and
owns an asyncio loop so async user methods and ``@serve.batch`` work.

TPU note: model construction (and therefore XLA compilation) happens in
``__init__`` — the controller only marks a replica ready once ``__init__``
returned, so traffic never hits a cold, uncompiled replica (SURVEY.md §7.3).
"""

from __future__ import annotations

import asyncio
import inspect
import threading
from typing import Any, Dict, Tuple


class HandleMarker:
    """Placeholder in init args for a bound sub-deployment (composition)."""

    def __init__(self, dep_key: str):
        self.dep_key = dep_key

    def __repr__(self):
        return f"HandleMarker({self.dep_key})"


def _resolve_markers(obj: Any) -> Any:
    from ray_tpu.serve.handle import DeploymentHandle
    if isinstance(obj, HandleMarker):
        return DeploymentHandle(obj.dep_key)
    if isinstance(obj, list):
        return [_resolve_markers(o) for o in obj]
    if isinstance(obj, tuple):
        return tuple(_resolve_markers(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _resolve_markers(v) for k, v in obj.items()}
    return obj


class Replica:
    def __init__(self, dep_key: str, replica_tag: str, user_cls: type,
                 init_args: Tuple, init_kwargs: Dict):
        self._dep_key = dep_key
        self._replica_tag = replica_tag
        self._loop = asyncio.new_event_loop()
        threading.Thread(target=self._loop.run_forever,
                         name="replica-asyncio", daemon=True).start()
        init_args = _resolve_markers(tuple(init_args))
        init_kwargs = _resolve_markers(dict(init_kwargs))
        self._streams: Dict[str, Tuple[Any, float]] = {}
        self._streams_lock = threading.Lock()
        self._ongoing = 0
        self._ongoing_lock = threading.Lock()
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu.util import metrics_catalog as mcat
        if GLOBAL_CONFIG.metrics_enabled:
            # group-label convention (metrics_catalog.py): this process
            # hosts exactly one replica of one deployment, so the LLM
            # engine's rtpu_llm_* series — emitted deep inside the
            # engine, where no dep_key is in scope — inherit the
            # deployment key as ``group`` via process-level default tags
            # (stamped BEFORE user __init__ constructs the engine).
            for _name in ("rtpu_llm_sequences", "rtpu_llm_kv_blocks",
                          "rtpu_llm_batch_occupancy",
                          "rtpu_llm_preemptions_total",
                          "rtpu_llm_ttft_seconds",
                          "rtpu_llm_tpot_seconds",
                          "rtpu_llm_tokens_total"):
                mcat.get(_name).set_default_tags({"group": dep_key})
        self._instance = user_cls(*init_args, **init_kwargs)

    def _track_ongoing(self, delta: int) -> None:
        """rtpu_serve_ongoing_requests: requests executing inside THIS
        replica right now (reference: ``serve_replica_processing_queries``).
        The replica process's background publisher ships it — the
        controller's autoscaler signal stays handle-reported and
        unchanged."""
        from ray_tpu._private.config import GLOBAL_CONFIG
        from ray_tpu.util import metrics_catalog as mcat
        with self._ongoing_lock:
            # gauge set INSIDE the lock: counter update and publication
            # must be atomic, or a delayed set() from a finished request
            # can overwrite a newer value and stick the gauge wrong until
            # the next request
            self._ongoing += delta
            if GLOBAL_CONFIG.metrics_enabled:
                mcat.get("rtpu_serve_ongoing_requests").set(
                    self._ongoing, tags={"deployment": self._dep_key,
                                         "replica": self._replica_tag,
                                         "group": self._dep_key})

    def handle_request(self, method: str, args: Tuple, kwargs: Dict):
        self._track_ongoing(1)
        try:
            return self._handle_request(method, args, kwargs)
        finally:
            self._track_ongoing(-1)

    def _handle_request(self, method: str, args: Tuple, kwargs: Dict):
        import ray_tpu
        from ray_tpu._private.object_ref import ObjectRef

        # Chained DeploymentResponses arrive as ObjectRefs inside the args
        # tuple — possibly nested in containers (the worker only
        # auto-resolves TOP-level task args); resolve them all here so
        # composed deployments see values, not refs.
        def resolve(o):
            if isinstance(o, ObjectRef):
                return ray_tpu.get(o)
            if isinstance(o, list):
                return [resolve(x) for x in o]
            if isinstance(o, tuple):
                return tuple(resolve(x) for x in o)
            if isinstance(o, dict):
                return {k: resolve(v) for k, v in o.items()}
            return o

        args = tuple(resolve(a) for a in args)
        kwargs = {k: resolve(v) for k, v in kwargs.items()}
        # multiplex routing metadata rides a reserved kwarg; expose it to
        # the user method via serve.get_multiplexed_model_id()
        model_id = kwargs.pop("__serve_model_id__", "")
        from ray_tpu.serve import multiplex as _mux
        m = getattr(self._instance, method)
        if inspect.iscoroutinefunction(m):
            # contextvars do not cross run_coroutine_threadsafe into the
            # loop thread; set the id inside the task's own context
            async def _run():
                tok = _mux._set_model_id(model_id)
                try:
                    return await m(*args, **kwargs)
                finally:
                    _mux._current_model_id.reset(tok)

            fut = asyncio.run_coroutine_threadsafe(_run(), self._loop)
            result = fut.result()
        else:
            token = _mux._set_model_id(model_id)
            try:
                result = m(*args, **kwargs)
            finally:
                _mux._current_model_id.reset(token)
        return self._maybe_register_stream(result, model_id)

    # ------------------------------------------------------------ streaming
    def _maybe_register_stream(self, result: Any, model_id: str = ""):
        """Generators / StreamingResponse stay replica-side; the caller
        gets a marker and pulls chunks via ``stream_next`` (the router
        pins continuations to THIS replica).  ``model_id`` is remembered
        with the stream: a generator body executes during stream_next
        pulls (arbitrary actor threads), so get_multiplexed_model_id()
        must be re-established around each pull, not around the call
        that merely CREATED the generator."""
        from ray_tpu.serve.http_util import StreamingResponse
        status, ctype, it, pull = 200, "text/plain", None, 16
        if isinstance(result, StreamingResponse):
            status, ctype = result.status_code, result.content_type
            pull = result.pull_chunks
            it = (self._drive_asyncgen(result.content, model_id)
                  if inspect.isasyncgen(result.content)
                  else iter(result.content))
        elif inspect.isgenerator(result):
            it = result
        elif inspect.isasyncgen(result):
            it = self._drive_asyncgen(result, model_id)
        if it is None:
            return result
        import time as _time
        import uuid
        self._reap_abandoned_streams()
        sid = uuid.uuid4().hex
        with self._streams_lock:
            self._streams[sid] = (it, _time.time(), model_id)
        # a live stream IS an ongoing request: the generator body runs
        # during later stream_next pulls, after handle_request's finally
        # already decremented — re-count it until the stream completes
        # (stream_next done / cancel / abandoned-reap)
        self._track_ongoing(1)
        return {"__serve_stream__": sid, "status": status,
                "content_type": ctype, "pull": pull}

    def _reap_abandoned_streams(self, max_age_s: float = 600.0) -> None:
        """Drop streams whose client vanished without draining or
        cancelling — pop under the lock, close OUTSIDE it (a generator
        finally can block; it must not stall every concurrent stream on
        the replica).  Runs on every new stream registration AND from the
        controller's periodic check_health, so an idle replica's
        ongoing-request gauge cannot stay stuck on a phantom stream."""
        import time as _time
        with self._streams_lock:
            now = _time.time()
            reaped = [self._streams.pop(s) for s, entry in
                      list(self._streams.items())
                      if now - entry[1] > max_age_s]
        if reaped:
            self._track_ongoing(-len(reaped))
        for entry in reaped:
            close = getattr(entry[0], "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 - user finally raised
                    pass

    def _drive_asyncgen(self, agen, model_id: str = ""):
        from ray_tpu.serve import multiplex as _mux

        async def _next():
            # async-gen body runs on the LOOP thread: establish the
            # multiplexed model id in that task's context per pull
            tok = _mux._set_model_id(model_id)
            try:
                return await agen.__anext__()
            finally:
                _mux._current_model_id.reset(tok)

        try:
            while True:
                fut = asyncio.run_coroutine_threadsafe(_next(), self._loop)
                try:
                    yield fut.result()
                except StopAsyncIteration:
                    return
        finally:
            # closing this sync wrapper (stream_cancel / abandoned-stream
            # reap) must also close the UNDERLYING async generator so
            # ``finally`` blocks in the deployment body run now — aclose
            # has to execute on the loop thread that owns the agen
            try:
                asyncio.run_coroutine_threadsafe(
                    agen.aclose(), self._loop).result(timeout=5)
            except Exception:  # noqa: BLE001 - already closed / loop gone
                pass

    def stream_next(self, sid: str, max_chunks: int = 16):
        """Pull up to ``max_chunks`` items; returns (chunks, done).

        If the stream object implements ``__serve_poll__(max_chunks)``
        — returning (ready_chunks, done) without blocking until
        ``max_chunks`` items EXIST — it is preferred over ``next()``:
        a latency-bound producer (serve.llm decode loop) then occupies
        this actor thread only until the first chunk (bounded wait),
        not for ``max_chunks`` production steps, and an idle stream
        returns ``([], False)`` so hundreds of pending streams cannot
        starve the replica's thread pool out of serving new requests."""
        import time as _time

        from ray_tpu.serve import multiplex as _mux
        with self._streams_lock:
            entry = self._streams.get(sid)
        if entry is None:
            return [], True
        it, _, model_id = entry
        chunks, done = [], False
        token = _mux._set_model_id(model_id)
        try:
            try:
                poll = getattr(it, "__serve_poll__", None)
                if poll is not None:
                    chunks, done = poll(max_chunks)
                    chunks = list(chunks)
                else:
                    for _ in range(max_chunks):
                        try:
                            chunks.append(next(it))
                        except StopIteration:
                            done = True
                            break
            except BaseException:
                # a producer failure (e.g. the llm engine failing the
                # request) ends the stream NOW: deregister and release
                # the ongoing-request slot instead of pinning both
                # until the 600s abandoned-stream reap
                with self._streams_lock:
                    popped = self._streams.pop(sid, None)
                if popped is not None:
                    self._track_ongoing(-1)
                    close = getattr(popped[0], "close", None)
                    if close is not None:
                        try:
                            close()
                        except Exception:  # noqa: BLE001 - already dead
                            pass
                raise
        finally:
            _mux._current_model_id.reset(token)
        popped = None
        with self._streams_lock:
            if done:
                popped = self._streams.pop(sid, None)
            elif sid in self._streams:
                self._streams[sid] = (it, _time.time(), model_id)
        if popped is not None:
            self._track_ongoing(-1)  # stream drained: no longer ongoing
        return chunks, done

    def stream_cancel(self, sid: str) -> bool:
        """Drop a stream's generator without draining it (e.g. the unary
        gRPC ingress rejecting a streaming result); closes the generator
        so ``finally`` blocks in the deployment body run now, not at the
        600s abandoned-stream reap."""
        with self._streams_lock:
            entry = self._streams.pop(sid, None)
        if entry is None:
            return False
        self._track_ongoing(-1)  # cancelled stream: no longer ongoing
        it = entry[0]
        close = getattr(it, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001 - user finally raised
                pass
        return True

    def check_health(self) -> bool:
        self._reap_abandoned_streams()  # periodic gauge/stream hygiene
        chk = getattr(self._instance, "check_health", None)
        if chk is not None:
            chk()
        return True

    def prepare_shutdown(self) -> bool:
        """Graceful drain hook: user ``__del__``-style cleanup before kill."""
        hook = getattr(self._instance, "shutdown", None)
        if callable(hook):
            try:
                hook()
            except Exception:  # noqa: BLE001 - best-effort drain
                pass
        return True
