"""DeploymentHandle + Router: request assignment to replicas.

Reference: ``python/ray/serve/handle.py`` + the power-of-two-choices
``ReplicaScheduler`` (SURVEY.md §3.6).  The router keeps a local
ongoing-request count per replica, picks the less-loaded of two random
replicas, and periodically (a) reaps completed requests, (b) refreshes the
replica set from the controller, and (c) pushes per-deployment ongoing
counts to the controller — the autoscaler's input signal (as in Ray 2.x,
where handles report metrics rather than replicas).
"""

from __future__ import annotations

import os
import random
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu._private import rtlog

logger = rtlog.get("serve.router")

CONTROLLER_NAME = "SERVE_CONTROLLER"
_REPORT_INTERVAL_S = float(os.environ.get("RTPU_SERVE_REPORT_S", "0.5"))
# Max distinct multiplexed model ids tracked for replica affinity; LRU
# beyond this (each entry is ≤4 replica tags — a few thousand ids is KBs).
_AFFINITY_MAX_IDS = int(os.environ.get("RTPU_SERVE_AFFINITY_MAX_IDS", "4096"))


def get_controller():
    return ray_tpu.get_actor(CONTROLLER_NAME)


class DeploymentResponse:
    """Future for one assigned request (reference: ``DeploymentResponse``)."""

    def __init__(self, ref, router: "Router", replica_tag: str):
        self._ref = ref
        self._router = router
        self._replica_tag = replica_tag

    def result(self, timeout_s: Optional[float] = None) -> Any:
        out = ray_tpu.get(self._ref, timeout=timeout_s)
        if isinstance(out, dict) and "__serve_stream__" in out:
            # streaming method: hand back a generator that pulls chunks
            # from the replica that owns the generator state
            return self._stream_chunks(out["__serve_stream__"],
                                       out.get("pull", 16))
        return out

    def _stream_chunks(self, sid: str, pull: int = 16):
        # Re-look-up the replica on every pull: generator state lives on
        # the replica, so a replica that dies (or is scaled away) mid-stream
        # must surface as RayServeError, not a raw actor error.
        while True:
            with self._router._lock:
                handle = self._router._replicas.get(self._replica_tag)
            if handle is None:
                raise ray_tpu.exceptions.RayServeError(
                    "streaming replica went away mid-stream")
            try:
                chunks, done = ray_tpu.get(
                    handle.stream_next.remote(sid, pull))
            except ray_tpu.exceptions.RayActorError as e:
                raise ray_tpu.exceptions.RayServeError(
                    "streaming replica died mid-stream") from e
            yield from chunks
            if done:
                return
            if not chunks:
                # producer-paced stream (__serve_poll__) with nothing
                # ready: back off briefly instead of hammering the
                # replica mailbox
                time.sleep(0.05)

    def _to_object_ref(self):
        return self._ref


def _strip_responses(obj: Any) -> Any:
    if isinstance(obj, DeploymentResponse):
        return obj._to_object_ref()
    if isinstance(obj, list):
        return [_strip_responses(o) for o in obj]
    if isinstance(obj, tuple):
        return tuple(_strip_responses(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _strip_responses(v) for k, v in obj.items()}
    return obj


class Router:
    _instances: Dict[str, "Router"] = {}
    _instances_lock = threading.Lock()

    @classmethod
    def for_deployment(cls, dep_key: str) -> "Router":
        with cls._instances_lock:
            r = cls._instances.get(dep_key)
            if r is None:
                r = cls._instances[dep_key] = Router(dep_key)
            return r

    @classmethod
    def reset_all(cls) -> None:
        with cls._instances_lock:
            for r in cls._instances.values():
                r._stop.set()
            cls._instances.clear()

    def __init__(self, dep_key: str):
        self.dep_key = dep_key
        self.router_id = uuid.uuid4().hex[:12]
        self._controller = None
        self._replicas: Dict[str, Any] = {}      # tag -> ActorHandle
        self._counts: Dict[str, int] = {}        # tag -> my ongoing
        self._outstanding: Dict[str, str] = {}   # ref id -> tag
        self._out_refs: Dict[str, Any] = {}      # ref id -> ObjectRef
        # model-multiplex affinity: model_id -> replica tags that have
        # served it (most recent last); the router prefers these so a
        # loaded (possibly XLA-compiled) model stays resident.  Bounded
        # LRU over model ids — a long-lived router seeing an unbounded id
        # stream must not grow without limit.
        self._model_affinity: "OrderedDict[str, List[str]]" = OrderedDict()
        self._pending = 0        # waiting in assign() — autoscale signal too
        self._max_ongoing = 0    # 0 = unknown/unbounded
        self._deployment_gone = False  # controller no longer knows the key
        self._version = -1
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._last_refresh = 0.0
        threading.Thread(target=self._background_loop,
                         name=f"serve-router-{dep_key}", daemon=True).start()

    # ---------------------------------------------------------------- routing
    def assign(self, method: str, args: tuple, kwargs: dict,
               timeout_s: float = 60.0,
               multiplexed_model_id: str = "") -> DeploymentResponse:
        # DeploymentResponses anywhere in the args become ObjectRefs (they
        # hold live threads/locks and must never be pickled); the replica
        # resolves refs — nested ones included — back to values.
        args = tuple(_strip_responses(a) for a in args)
        kwargs = {k: _strip_responses(v) for k, v in kwargs.items()}
        deadline = time.monotonic() + timeout_s
        with self._lock:
            self._pending += 1
        try:
            while True:
                with self._lock:
                    tags = list(self._replicas)
                    if tags:
                        tag = self._pick(tags, multiplexed_model_id)
                        # Enforce max_ongoing_requests at the router: hold
                        # the request here (counted in _pending → autoscale
                        # signal) instead of queueing it at a full replica.
                        if not self._max_ongoing or \
                                self._counts.get(tag, 0) < self._max_ongoing:
                            handle = self._replicas[tag]
                            self._counts[tag] = self._counts.get(tag, 0) + 1
                            break
                if time.monotonic() > deadline:
                    raise ray_tpu.exceptions.RayServeError(
                        f"no replica of {self.dep_key!r} became available "
                        f"within {timeout_s}s")
                self._refresh(force=True)
                self._reap()
                time.sleep(0.05)
        finally:
            with self._lock:
                self._pending -= 1
        if multiplexed_model_id:
            kwargs = dict(kwargs)
            kwargs["__serve_model_id__"] = multiplexed_model_id
            with self._lock:
                aff = self._model_affinity.setdefault(
                    multiplexed_model_id, [])
                if tag in aff:
                    aff.remove(tag)
                aff.append(tag)
                del aff[:-4]             # keep the few most recent holders
                self._model_affinity.move_to_end(multiplexed_model_id)
                while len(self._model_affinity) > _AFFINITY_MAX_IDS:
                    self._model_affinity.popitem(last=False)
        ref = handle.handle_request.remote(method, args, kwargs)
        with self._lock:
            self._outstanding[str(ref.id)] = tag
            self._out_refs[str(ref.id)] = ref
        return DeploymentResponse(ref, self, tag)

    def _pick(self, tags: List[str], model_id: str = "") -> str:
        if model_id:
            # prefer the most recent non-saturated replica known to hold
            # this model (reference: multiplex-aware replica scheduler)
            for tag in reversed(self._model_affinity.get(model_id, [])):
                if tag in self._replicas and (
                        not self._max_ongoing
                        or self._counts.get(tag, 0) < self._max_ongoing):
                    return tag
        if len(tags) == 1:
            return tags[0]
        a, b = random.sample(tags, 2)
        ca, cb = self._counts.get(a, 0), self._counts.get(b, 0)
        return a if ca <= cb else b

    # ------------------------------------------------------------- background
    def _background_loop(self) -> None:
        while not self._stop.wait(_REPORT_INTERVAL_S):
            try:
                self._reap()
                self._refresh()
                self._report()
            except Exception:  # noqa: BLE001 - cluster may be shutting down
                if ray_tpu.is_initialized():
                    logger.exception("router background loop error")
                else:
                    return

    def _reap(self) -> None:
        with self._lock:
            refs = list(self._out_refs.values())
        if not refs:
            return
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0)
        if not ready:
            return
        with self._lock:
            for r in ready:
                tag = self._outstanding.pop(str(r.id), None)
                self._out_refs.pop(str(r.id), None)
                if tag is not None and tag in self._counts:
                    self._counts[tag] = max(0, self._counts[tag] - 1)

    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        min_gap = 0.2 if force else 2 * _REPORT_INTERVAL_S
        if now - self._last_refresh < min_gap:
            return
        self._last_refresh = now
        if self._controller is None:
            self._controller = get_controller()
        info = ray_tpu.get(
            self._controller.get_deployment_targets.remote(self.dep_key))
        if info is None:
            # deployment deleted: stop republishing its last queue depth
            # (the flag keeps _report from resurrecting the series on the
            # very next tick)
            self._deployment_gone = True
            from ray_tpu.util import metrics_catalog as mcat
            mcat.get("rtpu_serve_replica_queue_depth").remove_series(
                tags={"deployment": self.dep_key, "group": self.dep_key})
            return
        self._deployment_gone = False  # (re)deployed
        with self._lock:
            self._max_ongoing = info.get("max_ongoing") or 0
            if info["version"] == self._version and not force:
                return
            self._version = info["version"]
            new = {}
            for tag, actor_name in info["replicas"].items():
                if tag in self._replicas:
                    new[tag] = self._replicas[tag]
                else:
                    try:
                        new[tag] = ray_tpu.get_actor(actor_name)
                    except Exception:  # noqa: BLE001 - not registered yet
                        continue
            self._replicas = new
            self._counts = {t: self._counts.get(t, 0) for t in new}
            # drop affinity tags for replicas that no longer exist (and
            # the id entirely once no live replica holds it)
            for mid in list(self._model_affinity):
                live = [t for t in self._model_affinity[mid] if t in new]
                if live:
                    self._model_affinity[mid] = live
                else:
                    del self._model_affinity[mid]

    def _report(self) -> None:
        if self._controller is None or self._deployment_gone:
            return
        with self._lock:
            # Waiting-to-be-assigned requests count toward load, otherwise
            # scale-from-zero (min_replicas=0) could never trigger.
            total = len(self._outstanding) + self._pending
            pending = self._pending
        from ray_tpu._private.config import GLOBAL_CONFIG
        if GLOBAL_CONFIG.metrics_enabled:
            # queue depth = requests held in assign() by the
            # max_ongoing_requests gate; the backpressure signal operators
            # watch to see a saturated deployment before latency blows up
            from ray_tpu.util import metrics_catalog as mcat
            mcat.get("rtpu_serve_replica_queue_depth").set(
                pending,
                tags={"deployment": self.dep_key, "group": self.dep_key})
        self._controller.report_handle_stats.remote(
            self.router_id, self.dep_key, total)


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args: Any, **kwargs: Any) -> DeploymentResponse:
        return self._handle._router().assign(
            self._method, args, kwargs,
            multiplexed_model_id=self._handle._model_id)


_warned_handle_options: set = set()


class DeploymentHandle:
    """Callable reference to a deployment; picklable across processes."""

    def __init__(self, dep_key: str, multiplexed_model_id: str = ""):
        self._dep_key = dep_key
        self._model_id = multiplexed_model_id

    def options(self, *, multiplexed_model_id: str = "",
                **_compat: Any) -> "DeploymentHandle":
        """Per-request routing options (reference:
        ``handle.options(multiplexed_model_id=...)``).

        Unrecognized reference options (``method_name``, ``stream``, …)
        are NOT silently honored here — warn so callers porting reference
        code see the behavior difference instead of a silent no-op."""
        if _compat:
            unknown = tuple(sorted(_compat))
            if unknown not in _warned_handle_options:   # once per shape,
                _warned_handle_options.add(unknown)     # not per request
                logger.warning(
                    "DeploymentHandle.options(): unsupported option(s) %s "
                    "ignored — only multiplexed_model_id is honored "
                    "(call methods as handle.method.remote(...) instead of "
                    "method_name=...)", list(unknown))
        return DeploymentHandle(self._dep_key, multiplexed_model_id)

    def _router(self) -> Router:
        return Router.for_deployment(self._dep_key)

    def remote(self, *args: Any, **kwargs: Any) -> DeploymentResponse:
        return self._router().assign("__call__", args, kwargs,
                                     multiplexed_model_id=self._model_id)

    def __getattr__(self, name: str) -> _MethodCaller:
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    def __reduce__(self):
        return (DeploymentHandle, (self._dep_key, self._model_id))

    def __repr__(self):
        return f"DeploymentHandle({self._dep_key!r})"
