"""Serve public API: start / run / status / shutdown / handles.

Reference: ``python/ray/serve/api.py`` (SURVEY.md §3.6).  ``serve.run``
deploys a bound application graph onto the running ray_tpu cluster; the
controller and HTTP proxy are detached named actors, so applications
outlive the deploying driver until ``serve.shutdown()``.
"""

from __future__ import annotations

import time
from typing import Optional

import ray_tpu
from ray_tpu.serve._proxy import ProxyActor
from ray_tpu.serve.config import HTTPOptions, gRPCOptions
from ray_tpu.serve.controller import ServeController
from ray_tpu.serve.deployment import Application
from ray_tpu.serve.handle import (CONTROLLER_NAME, DeploymentHandle, Router,
                                  get_controller)

PROXY_NAME = "SERVE_PROXY"
GRPC_PROXY_NAME = "SERVE_GRPC_PROXY"


def start(http_options: Optional[HTTPOptions] = None, *,
          proxy: bool = True,
          grpc_options: Optional[gRPCOptions] = None):
    """Idempotently start the Serve system actors; returns the controller."""
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    requested = http_options
    http_options = http_options or HTTPOptions(port=0)
    controller = ray_tpu.remote(ServeController).options(
        name=CONTROLLER_NAME, lifetime="detached", num_cpus=0,
        max_concurrency=8, get_if_exists=True).remote()
    ray_tpu.get(controller.__ray_ready__.remote())
    if proxy:
        p = ray_tpu.remote(ProxyActor).options(
            name=PROXY_NAME, lifetime="detached", num_cpus=0,
            max_concurrency=32, get_if_exists=True,
        ).remote(http_options.host, http_options.port,
                 http_options.request_timeout_s)
        ray_tpu.get(p.__ray_ready__.remote())
        if requested is not None:
            actual = ray_tpu.get(controller.get_http_address.remote())
            if actual is not None and requested.port not in (0, actual[1]):
                from ray_tpu._private import rtlog
                rtlog.get("serve").warning(
                    "Serve proxy already running on %s:%d; requested "
                    "http_options (port=%d) ignored — call serve.shutdown() "
                    "first to change HTTP options", actual[0], actual[1],
                    requested.port)
    if grpc_options is not None:
        from ray_tpu.serve._grpc_proxy import GrpcProxyActor
        g = ray_tpu.remote(GrpcProxyActor).options(
            name=GRPC_PROXY_NAME, lifetime="detached", num_cpus=0,
            max_concurrency=32, get_if_exists=True,
        ).remote(grpc_options.host, grpc_options.port,
                 grpc_options.request_timeout_s,
                 allow_pickle=getattr(grpc_options, "allow_pickle", False))
        ray_tpu.get(g.__ray_ready__.remote())
        actual = ray_tpu.get(controller.get_grpc_address.remote())
        if actual is not None and grpc_options.port not in (0, actual[1]):
            from ray_tpu._private import rtlog
            rtlog.get("serve").warning(
                "Serve gRPC proxy already running on %s:%d; requested "
                "grpc_options (port=%d) ignored — call serve.shutdown() "
                "first to change gRPC options", actual[0], actual[1],
                grpc_options.port)
        # get_if_exists can hand back a proxy started with a DIFFERENT
        # pickle posture — __init__ options don't re-apply.  A silent
        # mismatch in either direction is a security surprise; warn.
        requested_ap = getattr(grpc_options, "allow_pickle", False)
        try:
            actual_ap = ray_tpu.get(g.get_allow_pickle.remote(), timeout=10)
        except Exception:  # noqa: BLE001 - pre-upgrade proxy lacks the RPC
            actual_ap = None
        if actual_ap is not None and actual_ap != requested_ap:
            from ray_tpu._private import rtlog
            rtlog.get("serve").warning(
                "Serve gRPC proxy already running with allow_pickle=%s; "
                "requested allow_pickle=%s ignored — call serve.shutdown() "
                "first to change the pickle codec posture",
                actual_ap, requested_ap)
    return controller


def run(target: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/", blocking: bool = False,
        http_options: Optional[HTTPOptions] = None,
        grpc_options: Optional[gRPCOptions] = None,
        _wait_timeout_s: float = 120.0) -> DeploymentHandle:
    if not isinstance(target, Application):
        raise TypeError("serve.run expects a bound Application "
                        "(use MyDeployment.bind(...))")
    controller = start(http_options=http_options, grpc_options=grpc_options)
    nodes: dict = {}
    target._collect(nodes)
    payload = []
    for dep_name, node in nodes.items():
        args, kwargs = node._marked_args(name)
        payload.append(dict(
            name=dep_name, user_cls=node._deployment.user_class,
            init_args=args, init_kwargs=kwargs,
            config=node._deployment.to_config()))
    ingress = target._deployment.name
    ray_tpu.get(controller.deploy_application.remote(
        name, route_prefix, payload, ingress))
    _wait_ready(controller, [f"{name}#{d}" for d in nodes], _wait_timeout_s)
    handle = DeploymentHandle(f"{name}#{ingress}")
    if blocking:
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
    return handle


def _wait_ready(controller, keys, timeout_s: float) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        status = ray_tpu.get(controller.status.remote())
        pending = [k for k in keys
                   if status.get(k, {}).get("ready", 0) <
                   status.get(k, {}).get("target", 1)]
        bad = [k for k in keys if k not in status]
        if not pending and not bad:
            return
        time.sleep(0.1)
    raise ray_tpu.exceptions.RayServeError(
        f"application not ready within {timeout_s}s: {status}")


def status() -> dict:
    return ray_tpu.get(get_controller().status.remote())


def get_http_address() -> Optional[tuple]:
    return ray_tpu.get(get_controller().get_http_address.remote())


def get_grpc_address() -> Optional[tuple]:
    return ray_tpu.get(get_controller().get_grpc_address.remote())


def get_app_handle(name: str = "default") -> DeploymentHandle:
    key = ray_tpu.get(get_controller().get_app_ingress.remote(name))
    if key is None:
        raise ray_tpu.exceptions.RayServeError(f"no application {name!r}")
    return DeploymentHandle(key)


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(f"{app_name}#{deployment_name}")


def delete(name: str) -> None:
    ray_tpu.get(get_controller().delete_application.remote(name))


def shutdown() -> None:
    """Tear down all applications and the Serve system actors."""
    Router.reset_all()
    try:
        controller = get_controller()
    except Exception:  # noqa: BLE001 - serve never started
        return
    try:
        ray_tpu.get(controller.shutdown_all.remote(), timeout=10)
    except Exception:  # noqa: BLE001
        pass
    for proxy_name in (PROXY_NAME, GRPC_PROXY_NAME):
        try:
            proxy = ray_tpu.get_actor(proxy_name)
            ray_tpu.get(proxy.shutdown.remote(), timeout=5)
            ray_tpu.kill(proxy)
        except Exception:  # noqa: BLE001
            pass
    try:
        ray_tpu.kill(controller)
    except Exception:  # noqa: BLE001
        pass
