"""``@serve.deployment`` decorator, ``Deployment``, and ``Application``.

Reference: ``python/ray/serve/deployment.py`` + the 2.x DAG/bind API
(SURVEY.md §2.5): ``@serve.deployment`` wraps a class or function;
``.bind(*args)`` builds an application graph node whose arguments may be
other bound deployments (model composition); ``serve.run(app)`` deploys the
whole graph with the outermost node as HTTP ingress.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, Optional, Tuple, Union

from ray_tpu.serve._replica import HandleMarker
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig


def _wrap_function(fn: Callable) -> type:
    """Function deployments become a class whose __call__ is the function."""
    if inspect.iscoroutinefunction(fn):
        class FuncDeployment:
            async def __call__(self, *args, **kwargs):
                return await fn(*args, **kwargs)
    else:
        class FuncDeployment:
            def __call__(self, *args, **kwargs):
                return fn(*args, **kwargs)
    FuncDeployment.__name__ = getattr(fn, "__name__", "FuncDeployment")
    return FuncDeployment


class Application:
    """One node of a bound deployment graph."""

    def __init__(self, deployment: "Deployment", args: Tuple, kwargs: Dict):
        self._deployment = deployment
        self._args = args
        self._kwargs = kwargs

    def _collect(self, out: Dict[str, "Application"]) -> None:
        existing = out.get(self._deployment.name)
        if existing is not None and existing is not self:
            raise ValueError(
                f"two different deployments named {self._deployment.name!r} "
                "in one application")
        out[self._deployment.name] = self
        for child in self._children():
            child._collect(out)

    def _children(self):
        def walk(obj):
            if isinstance(obj, Application):
                yield obj
            elif isinstance(obj, (list, tuple)):
                for o in obj:
                    yield from walk(o)
            elif isinstance(obj, dict):
                for o in obj.values():
                    yield from walk(o)
        for a in self._args:
            yield from walk(a)
        for a in self._kwargs.values():
            yield from walk(a)

    def _marked_args(self, app_name: str) -> Tuple[Tuple, Dict]:
        def mark(obj):
            if isinstance(obj, Application):
                return HandleMarker(f"{app_name}#{obj._deployment.name}")
            if isinstance(obj, list):
                return [mark(o) for o in obj]
            if isinstance(obj, tuple):
                return tuple(mark(o) for o in obj)
            if isinstance(obj, dict):
                return {k: mark(v) for k, v in obj.items()}
            return obj
        return (tuple(mark(a) for a in self._args),
                {k: mark(v) for k, v in self._kwargs.items()})


class Deployment:
    def __init__(self, cls_or_fn: Union[type, Callable],
                 name: Optional[str] = None,
                 num_replicas: Union[int, str] = 1,
                 autoscaling_config: Optional[Union[dict, AutoscalingConfig]] = None,
                 max_ongoing_requests: int = 8,
                 ray_actor_options: Optional[dict] = None,
                 graceful_shutdown_wait_s: float = 2.0,
                 health_check_period_s: float = 5.0):
        self._target = cls_or_fn
        self.name = name or getattr(cls_or_fn, "__name__", "deployment")
        if isinstance(autoscaling_config, dict):
            autoscaling_config = AutoscalingConfig(**autoscaling_config)
        if num_replicas == "auto":
            autoscaling_config = autoscaling_config or AutoscalingConfig(
                min_replicas=1, max_replicas=100)
            num_replicas = autoscaling_config.min_replicas or 1
        self._options = dict(
            num_replicas=num_replicas, autoscaling_config=autoscaling_config,
            max_ongoing_requests=max_ongoing_requests,
            ray_actor_options=ray_actor_options,
            graceful_shutdown_wait_s=graceful_shutdown_wait_s,
            health_check_period_s=health_check_period_s)

    def options(self, **overrides: Any) -> "Deployment":
        name = overrides.pop("name", self.name)
        merged = {**self._options}
        for k, v in overrides.items():
            if k not in merged:
                raise ValueError(f"unknown deployment option {k!r}")
            merged[k] = v
        return Deployment(self._target, name=name, **merged)

    def bind(self, *args: Any, **kwargs: Any) -> Application:
        return Application(self, args, kwargs)

    @property
    def user_class(self) -> type:
        if inspect.isclass(self._target):
            return self._target
        return _wrap_function(self._target)

    def to_config(self) -> DeploymentConfig:
        o = self._options
        return DeploymentConfig(
            num_replicas=int(o["num_replicas"]),
            max_ongoing_requests=o["max_ongoing_requests"],
            autoscaling_config=o["autoscaling_config"],
            ray_actor_options=o["ray_actor_options"],
            graceful_shutdown_wait_s=o["graceful_shutdown_wait_s"],
            health_check_period_s=o["health_check_period_s"])

    def __repr__(self):
        return f"Deployment({self.name!r})"


def deployment(_target=None, **options: Any):
    """``@serve.deployment`` / ``@serve.deployment(num_replicas=..., ...)``."""
    if _target is not None:
        return Deployment(_target)

    def wrap(target):
        return Deployment(target, **options)
    return wrap
