"""HTTP proxy actor: socket in, deployment handle out.

Reference: ``python/ray/serve/_private/proxy.py`` (SURVEY.md §3.6) — the
proxy owns the HTTP listener, resolves the route prefix to an app's ingress
deployment, and forwards the request through a ``DeploymentHandle`` (whose
router does power-of-two-choices replica selection).  The reference runs
uvicorn/Starlette; here a stdlib ``ThreadingHTTPServer`` serves the same
role with zero dependencies — each connection thread blocks on the handle
result, giving natural per-connection backpressure.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ray_tpu._private import rtlog
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.serve.handle import DeploymentHandle, get_controller
from ray_tpu.serve.http_util import Request, coerce_response, match_route
from ray_tpu.util import metrics_catalog as mcat

import ray_tpu

logger = rtlog.get("serve.proxy")


def _observe_request(dep_key: str, status: int, t0: float) -> None:
    """Per-deployment data-plane series (reference: Serve's
    ``serve_deployment_request_counter`` / ``_processing_latency_ms``):
    recorded at the proxy so every HTTP outcome — success, timeout,
    no-replica 503, user 500 — lands in the same histogram."""
    if not GLOBAL_CONFIG.metrics_enabled:
        return
    # ``group`` is the cross-layer cohort label (same convention as the
    # train plane's rtpu_train_step_seconds): serve/LLM series stamp the
    # deployment key so one selector ({group="X"}) follows a deployment
    # across proxy, handle, replica, and engine series.
    mcat.get("rtpu_serve_request_latency_seconds").observe(
        time.monotonic() - t0,
        tags={"deployment": dep_key, "group": dep_key})
    mcat.get("rtpu_serve_requests_total").inc(
        tags={"deployment": dep_key, "code": str(status),
              "group": dep_key})
    if status >= 500:
        mcat.get("rtpu_serve_errors_total").inc(
            tags={"deployment": dep_key, "group": dep_key})


class ProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 request_timeout_s: float = 120.0):
        self._controller = get_controller()
        self._routes: Dict[str, str] = {}
        self._routes_ts = 0.0
        self._refresh_lock = threading.Lock()
        self._timeout = request_timeout_s
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # noqa: A003
                logger.debug("http: " + fmt % args)

            def _dispatch(self):
                self.serve_response_started = False
                try:
                    proxy._handle(self)
                except BrokenPipeError:
                    self.close_connection = True
                except Exception as e:  # noqa: BLE001
                    if self.serve_response_started:
                        # Headers already on the wire: a second response
                        # would corrupt HTTP/1.1 framing — drop the conn.
                        self.close_connection = True
                        return
                    try:
                        body = f"internal error: {e}".encode()
                        self.send_response(500)
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    except OSError:
                        self.close_connection = True

            do_GET = do_POST = do_PUT = do_DELETE = do_PATCH = _dispatch

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[:2]
        threading.Thread(target=self._server.serve_forever,
                         name="serve-http", daemon=True).start()
        ray_tpu.get(self._controller.set_http_address.remote(
            self.host, self.port))
        logger.info("proxy listening on %s:%d", self.host, self.port)

    def address(self) -> tuple:
        return (self.host, self.port)

    # ---------------------------------------------------------------- routing
    def _get_routes(self) -> Dict[str, str]:
        # Serve the cached dict; at most ONE thread refreshes a stale cache
        # (non-blocking acquire) so a slow controller never stalls the
        # whole HTTP data plane behind a lock held across an RPC.
        if time.monotonic() - self._routes_ts > 1.0 and \
                self._refresh_lock.acquire(blocking=False):
            try:
                self._routes = ray_tpu.get(
                    self._controller.get_routes.remote(), timeout=10)
                self._routes_ts = time.monotonic()
            except Exception:  # noqa: BLE001 - keep serving the stale map
                pass
            finally:
                self._refresh_lock.release()
        return self._routes

    def _match(self, path: str) -> Optional[tuple]:
        return match_route(path, self._get_routes())

    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        path = req.path.split("?")[0]
        if path == "/-/healthz":
            self._respond(req, 200, b"success", "text/plain")
            return
        if path == "/-/routes":
            body = json.dumps(self._get_routes()).encode()
            self._respond(req, 200, body, "application/json")
            return
        match = self._match(path)
        if match is None:
            self._respond(req, 404, b"no route matched", "text/plain")
            return
        prefix, dep_key = match
        # one HTTP request = one candidate trace root, sampled head-based
        # at trace_sample_rate; when sampled, the span context rides the
        # actor call to the replica (router → handle_request) and on into
        # the engine, so the whole proxy→router→replica→engine path is
        # ONE tree.  Streaming requests keep the span open until the last
        # chunk (the latency metric below still records TTFB).
        from ray_tpu.util import tracing
        with tracing.request_trace(f"serve.{dep_key}", http_path=path,
                                   method=req.command):
            self._handle_routed(req, path, prefix, dep_key)

    def _handle_routed(self, req: BaseHTTPRequestHandler, path: str,
                       prefix: str, dep_key: str) -> None:
        length = int(req.headers.get("Content-Length") or 0)
        body = req.rfile.read(length) if length else b""
        request = Request.from_parts(req.command, req.path,
                                     dict(req.headers), body, prefix)
        handle = DeploymentHandle(dep_key)
        # reference header contract: serve_multiplexed_model_id routes to
        # a replica already holding that model (multiplex.py)
        model_id = req.headers.get("serve_multiplexed_model_id", "")
        start = time.monotonic()
        try:
            # The configured request timeout bounds BOTH phases: waiting
            # for a replica (assign) and waiting for the result.
            resp_f = handle._router().assign(
                "__call__", (request,), {}, timeout_s=self._timeout,
                multiplexed_model_id=model_id)
            remaining = max(0.1, self._timeout - (time.monotonic() - start))
            # raw result: a stream MARKER must reach the chunked-encoding
            # path below, not result()'s generator conversion
            result = ray_tpu.get(resp_f._to_object_ref(),
                                 timeout=remaining)
        except ray_tpu.exceptions.GetTimeoutError:
            _observe_request(dep_key, 408, start)
            self._respond(req, 408, b"request timed out", "text/plain")
            return
        except ray_tpu.exceptions.RayServeError as e:
            _observe_request(dep_key, 503, start)
            self._respond(req, 503, str(e).encode(), "text/plain")
            return
        except Exception as e:  # noqa: BLE001 - user code raised
            _observe_request(dep_key, 500, start)
            self._respond(req, 500, str(e).encode(), "text/plain")
            return
        if isinstance(result, dict) and "__serve_stream__" in result:
            # streaming: the latency series records time-to-first-byte
            _observe_request(dep_key, result.get("status", 200), start)
            self._respond_stream(req, result, resp_f)
            return
        resp = coerce_response(result)
        _observe_request(dep_key, resp.status_code, start)
        self._respond(req, resp.status_code, resp.body, resp.content_type)

    @staticmethod
    def _respond_stream(req, marker: dict, resp_f) -> None:
        """Chunked transfer encoding fed by replica-side generator pulls
        (reference: Serve StreamingResponse over ASGI)."""
        from ray_tpu.serve.http_util import encode_chunk
        req.serve_response_started = True
        req.send_response(marker.get("status", 200))
        req.send_header("Content-Type",
                        marker.get("content_type", "text/plain"))
        req.send_header("Transfer-Encoding", "chunked")
        req.end_headers()
        try:
            for chunk in resp_f._stream_chunks(marker["__serve_stream__"],
                                               marker.get("pull", 16)):
                b = encode_chunk(chunk)
                if not b:
                    continue  # empty chunk would terminate the encoding
                req.wfile.write(f"{len(b):x}\r\n".encode() + b + b"\r\n")
                req.wfile.flush()
            req.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; replica reaper collects the stream

    @staticmethod
    def _respond(req, status: int, body: bytes, content_type: str) -> None:
        req.serve_response_started = True
        req.send_response(status)
        req.send_header("Content-Type", content_type)
        req.send_header("Content-Length", str(len(body)))
        req.end_headers()
        req.wfile.write(body)

    def shutdown(self) -> bool:
        self._server.shutdown()
        return True
