"""``ray_tpu.tune`` — hyperparameter search & trial execution.

Reference: ``python/ray/tune/`` (SURVEY.md §2.5).  ``tune.report`` shares
the Train session transport (Train's ``fit`` and Tune trials are the same
report plumbing — mirroring the reference where Train runs on Tune).
"""

from __future__ import annotations

from ray_tpu.train import get_checkpoint, report  # noqa: F401
from ray_tpu.train._checkpoint import Checkpoint  # noqa: F401
from ray_tpu.tune.schedulers import (  # noqa: F401
    ASHAScheduler, AsyncHyperBandScheduler, FIFOScheduler,
    MedianStoppingRule, PB2, PopulationBasedTraining, TrialScheduler,
)
from ray_tpu.tune.search import (  # noqa: F401
    BasicVariantGenerator, choice, grid_search, loguniform, qrandint,
    quniform, randint, randn, sample_from, uniform,
)
from ray_tpu.tune.trainable import Trainable  # noqa: F401
from ray_tpu.tune.trial import Trial  # noqa: F401
from ray_tpu.tune.tuner import (  # noqa: F401
    ResultGrid, TuneConfig, Tuner, run,
)
