"""TuneController: the experiment event loop.

Reference: ``python/ray/tune/execution/tune_controller.py`` (older:
``trial_runner.py``) — SURVEY.md §2.5: each trial is a remote execution;
the controller polls streamed results, consults the scheduler
(ASHA/PBT/median) for CONTINUE/STOP, enforces stop criteria, launches
pending trials up to the concurrency cap, and persists experiment state.

Trials run as framework TASKS (not long-lived actors): the trial wrapper
installs a train session (world_size=1) so ``tune.report`` shares the
Train report transport; early-stop is the session's cooperative stop flag
— schedulers never hard-kill a trial mid-step.
"""

from __future__ import annotations

import json
import os
import pickle
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.experimental import internal_kv
from ray_tpu.train._internal.session import NAMESPACE
from ray_tpu.tune.schedulers.trial_scheduler import (FIFOScheduler,
                                                     TrialScheduler)
from ray_tpu.tune.trial import Trial

_POLL = 0.02


@ray_tpu.remote
def _trial_task(run_id: str, fn_blob: bytes, config: Dict[str, Any],
                storage_dir: str, restore_path: Optional[str],
                start_iteration: int = 0) -> None:
    """The trial wrapper (runs in a worker process)."""
    import inspect

    import cloudpickle

    from ray_tpu.train._checkpoint import Checkpoint
    from ray_tpu.train._internal import session as sess
    from ray_tpu.train._internal.session import SessionStopped
    from ray_tpu.tune.trainable import Trainable

    restore = (Checkpoint.from_directory(restore_path)
               if restore_path and os.path.isdir(restore_path) else None)
    os.makedirs(storage_dir, exist_ok=True)
    sess.init_session(run_id=run_id, run_name=run_id, rank=0, world_size=1,
                      storage_dir=storage_dir, restore_checkpoint=restore,
                      sync_report=True, start_iteration=start_iteration)
    try:
        obj = cloudpickle.loads(fn_blob)
        if inspect.isclass(obj) and issubclass(obj, Trainable):
            obj(config)._train_loop()
        else:
            result = obj(config)
            if isinstance(result, dict):
                sess.get_session().report(result)
    except SessionStopped:
        pass
    finally:
        sess.shutdown_session()


class TuneController:
    def __init__(self, trainable: Any, trials: List[Trial], *,
                 scheduler: Optional[TrialScheduler] = None,
                 metric: Optional[str] = None, mode: str = "max",
                 stop: Optional[Dict[str, Any]] = None,
                 max_concurrent: int = 4, storage_root: str = "",
                 experiment_name: str = ""):
        import cloudpickle
        self.fn_blob = cloudpickle.dumps(trainable)
        self.trials = trials
        self.scheduler = scheduler or FIFOScheduler()
        self.scheduler.set_metric(metric, mode)
        self.metric = metric
        self.mode = mode
        self.stop = stop or {}
        self.max_concurrent = max_concurrent
        self.storage_root = storage_root
        self.experiment_name = experiment_name
        os.makedirs(self.exp_dir, exist_ok=True)

    @property
    def exp_dir(self) -> str:
        return os.path.join(self.storage_root, self.experiment_name)

    def get_trial(self, trial_id: str) -> Optional[Trial]:
        for t in self.trials:
            if t.id == trial_id:
                return t
        return None

    def request_clone(self, trial: Trial, config: Dict[str, Any],
                      ckpt: str) -> None:
        trial.prepare_clone(config, ckpt)

    # ------------------------------------------------------------ transport
    def _request_stop(self, trial: Trial) -> None:
        if not trial.stop_requested:
            internal_kv._internal_kv_put(f"{trial.run_id}/ctl/stop", b"1",
                                         namespace=NAMESPACE)
            trial.stop_requested = True

    def _drain_reports(self, trial: Trial) -> None:
        """Process every queued report: record → scheduler decision →
        (maybe) set stop flag → ONLY THEN delete the key.  The reporter
        blocks on key deletion (sync_report), so a STOP decision is always
        visible to it at the next line it executes."""
        prefix = f"{trial.run_id}/r/"
        for k in sorted(internal_kv._internal_kv_list(prefix,
                                                      namespace=NAMESPACE),
                        key=lambda k: int(k.rsplit("/", 2)[1])):
            it = int(k.rsplit("/", 2)[1])
            if it in trial.seen_iters:
                continue
            blob = internal_kv._internal_kv_get(k, namespace=NAMESPACE)
            if blob is None:
                continue
            payload = pickle.loads(blob)
            trial.seen_iters.add(it)
            metrics = dict(payload["metrics"])
            metrics["training_iteration"] = it
            metrics["trial_id"] = trial.id
            if payload.get("checkpoint_path"):
                trial.latest_checkpoint_path = payload["checkpoint_path"]
            trial.metrics_history.append(metrics)
            decision = self.scheduler.on_trial_result(self, trial, metrics)
            if decision == TrialScheduler.STOP or \
                    self._hit_stop_criteria(metrics):
                self._request_stop(trial)
            internal_kv._internal_kv_del(k, namespace=NAMESPACE)

    # ---------------------------------------------------------------- loop
    def _launch(self, trial: Trial) -> None:
        storage = os.path.join(self.exp_dir, trial.id)
        # clones continue the iteration numbering (no duplicate
        # training_iteration rows; stop criteria stay run-global)
        start_it = (max(trial.seen_iters | trial.all_seen_iters)
                    if (trial.seen_iters or trial.all_seen_iters) else 0)
        trial.ref = _trial_task.remote(trial.run_id, self.fn_blob,
                                       trial.config, storage,
                                       trial.restore_path, start_it)
        trial.status = "RUNNING"

    def _hit_stop_criteria(self, metrics: Dict[str, Any]) -> bool:
        # reference semantics: stop once attribute >= bound
        return any(metrics.get(k) is not None and metrics[k] >= bound
                   for k, bound in self.stop.items())

    def run(self) -> None:
        while True:
            running = [t for t in self.trials if t.status == "RUNNING"]
            # launch up to the cap (scheduler picks order)
            while len(running) < self.max_concurrent:
                nxt = self.scheduler.choose_trial_to_run(self)
                if nxt is None:
                    break
                self._launch(nxt)
                running.append(nxt)
            if not running:
                break

            for trial in running:
                self._drain_reports(trial)
                done, _ = ray_tpu.wait([trial.ref], num_returns=1,
                                       timeout=0)
                if not done:
                    continue
                self._drain_reports(trial)  # final sweep
                try:
                    ray_tpu.get(trial.ref)
                    trial.status = "TERMINATED"
                except (exc.RayTaskError, exc.RayActorError,
                        exc.ObjectLostError) as e:
                    trial.status = "ERROR"
                    trial.error = e
                self.scheduler.on_trial_complete(self, trial,
                                                 trial.last_result)
                # reclaim this launch's control/report keys
                internal_kv._internal_kv_del(f"{trial.run_id}/ctl/stop",
                                             namespace=NAMESPACE)
                if trial.pending_clone is not None:
                    trial.relaunch_as_clone()
                self._save_experiment_state()
            time.sleep(_POLL)
        self._save_experiment_state()

    # ------------------------------------------------------------- persist
    def _save_experiment_state(self) -> None:
        state = {
            "experiment_name": self.experiment_name,
            "metric": self.metric,
            "mode": self.mode,
            "trials": [{
                "id": t.id, "config": _jsonable(t.config),
                "status": t.status,
                "metrics_history": _jsonable(t.metrics_history),
                "latest_checkpoint_path": t.latest_checkpoint_path,
            } for t in self.trials],
        }
        with open(os.path.join(self.exp_dir, "experiment_state.json"),
                  "w") as f:
            json.dump(state, f, indent=1)


def _jsonable(x: Any) -> Any:
    try:
        json.dumps(x)
        return x
    except (TypeError, ValueError):
        if isinstance(x, dict):
            return {str(k): _jsonable(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return [_jsonable(v) for v in x]
        return repr(x)
