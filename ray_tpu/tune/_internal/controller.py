"""TuneController: the experiment event loop.

Reference: ``python/ray/tune/execution/tune_controller.py`` (older:
``trial_runner.py``) — SURVEY.md §2.5: each trial is a remote execution;
the controller polls streamed results, consults the scheduler
(ASHA/PBT/median) for CONTINUE/STOP, enforces stop criteria, launches
pending trials up to the concurrency cap, and persists experiment state.

Trials run as framework TASKS (not long-lived actors): the trial wrapper
installs a train session (world_size=1) so ``tune.report`` shares the
Train report transport; early-stop is the session's cooperative stop flag
— schedulers never hard-kill a trial mid-step.
"""

from __future__ import annotations

import base64
import json
import os
import pickle
import shutil
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.experimental import internal_kv
from ray_tpu.train._internal.session import NAMESPACE
from ray_tpu.tune.schedulers.trial_scheduler import (FIFOScheduler,
                                                     TrialScheduler)
from ray_tpu.tune.trial import Trial

_POLL = 0.02


@ray_tpu.remote
def _trial_task(run_id: str, fn_blob: bytes, config: Dict[str, Any],
                storage_dir: str, restore_path: Optional[str],
                start_iteration: int = 0, ckpt_freq: int = 0) -> None:
    """The trial wrapper (runs in a worker process)."""
    import inspect

    import cloudpickle

    from ray_tpu.train._checkpoint import Checkpoint
    from ray_tpu.train._internal import session as sess
    from ray_tpu.train._internal.session import SessionStopped
    from ray_tpu.tune.trainable import Trainable

    restore = (Checkpoint.from_directory(restore_path)
               if restore_path and os.path.isdir(restore_path) else None)
    os.makedirs(storage_dir, exist_ok=True)
    sess.init_session(run_id=run_id, run_name=run_id, rank=0, world_size=1,
                      storage_dir=storage_dir, restore_checkpoint=restore,
                      sync_report=True, start_iteration=start_iteration)
    try:
        obj = cloudpickle.loads(fn_blob)
        if inspect.isclass(obj) and issubclass(obj, Trainable):
            obj(config)._train_loop(ckpt_freq)
        else:
            result = obj(config)
            if isinstance(result, dict):
                sess.get_session().report(result)
    except SessionStopped:
        pass
    finally:
        sess.shutdown_session()


class TuneController:
    def __init__(self, trainable: Any, trials: List[Trial], *,
                 scheduler: Optional[TrialScheduler] = None,
                 searcher: Any = None,
                 metric: Optional[str] = None, mode: str = "max",
                 stop: Optional[Dict[str, Any]] = None,
                 max_concurrent: int = 4, storage_root: str = "",
                 experiment_name: str = "", checkpoint_config: Any = None):
        import cloudpickle
        self.fn_blob = cloudpickle.dumps(trainable)
        self.trials = trials
        self.searcher = searcher
        self.scheduler = scheduler or FIFOScheduler()
        self.scheduler.set_metric(metric, mode)
        self.metric = metric
        self.mode = mode
        self.stop = stop or {}
        self.max_concurrent = max_concurrent
        self.storage_root = storage_root
        self.experiment_name = experiment_name
        self.checkpoint_config = checkpoint_config
        self._last_state_save = 0.0
        os.makedirs(self.exp_dir, exist_ok=True)
        # Persist immediately: an experiment interrupted before any trial
        # completes must still be restorable.
        self._save_experiment_state()

    @property
    def exp_dir(self) -> str:
        return os.path.join(self.storage_root, self.experiment_name)

    def get_trial(self, trial_id: str) -> Optional[Trial]:
        for t in self.trials:
            if t.id == trial_id:
                return t
        return None

    def request_clone(self, trial: Trial, config: Dict[str, Any],
                      ckpt: str) -> None:
        trial.prepare_clone(config, ckpt)

    # ------------------------------------------------------------ transport
    def _request_stop(self, trial: Trial) -> None:
        if not trial.stop_requested:
            internal_kv._internal_kv_put(f"{trial.run_id}/ctl/stop", b"1",
                                         namespace=NAMESPACE)
            trial.stop_requested = True

    def _drain_reports(self, trial: Trial) -> None:
        """Process every queued report: record → scheduler decision →
        (maybe) set stop flag → ONLY THEN delete the key.  The reporter
        blocks on key deletion (sync_report), so a STOP decision is always
        visible to it at the next line it executes."""
        prefix = f"{trial.run_id}/r/"
        for k in sorted(internal_kv._internal_kv_list(prefix,
                                                      namespace=NAMESPACE),
                        key=lambda k: int(k.rsplit("/", 2)[1])):
            it = int(k.rsplit("/", 2)[1])
            if it in trial.seen_iters:
                continue
            blob = internal_kv._internal_kv_get(k, namespace=NAMESPACE)
            if blob is None:
                continue
            payload = pickle.loads(blob)
            trial.seen_iters.add(it)
            metrics = dict(payload["metrics"])
            metrics["training_iteration"] = it
            metrics["trial_id"] = trial.id
            if payload.get("checkpoint_path"):
                trial.latest_checkpoint_path = payload["checkpoint_path"]
                self._apply_checkpoint_retention(trial)
            trial.metrics_history.append(metrics)
            if self.searcher is not None:
                self.searcher.on_trial_result(trial.id, metrics)
            decision = self.scheduler.on_trial_result(self, trial, metrics)
            if decision == TrialScheduler.STOP or \
                    self._hit_stop_criteria(metrics):
                self._request_stop(trial)
            internal_kv._internal_kv_del(k, namespace=NAMESPACE)

    # ---------------------------------------------------------------- loop
    def _launch(self, trial: Trial) -> None:
        if trial.config is None and self.searcher is not None:
            trial.config = self.searcher.suggest(trial.id)
        storage = os.path.join(self.exp_dir, trial.id)
        # Clones/restores continue the iteration numbering (stop criteria
        # stay run-global).  When resuming from a checkpoint older than the
        # last report (checkpoint_frequency > 1), restart numbering at the
        # checkpoint's iteration so the gap is re-trained rather than
        # silently skipped.
        start_it = (max(trial.seen_iters | trial.all_seen_iters)
                    if (trial.seen_iters or trial.all_seen_iters) else 0)
        ckpt_it = _checkpoint_iteration(trial.restore_path)
        if ckpt_it is not None and ckpt_it < start_it:
            start_it = ckpt_it
            trial.metrics_history = [
                m for m in trial.metrics_history
                if m.get("training_iteration", 0) <= ckpt_it]
        ckpt_freq = getattr(self.checkpoint_config, "checkpoint_frequency",
                            0) or 0
        trial.ref = _trial_task.remote(trial.run_id, self.fn_blob,
                                       trial.config, storage,
                                       trial.restore_path, start_it,
                                       ckpt_freq)
        trial.status = "RUNNING"

    def _apply_checkpoint_retention(self, trial: Trial) -> None:
        """Keep only the newest ``num_to_keep`` checkpoint dirs of a trial
        (reference: ``CheckpointConfig.num_to_keep``)."""
        keep = getattr(self.checkpoint_config, "num_to_keep", None)
        if not keep or not trial.latest_checkpoint_path:
            return
        # Never delete a dir some trial still needs: its latest, a pending
        # PBT clone's donor checkpoint, or a restore point.
        pinned = set()
        for t in self.trials:
            pinned.add(t.latest_checkpoint_path)
            pinned.add(t.restore_path)
            if t.pending_clone is not None:
                pinned.add(t.pending_clone.get("ckpt"))
        trial_dir = os.path.dirname(trial.latest_checkpoint_path)
        try:
            ckpts = sorted(
                d for d in os.listdir(trial_dir)
                if d.startswith("checkpoint_")
                and os.path.isdir(os.path.join(trial_dir, d)))
        except OSError:
            return
        for d in ckpts[:-keep]:
            path = os.path.join(trial_dir, d)
            if path not in pinned:
                shutil.rmtree(path, ignore_errors=True)

    def _hit_stop_criteria(self, metrics: Dict[str, Any]) -> bool:
        # reference semantics: stop once attribute >= bound
        return any(metrics.get(k) is not None and metrics[k] >= bound
                   for k, bound in self.stop.items())

    def run(self) -> None:
        while True:
            running = [t for t in self.trials if t.status == "RUNNING"]
            # launch up to the cap (scheduler picks order)
            while len(running) < self.max_concurrent:
                nxt = self.scheduler.choose_trial_to_run(self)
                if nxt is None:
                    break
                self._launch(nxt)
                running.append(nxt)
            if not running:
                break

            for trial in running:
                self._drain_reports(trial)
                done, _ = ray_tpu.wait([trial.ref], num_returns=1,
                                       timeout=0)
                if not done:
                    continue
                self._drain_reports(trial)  # final sweep
                try:
                    ray_tpu.get(trial.ref)
                    trial.status = "TERMINATED"
                except (exc.RayTaskError, exc.RayActorError,
                        exc.ObjectLostError) as e:
                    trial.status = "ERROR"
                    trial.error = e
                self.scheduler.on_trial_complete(self, trial,
                                                 trial.last_result)
                if self.searcher is not None:
                    self.searcher.on_trial_complete(trial.id,
                                                    trial.last_result)
                # reclaim this launch's control/report keys
                internal_kv._internal_kv_del(f"{trial.run_id}/ctl/stop",
                                             namespace=NAMESPACE)
                if trial.pending_clone is not None:
                    trial.relaunch_as_clone()
                self._save_experiment_state()
            if time.monotonic() - self._last_state_save > 2.0:
                self._save_experiment_state()
            time.sleep(_POLL)
        self._save_experiment_state()

    # ------------------------------------------------------------- persist
    def _save_experiment_state(self) -> None:
        import cloudpickle
        self._last_state_save = time.monotonic()

        def b64(obj):
            try:
                return base64.b64encode(cloudpickle.dumps(obj)).decode()
            except Exception:  # noqa: BLE001 - unpicklable user object
                return None

        state_path = os.path.join(self.exp_dir, "experiment_state.json")
        # Merge with any prior state file: a restored run's controller only
        # holds the re-run trials, but previously TERMINATED trials must
        # stay discoverable.
        prior_trials = {}
        if os.path.exists(state_path):
            try:
                with open(state_path) as f:
                    prior_trials = {t["id"]: t
                                    for t in json.load(f).get("trials", [])}
            except (OSError, ValueError):
                prior_trials = {}
        for t in self.trials:
            prior_trials[t.id] = {
                "id": t.id, "config": _jsonable(t.config),
                "status": t.status,
                "metrics_history": _jsonable(t.metrics_history),
                "latest_checkpoint_path": t.latest_checkpoint_path,
                "rungs_hit": sorted(t.rungs_hit),
            }
        state = {
            "experiment_name": self.experiment_name,
            "metric": self.metric,
            "mode": self.mode,
            "stop": _jsonable(self.stop),
            "scheduler_b64": b64(self.scheduler),
            "checkpoint_config_b64": b64(self.checkpoint_config),
            "trials": list(prior_trials.values()),
        }
        with open(state_path, "w") as f:
            json.dump(state, f, indent=1)


def _checkpoint_iteration(path: Optional[str]) -> Optional[int]:
    """Parse the iteration out of a ``checkpoint_a{N}_{IIIIII}`` dir name."""
    if not path:
        return None
    try:
        return int(os.path.basename(path.rstrip("/")).rsplit("_", 1)[1])
    except (IndexError, ValueError):
        return None


def _jsonable(x: Any) -> Any:
    try:
        json.dumps(x)
        return x
    except (TypeError, ValueError):
        if isinstance(x, dict):
            return {str(k): _jsonable(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return [_jsonable(v) for v in x]
        return repr(x)
