"""Tune internals."""
