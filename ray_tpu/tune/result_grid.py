"""ResultGrid: the output of Tuner.fit().

Reference: ``python/ray/tune/result_grid.py``.
"""

from __future__ import annotations

from typing import List, Optional

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train.result import Result


class ResultGrid:
    def __init__(self, results: List[Result],
                 default_metric: Optional[str] = None,
                 default_mode: str = "max"):
        self._results = results
        self._default_metric = default_metric
        self._default_mode = default_mode

    def __len__(self) -> int:
        return len(self._results)

    def __getitem__(self, i: int) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[BaseException]:
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._default_metric
        mode = mode or self._default_mode
        if metric is None:
            raise ValueError("no metric given and none configured on the "
                             "Tuner (TuneConfig(metric=...))")
        scored = [r for r in self._results
                  if r.metrics and metric in r.metrics]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        best_of = max if mode == "max" else min
        return best_of(scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        import pandas as pd
        return pd.DataFrame([r.metrics or {} for r in self._results])
