"""Tuner: the experiment entry point.

Reference: ``python/ray/tune/tuner.py`` + ``tune_config.py`` (SURVEY.md
§2.5): expand the param space into trials, run them through the
controller, return a ResultGrid; ``Tuner.restore`` reloads a finished or
interrupted experiment from its state file.
"""

from __future__ import annotations

import base64
import json
import os
import uuid
from dataclasses import dataclass
from typing import Any, Dict, Optional

from ray_tpu.air.config import RunConfig
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train.result import Result
from ray_tpu.tune._internal.controller import TuneController
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator
from ray_tpu.tune.trial import Trial


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Optional[TrialScheduler] = None
    search_alg: Optional[BasicVariantGenerator] = None
    seed: Optional[int] = None


class Tuner:
    def __init__(self, trainable: Any, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        # BaseTrainer instances become function trainables (reference:
        # Tuner(trainer) — Train rides on Tune)
        from ray_tpu.train.base_trainer import BaseTrainer
        if isinstance(trainable, BaseTrainer):
            trainable = trainable.as_trainable()
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        exp_name = self.run_config.name or f"tune_{uuid.uuid4().hex[:8]}"
        from ray_tpu.tune.search.searcher import Searcher
        searcher = None
        if isinstance(tc.search_alg, Searcher):
            # adaptive search: configs proposed lazily at launch time so
            # later trials exploit earlier results
            searcher = tc.search_alg
            searcher.set_search_properties(tc.metric, tc.mode,
                                           self.param_space)
            trials = [Trial(f"{exp_name}_{i:05d}", None)
                      for i in range(tc.num_samples)]
        else:
            gen = tc.search_alg or BasicVariantGenerator(seed=tc.seed)
            configs = gen.generate(self.param_space, tc.num_samples)
            trials = [Trial(f"{exp_name}_{i:05d}", cfg)
                      for i, cfg in enumerate(configs)]
        controller = TuneController(
            self.trainable, trials, scheduler=tc.scheduler,
            searcher=searcher,
            metric=tc.metric, mode=tc.mode,
            stop=self.run_config.stop or {},
            max_concurrent=tc.max_concurrent_trials,
            storage_root=self.run_config.resolved_storage_path(),
            experiment_name=exp_name,
            checkpoint_config=self.run_config.checkpoint_config)
        controller.run()
        return ResultGrid([_trial_to_result(t) for t in trials],
                          default_metric=tc.metric, default_mode=tc.mode)

    @classmethod
    def restore(cls, path: str, trainable: Any = None) -> "_RestoredTuner":
        with open(os.path.join(path, "experiment_state.json")) as f:
            state = json.load(f)
        return _RestoredTuner(state, trainable, os.path.dirname(path.rstrip("/")))


class _RestoredTuner:
    """Restored experiment: ``get_results()`` for what finished;
    ``fit()`` (requires the trainable) re-runs unfinished trials from
    their latest checkpoints and merges the results."""

    def __init__(self, state: Dict[str, Any], trainable: Any,
                 storage_root: str):
        self._state = state
        self._trainable = trainable
        self._storage_root = storage_root

    def get_results(self) -> ResultGrid:
        results = []
        for t in self._state["trials"]:
            results.append(self._to_result(t))
        return ResultGrid(results, default_metric=self._state.get("metric"),
                          default_mode=self._state.get("mode") or "max")

    def _to_result(self, t: Dict[str, Any]) -> Result:
        hist = t.get("metrics_history") or []
        ckpt = (Checkpoint.from_directory(t["latest_checkpoint_path"])
                if t.get("latest_checkpoint_path") and
                os.path.isdir(t["latest_checkpoint_path"]) else None)
        return Result(
            metrics=hist[-1] if hist else None, checkpoint=ckpt,
            metrics_history=hist,
            error=None if t["status"] != "ERROR" else
            RuntimeError("trial errored (restored)"))

    def fit(self) -> ResultGrid:
        if self._trainable is None:
            raise ValueError(
                "Tuner.restore(path, trainable=...) is required to re-run "
                "unfinished trials")
        from ray_tpu.tune._internal.controller import TuneController
        from ray_tpu.tune.trial import Trial
        done, rerun = [], []
        for t in self._state["trials"]:
            if t["status"] == "TERMINATED":
                done.append(self._to_result(t))
            else:
                tr = Trial(t["id"], t.get("config") or {})
                if t.get("latest_checkpoint_path") and \
                        os.path.isdir(t["latest_checkpoint_path"]):
                    tr.restore_path = t["latest_checkpoint_path"]
                # Resume iteration numbering where the interrupted run left
                # off so run-global stop criteria keep their meaning.
                for m in t.get("metrics_history") or []:
                    it = m.get("training_iteration")
                    if it is not None:
                        tr.all_seen_iters.add(int(it))
                        tr.metrics_history.append(m)
                # Rungs already passed (ASHA/median bookkeeping) must not be
                # re-recorded by the resumed run.
                tr.rungs_hit = set(t.get("rungs_hit") or [])
                rerun.append(tr)
        if rerun:
            def unb64(key):
                blob = self._state.get(key)
                if not blob:
                    return None
                import cloudpickle
                try:
                    return cloudpickle.loads(base64.b64decode(blob))
                except Exception:  # noqa: BLE001 - version drift
                    return None
            controller = TuneController(
                self._trainable, rerun,
                scheduler=unb64("scheduler_b64"),
                metric=self._state.get("metric"),
                mode=self._state.get("mode") or "max",
                stop=self._state.get("stop") or {},
                storage_root=self._storage_root,
                experiment_name=self._state["experiment_name"],
                checkpoint_config=unb64("checkpoint_config_b64"))
            controller.run()
            done.extend(_trial_to_result(t) for t in rerun)
        return ResultGrid(done, default_metric=self._state.get("metric"),
                          default_mode=self._state.get("mode") or "max")


def _trial_to_result(t: Trial) -> Result:
    ckpt = None
    if t.latest_checkpoint_path and os.path.isdir(t.latest_checkpoint_path):
        ckpt = Checkpoint.from_directory(t.latest_checkpoint_path)
    metrics = dict(t.last_result or {})
    if t.config is not None:
        metrics["config"] = t.config
    return Result(metrics=metrics or None, checkpoint=ckpt,
                  error=t.error, metrics_history=t.metrics_history)


def run(trainable: Any, *, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, metric: Optional[str] = None,
        mode: str = "max", scheduler: Optional[TrialScheduler] = None,
        stop: Optional[Dict[str, Any]] = None,
        storage_path: Optional[str] = None, name: Optional[str] = None,
        max_concurrent_trials: int = 4, **_compat: Any) -> ResultGrid:
    """``tune.run`` — the classic API (reference:
    ``python/ray/tune/tune.py``)."""
    rc = RunConfig(name=name, storage_path=storage_path, stop=stop)
    tuner = Tuner(trainable, param_space=config,
                  tune_config=TuneConfig(
                      metric=metric, mode=mode, num_samples=num_samples,
                      scheduler=scheduler,
                      max_concurrent_trials=max_concurrent_trials),
                  run_config=rc)
    return tuner.fit()
