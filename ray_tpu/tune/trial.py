"""Trial state.

Reference: ``python/ray/tune/experiment/trial.py`` — one hyperparameter
configuration's lifecycle: PENDING → RUNNING → TERMINATED | ERROR.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional, Set


class Trial:
    def __init__(self, trial_id: str, config: Dict[str, Any]):
        self.id = trial_id
        self.config = config
        self.status = "PENDING"
        self.ref = None                     # running task ref
        # KV report channel — unique per process+launch so a re-run of a
        # same-named experiment can never see a stale stop flag
        self.run_id = f"{trial_id}_{uuid.uuid4().hex[:6]}"
        self.metrics_history: List[Dict[str, Any]] = []
        self.latest_checkpoint_path: Optional[str] = None
        self.restore_path: Optional[str] = None
        self.error: Optional[BaseException] = None
        self.rungs_hit: Set[int] = set()    # ASHA bookkeeping
        self.clone_count = 0
        self.pending_clone: Optional[Dict[str, Any]] = None
        self.seen_iters: Set[int] = set()
        self.all_seen_iters: Set[int] = set()  # across clone relaunches
        self.stop_requested = False

    @property
    def last_result(self) -> Optional[Dict[str, Any]]:
        return self.metrics_history[-1] if self.metrics_history else None

    def prepare_clone(self, config: Dict[str, Any], ckpt: str) -> None:
        self.pending_clone = {"config": config, "ckpt": ckpt}

    def relaunch_as_clone(self) -> None:
        spec = self.pending_clone
        self.pending_clone = None
        self.clone_count += 1
        self.config = spec["config"]
        self.restore_path = spec["ckpt"]
        self.run_id = f"{self.id}_c{self.clone_count}_{uuid.uuid4().hex[:6]}"
        self.status = "PENDING"
        self.ref = None
        self.all_seen_iters |= self.seen_iters
        self.seen_iters = set()
        self.stop_requested = False

    def __repr__(self) -> str:
        return f"Trial({self.id}, {self.status})"
