"""Trainable: the unit Tune runs.

Reference: ``python/ray/tune/trainable/trainable.py`` — function API
(``def f(config): tune.report(...)``) and class API (``setup``/``step``/
``save_checkpoint``/``load_checkpoint``).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

from ray_tpu.train._checkpoint import Checkpoint


class Trainable:
    """Class-API trainable.  The trial wrapper drives: setup(config), then
    step() per iteration (reporting its return dict), checkpointing via
    save_checkpoint/load_checkpoint around PBT clones and restores."""

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        self.config = dict(config or {})
        self.iteration = 0
        self.setup(self.config)

    # -- user hooks
    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[str]:
        return None

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        pass

    def cleanup(self) -> None:
        pass

    # -- driver used by the trial wrapper
    def _train_loop(self, ckpt_freq: int = 0) -> None:
        """``ckpt_freq``: save every N iterations
        (``CheckpointConfig.checkpoint_frequency``); 0/1 → every iteration
        (kept as the default so schedulers can always clone/restore)."""
        import shutil
        import tempfile

        from ray_tpu.train._internal.session import get_session
        sess = get_session()
        restore = sess.get_checkpoint()
        if restore is not None:
            with restore.as_directory() as d:
                self.load_checkpoint(d)
            self.iteration = max(self.iteration, sess.iteration)
        ckpt_freq = max(int(ckpt_freq), 1)
        try:
            while True:
                self.iteration += 1
                metrics = dict(self.step())
                if self.iteration % ckpt_freq != 0:
                    sess.report(metrics)
                    continue
                tmp = tempfile.mkdtemp(prefix="rtpu_trainable_ckpt_")
                try:
                    self.save_checkpoint(tmp)
                    ckpt = (Checkpoint.from_directory(tmp)
                            if os.listdir(tmp) else None)
                    sess.report(metrics, checkpoint=ckpt)
                finally:
                    shutil.rmtree(tmp, ignore_errors=True)
        finally:
            self.cleanup()
