"""Third-party searcher adapters (Optuna / HyperOpt).

Reference: ``python/ray/tune/search/optuna/`` and
``python/ray/tune/search/hyperopt/`` — thin adapters that translate the
Searcher protocol (suggest / on_trial_complete) onto an external
optimization library's ask/tell interface.

Neither library ships in this image; the adapters are import-gated with
an actionable error naming the native equivalents (TPESearcher — the
same algorithm family hyperopt implements — and BOHBSearcher).  When the
library IS installed the adapter is a real ask/tell bridge, not a stub;
see PARITY.md for the validation caveat.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer
from ray_tpu.tune.search.searcher import Searcher
from ray_tpu.tune.search.tpe import _set, _walk


class OptunaSearch(Searcher):
    """Adapter onto ``optuna``'s ask/tell API (reference: OptunaSearch)."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None, *,
                 sampler: Any = None, seed: Optional[int] = None):
        try:
            import optuna  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires the 'optuna' package, which is not "
                "installed. The native TPESearcher "
                "(ray_tpu.tune.search.tpe) implements the same TPE "
                "algorithm with no dependencies; BOHBSearcher adds "
                "multi-fidelity.") from e
        super().__init__(metric, mode)
        import optuna
        self._optuna = optuna
        optuna.logging.set_verbosity(optuna.logging.WARNING)
        self._sampler = sampler or optuna.samplers.TPESampler(seed=seed)
        self._rng = np.random.default_rng(seed)
        self._study = None
        self._trials: Dict[str, Any] = {}

    def _ensure_study(self):
        if self._study is None:
            self._study = self._optuna.create_study(
                direction="maximize" if self.mode == "max" else "minimize",
                sampler=self._sampler)
        return self._study

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        assert self._space is not None, "set_search_properties not called"
        study = self._ensure_study()
        ot = study.ask()
        cfg: Dict[str, Any] = {}
        for path, leaf in _walk(self._space):
            name = "/".join(map(str, path))
            if isinstance(leaf, Float):
                q = getattr(leaf, "q", None)
                log = bool(getattr(leaf, "log", False))
                # step inside the study so the told and executed values
                # match (optuna disallows step together with log)
                v = ot.suggest_float(name, leaf.lower, leaf.upper,
                                     log=log,
                                     step=None if log else q)
            elif isinstance(leaf, Integer):
                v = ot.suggest_int(name, int(leaf.lower),
                                   int(leaf.upper) - 1)
            elif isinstance(leaf, Categorical):
                cats = list(leaf.categories)
                try:
                    # unordered-aware modeling; optuna requires
                    # primitive choices
                    v = ot.suggest_categorical(name, cats)
                except Exception:  # noqa: BLE001 - non-primitive values
                    idx = ot.suggest_categorical(
                        f"{name}#idx", list(range(len(cats))))
                    v = cats[idx]
            elif isinstance(leaf, Domain):
                v = leaf.sample(self._rng)
            else:
                v = leaf
            _set(cfg, path, v)
        self._trials[trial_id] = ot
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        ot = self._trials.pop(trial_id, None)
        if ot is None or self._study is None:
            return
        if result and self.metric in result:
            self._study.tell(ot, float(result[self.metric]))
        else:
            self._study.tell(
                ot, state=self._optuna.trial.TrialState.FAIL)


class HyperOptSearch(Searcher):
    """Adapter onto ``hyperopt``'s suggest machinery (reference:
    HyperOptSearch)."""

    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None, *,
                 n_initial_points: int = 10, seed: Optional[int] = None):
        try:
            import hyperopt  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "HyperOptSearch requires the 'hyperopt' package, which is "
                "not installed. The native TPESearcher "
                "(ray_tpu.tune.search.tpe) implements the same TPE "
                "algorithm with no dependencies.") from e
        super().__init__(metric, mode)
        import hyperopt
        self._hp = hyperopt
        self._n_initial = n_initial_points
        self._seed = seed
        self._rng = np.random.default_rng(seed)
        self._domain = None
        self._hp_trials = hyperopt.Trials()
        self._ids: Dict[str, int] = {}
        self._next = 0

    def _hp_space(self) -> Tuple[Dict[str, Any], Dict[str, Tuple]]:
        hp = self._hp.hp
        space, paths = {}, {}
        for path, leaf in _walk(self._space):
            name = "/".join(map(str, path))
            paths[name] = (path, leaf)
            if isinstance(leaf, Float):
                if getattr(leaf, "log", False):
                    import math
                    space[name] = hp.loguniform(
                        name, math.log(leaf.lower), math.log(leaf.upper))
                else:
                    space[name] = hp.uniform(name, leaf.lower, leaf.upper)
            elif isinstance(leaf, Integer):
                space[name] = hp.randint(
                    name, int(leaf.lower), int(leaf.upper))
            elif isinstance(leaf, Categorical):
                space[name] = hp.choice(name, list(leaf.categories))
            elif not isinstance(leaf, Domain):
                space[name] = leaf
        return space, paths

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        assert self._space is not None, "set_search_properties not called"
        hp = self._hp
        if self._domain is None:
            space, self._paths = self._hp_space()
            self._domain = hp.base.Domain(lambda c: 0.0, space)
        tid = self._next
        self._next += 1
        self._ids[trial_id] = tid
        algo = hp.tpe.suggest if self._next > self._n_initial \
            else hp.rand.suggest
        docs = algo([tid], self._domain, self._hp_trials,
                    (self._seed or 0) + tid)
        self._hp_trials.insert_trial_docs(docs)
        self._hp_trials.refresh()
        vals = {k: v[0] for k, v in docs[0]["misc"]["vals"].items() if v}
        cfg: Dict[str, Any] = {}
        for name, (path, leaf) in self._paths.items():
            if name in vals:
                v = vals[name]
                if isinstance(leaf, Categorical):
                    v = leaf.categories[int(v)]
                elif isinstance(leaf, Integer):
                    v = int(v)
                _set(cfg, path, v)
            else:
                _set(cfg, path, leaf if not isinstance(leaf, Domain)
                     else leaf.sample(self._rng))
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        tid = self._ids.pop(trial_id, None)
        if tid is None:
            return
        hp = self._hp
        for doc in self._hp_trials.trials:
            if doc["tid"] != tid:
                continue
            if result and self.metric in result:
                sign = -1.0 if self.mode == "max" else 1.0
                doc["result"] = {"loss": sign * float(result[self.metric]),
                                 "status": hp.STATUS_OK}
            else:
                doc["result"] = {"status": hp.STATUS_FAIL}
            doc["state"] = hp.JOB_STATE_DONE
        self._hp_trials.refresh()
