"""Adaptive searcher interface (sequential model-based optimization).

Reference: ``python/ray/tune/search/searcher.py`` — unlike the upfront
``BasicVariantGenerator``, a Searcher proposes each trial's config lazily
(``suggest``) and learns from completed trials (``on_trial_complete``), so
later trials exploit earlier results.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class Searcher:
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None):
        self.metric = metric
        self._mode_explicit = mode is not None
        self.mode = mode or "max"
        self._space: Optional[Dict[str, Any]] = None

    def set_search_properties(self, metric: Optional[str], mode: Optional[str],
                              space: Dict[str, Any]) -> None:
        if self.metric is None:
            self.metric = metric
        # an explicitly-constructed mode wins over TuneConfig's default
        # ("max") — overwriting would silently invert the optimization
        if mode and not self._mode_explicit:
            self.mode = mode
        self._space = space

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str,
                        result: Dict[str, Any]) -> None:
        """Intermediate (per-report) observation — multi-fidelity
        searchers (BOHB) learn from rung results, not just finals."""

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        pass
