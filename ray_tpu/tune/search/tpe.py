"""TPE: Tree-structured Parzen Estimator searcher (native, no deps).

Reference analog: ``python/ray/tune/search/hyperopt/`` — Ray wraps
hyperopt's TPE; this is a from-scratch implementation of the same
published algorithm (Bergstra et al., NeurIPS 2011): split observed
trials into good (top ``gamma`` quantile) and bad; model each group with
a Parzen (kernel-density) estimator per dimension; propose the candidate
maximizing ``l(x)/g(x)`` (likelihood under good ÷ likelihood under bad).

Handles Float (linear/log/quantized), Integer, and Categorical domains;
``grid_search`` leaves are treated as Categorical; other leaves fall back
to random sampling.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.search.sample import Categorical, Domain, Float, Integer
from ray_tpu.tune.search.searcher import Searcher


def _walk(space: Any, path: Tuple = ()):  # (path, leaf) pairs
    if isinstance(space, dict):
        if set(space.keys()) == {"grid_search"}:
            yield (path, Categorical(space["grid_search"]))
            return
        for k, v in space.items():
            yield from _walk(v, path + (k,))
    else:
        yield (path, space)


def _set(d: Dict, path: Tuple, value: Any) -> None:
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def _get(d: Dict, path: Tuple) -> Any:
    for k in path:
        d = d[k]
    return d


class TPESearcher(Searcher):
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None, *,
                 n_initial_points: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.n_initial = n_initial_points
        self.gamma = gamma
        self.n_candidates = n_candidates
        self._rng = np.random.default_rng(seed)
        self._pending: Dict[str, Dict] = {}
        self._observations: List[Tuple[Dict, float]] = []

    # -- proposal ------------------------------------------------------------
    def suggest(self, trial_id: str) -> Dict[str, Any]:
        assert self._space is not None, "set_search_properties not called"
        cfg: Dict[str, Any] = {}
        use_tpe = len(self._observations) >= self.n_initial
        for path, leaf in _walk(self._space):
            if isinstance(leaf, Domain):
                if use_tpe and isinstance(leaf, (Float, Integer, Categorical)):
                    value = self._tpe_sample(path, leaf)
                else:
                    value = leaf.sample(self._rng)
            else:
                value = leaf  # constant
            _set(cfg, path, value)
        self._pending[trial_id] = cfg
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        cfg = self._pending.pop(trial_id, None)
        if cfg is None or not result or self.metric not in result:
            return
        sign = 1.0 if self.mode == "max" else -1.0
        self._observations.append((cfg, sign * float(result[self.metric])))

    # -- TPE core ------------------------------------------------------------
    def _split(self) -> Tuple[List[Dict], List[Dict]]:
        obs = sorted(self._observations, key=lambda o: -o[1])
        n_good = max(1, int(math.ceil(self.gamma * len(obs))))
        return ([c for c, _ in obs[:n_good]], [c for c, _ in obs[n_good:]])

    def _tpe_sample(self, path: Tuple, leaf: Domain) -> Any:
        good, bad = self._split()
        gv = [_get(c, path) for c in good]
        bv = [_get(c, path) for c in bad]
        if isinstance(leaf, Categorical):
            return self._tpe_categorical(leaf, gv, bv)
        return self._tpe_numeric(leaf, gv, bv)

    def _tpe_categorical(self, leaf: Categorical, gv, bv) -> Any:
        cats = leaf.categories
        prior = 1.0
        g_counts = np.array([prior + sum(1 for v in gv if v == c)
                             for c in cats], float)
        b_counts = np.array([prior + sum(1 for v in bv if v == c)
                             for c in cats], float)
        score = (g_counts / g_counts.sum()) / (b_counts / b_counts.sum())
        # sample proportionally to l/g (softens pure argmax exploitation)
        p = score / score.sum()
        return cats[int(self._rng.choice(len(cats), p=p))]

    def _to_unit(self, leaf, v: float) -> float:
        lo, hi = float(leaf.lower), float(leaf.upper)
        if getattr(leaf, "log", False):
            return (math.log(v) - math.log(lo)) / (math.log(hi) - math.log(lo))
        return (v - lo) / (hi - lo)

    def _from_unit(self, leaf, u: float) -> Any:
        lo, hi = float(leaf.lower), float(leaf.upper)
        u = min(1.0, max(0.0, u))
        if getattr(leaf, "log", False):
            v = math.exp(math.log(lo) + u * (math.log(hi) - math.log(lo)))
        else:
            v = lo + u * (hi - lo)
        if isinstance(leaf, Integer):
            v = int(round(v))
            if leaf.q:
                v = int(round(v / leaf.q) * leaf.q)
            return max(leaf.lower, min(leaf.upper - 1, v))
        if getattr(leaf, "q", None):
            v = round(v / leaf.q) * leaf.q
        return float(v)

    def _kde_logpdf(self, xs: np.ndarray, centers: np.ndarray) -> np.ndarray:
        """Parzen estimator in unit space: mixture of Gaussians at the
        observed points with a scaled-silverman bandwidth + uniform prior."""
        n = len(centers)
        bw = max(1e-3, 1.06 * (np.std(centers) + 1e-3) * n ** -0.2)
        diffs = (xs[:, None] - centers[None, :]) / bw
        comp = np.exp(-0.5 * diffs ** 2) / (bw * math.sqrt(2 * math.pi))
        # mixture incl. a uniform component (the prior over [0,1])
        pdf = (comp.sum(axis=1) + 1.0) / (n + 1)
        return np.log(pdf + 1e-12)

    def _tpe_numeric(self, leaf, gv, bv) -> Any:
        g = np.array([self._to_unit(leaf, v) for v in gv], float)
        b = np.array([self._to_unit(leaf, v) for v in bv], float) \
            if bv else np.array([0.5])
        # candidates drawn from the GOOD model (plus uniform exploration)
        n_from_good = max(1, self.n_candidates - 4)
        bw = max(1e-3, 1.06 * (np.std(g) + 1e-3) * len(g) ** -0.2)
        cand = np.concatenate([
            self._rng.choice(g, size=n_from_good) +
            self._rng.normal(0, bw, size=n_from_good),
            self._rng.uniform(0, 1, size=4),
        ])
        cand = np.clip(cand, 0.0, 1.0)
        score = self._kde_logpdf(cand, g) - self._kde_logpdf(cand, b)
        return self._from_unit(leaf, float(cand[int(np.argmax(score))]))
