"""BOHB: Bayesian Optimization + HyperBand (native, no deps).

Reference analog: ``python/ray/tune/search/bohb/`` — Ray wraps the
``hpbandster`` package; this is a from-scratch implementation of the
same published algorithm (Falkner, Klein, Hutter, ICML 2018): run
HyperBand's bracketed successive halving for budget allocation, but
replace its random sampling with a TPE-style model.  The model for a new
suggestion is built from observations at the LARGEST budget that has
enough of them (the paper's |D_b| >= d + 2 rule) — low-budget results
bootstrap the model early, high-budget results dominate once available.

Pairs with ``HyperBandScheduler`` (``HyperBandForBOHB`` is the
reference-named alias) — the scheduler prunes, the searcher proposes:

    tune.Tuner(train_fn, tune_config=tune.TuneConfig(
        search_alg=BOHBSearcher(), scheduler=HyperBandForBOHB(...)))
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.schedulers.hyperband import HyperBandScheduler
from ray_tpu.tune.search.tpe import TPESearcher, _walk

# reference-named alias: the reference couples BOHB to a HyperBand
# scheduler subclass; ours needs no coupling beyond the shared
# time_attr, so the alias IS the scheduler
HyperBandForBOHB = HyperBandScheduler


class BOHBSearcher(TPESearcher):
    def __init__(self, metric: Optional[str] = None,
                 mode: Optional[str] = None, *,
                 time_attr: str = "training_iteration",
                 n_initial_points: int = 6, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        super().__init__(metric, mode, n_initial_points=n_initial_points,
                         gamma=gamma, n_candidates=n_candidates, seed=seed)
        self.time_attr = time_attr
        # budget -> list of (config, signed score); a trial contributes
        # its LATEST score per budget
        self._by_budget: Dict[int, Dict[str, Tuple[Dict, float]]] = {}

    # -- observation intake --------------------------------------------------
    def on_trial_result(self, trial_id: str,
                        result: Dict[str, Any]) -> None:
        """Intermediate (rung) results feed the model — this is the
        entire point of BOHB versus plain TPE-on-final-results."""
        if not result or self.metric not in result:
            return
        cfg = self._pending.get(trial_id)
        if cfg is None:
            return
        budget = int(result.get(self.time_attr, 0) or 0)
        sign = 1.0 if self.mode == "max" else -1.0
        self._by_budget.setdefault(budget, {})[trial_id] = (
            cfg, sign * float(result[self.metric]))

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]]) -> None:
        if result and self.metric in result:
            self.on_trial_result(trial_id, result)
        self._pending.pop(trial_id, None)

    # -- model source --------------------------------------------------------
    def _model_observations(self) -> List[Tuple[Dict, float]]:
        if not self._by_budget:
            return []
        from ray_tpu.tune.search.sample import Domain
        # d = number of HYPERPARAMETERS (Domain leaves) — constants in
        # the space must not inflate the |D_b| >= d+2 activation bar
        dims = sum(1 for _, leaf in _walk(self._space)
                   if isinstance(leaf, Domain)) if self._space else 1
        need = max(self.n_initial, dims + 2)
        for budget in sorted(self._by_budget, reverse=True):
            obs = list(self._by_budget[budget].values())
            if len(obs) >= need:
                return obs
        # no budget is rich enough yet: pool everything (still better
        # than random once a handful of rungs exist)
        pooled: List[Tuple[Dict, float]] = []
        for per_trial in self._by_budget.values():
            pooled.extend(per_trial.values())
        return pooled if len(pooled) >= need else []

    def suggest(self, trial_id: str) -> Dict[str, Any]:
        # swap the TPE base's final-result list for the budget-aware
        # selection, then reuse its proposal machinery wholesale
        self._observations = self._model_observations()
        return super().suggest(trial_id)
