"""Variant generation: grid expansion × random sampling.

Reference: ``python/ray/tune/search/basic_variant.py``
(``BasicVariantGenerator``) — every ``grid_search`` key is expanded into
its cross-product; ``Domain`` leaves are sampled per trial; the product is
repeated ``num_samples`` times.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.search.sample import Domain


def _walk(space: Any, path: Tuple) -> Iterator[Tuple[Tuple, Any]]:
    if isinstance(space, dict):
        if set(space.keys()) == {"grid_search"}:
            yield (path, space)
            return
        for k, v in space.items():
            yield from _walk(v, path + (k,))
    else:
        yield (path, space)


def _set(d: Dict, path: Tuple, value: Any) -> None:
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


class BasicVariantGenerator:
    def __init__(self, seed: Optional[int] = None):
        self._rng = np.random.default_rng(seed)

    def generate(self, param_space: Dict[str, Any],
                 num_samples: int = 1) -> List[Dict[str, Any]]:
        leaves = list(_walk(param_space, ()))
        grid_paths = [(p, v["grid_search"]) for p, v in leaves
                      if isinstance(v, dict) and set(v) == {"grid_search"}]
        other = [(p, v) for p, v in leaves
                 if not (isinstance(v, dict) and set(v) == {"grid_search"})]
        grids = [list(vals) for _, vals in grid_paths]
        configs: List[Dict[str, Any]] = []
        for _ in range(num_samples):
            for combo in itertools.product(*grids) if grids else [()]:
                cfg: Dict[str, Any] = {}
                for (p, _), val in zip(grid_paths, combo):
                    _set(cfg, p, val)
                for p, v in other:
                    _set(cfg, p, v.sample(self._rng)
                         if isinstance(v, Domain) else v)
                configs.append(cfg)
        return configs
