"""Search-space domains.

Reference: ``python/ray/tune/search/sample.py`` — ``tune.uniform``/
``loguniform``/``randint``/``choice``/``grid_search``/``sample_from``.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


class Domain:
    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False,
                 q: Optional[float] = None):
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng):
        if self.log:
            v = math.exp(rng.uniform(math.log(self.lower),
                                     math.log(self.upper)))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self.q:
            v = round(v / self.q) * self.q
        return float(v)


class Integer(Domain):
    def __init__(self, lower: int, upper: int, q: Optional[int] = None):
        self.lower, self.upper, self.q = lower, upper, q

    def sample(self, rng):
        v = int(rng.integers(self.lower, self.upper))
        if self.q:
            v = int(round(v / self.q) * self.q)
        return v


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return self.categories[int(rng.integers(0, len(self.categories)))]


class Normal(Domain):
    def __init__(self, mean: float, sd: float):
        self.mean, self.sd = mean, sd

    def sample(self, rng):
        return float(rng.normal(self.mean, self.sd))


class Function(Domain):
    def __init__(self, fn: Callable[[Optional[Dict]], Any]):
        self.fn = fn

    def sample(self, rng):
        try:
            return self.fn(None)
        except TypeError:
            return self.fn()


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int) -> Integer:
    return Integer(lower, upper, q=q)


def randn(mean: float = 0.0, sd: float = 1.0) -> Normal:
    return Normal(mean, sd)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values: Sequence[Any]) -> Dict[str, List[Any]]:
    """Reference encoding: {'grid_search': [...]} in the param space."""
    return {"grid_search": list(values)}
