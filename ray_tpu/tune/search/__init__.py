"""Search spaces and searchers (reference: ``python/ray/tune/search/``)."""

from ray_tpu.tune.search.sample import (  # noqa: F401
    Categorical, Domain, Float, Integer, choice, grid_search, loguniform,
    qrandint, quniform, randint, randn, sample_from, uniform,
)
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator  # noqa: F401
from ray_tpu.tune.search.searcher import Searcher  # noqa: F401
from ray_tpu.tune.search.tpe import TPESearcher  # noqa: F401
from ray_tpu.tune.search.bohb import BOHBSearcher, HyperBandForBOHB  # noqa: F401
from ray_tpu.tune.search.adapters import (  # noqa: F401
    HyperOptSearch, OptunaSearch,
)
