"""Trial schedulers (reference: ``python/ray/tune/schedulers/``)."""

from ray_tpu.tune.schedulers.trial_scheduler import (  # noqa: F401
    FIFOScheduler, TrialScheduler,
)
from ray_tpu.tune.schedulers.async_hyperband import (  # noqa: F401
    ASHAScheduler, AsyncHyperBandScheduler,
)
from ray_tpu.tune.schedulers.median_stopping import (  # noqa: F401
    MedianStoppingRule,
)
from ray_tpu.tune.schedulers.pbt import (  # noqa: F401
    PopulationBasedTraining,
)
from ray_tpu.tune.schedulers.pb2 import PB2  # noqa: F401
from ray_tpu.tune.schedulers.hyperband import (  # noqa: F401
    HyperBandScheduler,
)
