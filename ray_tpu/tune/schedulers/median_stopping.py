"""Median stopping rule.

Reference: ``python/ray/tune/schedulers/median_stopping_rule.py`` — stop a
trial at step t if its best metric so far is worse than the median of
other trials' running averages at t.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class MedianStoppingRule(TrialScheduler):
    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 grace_period: int = 1, min_samples_required: int = 3):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, List[float]] = {}

    def on_trial_result(self, controller, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        val = result.get(self.metric)
        if val is None:
            return self.CONTINUE
        sign = 1.0 if self.mode == "max" else -1.0
        self._avgs.setdefault(trial.id, []).append(sign * float(val))
        if t < self.grace_period:
            return self.CONTINUE
        others = [np.mean(v) for tid, v in self._avgs.items()
                  if tid != trial.id]
        if len(others) < self.min_samples:
            return self.CONTINUE
        best = max(self._avgs[trial.id])
        if best < np.median(others):
            return self.STOP
        return self.CONTINUE
