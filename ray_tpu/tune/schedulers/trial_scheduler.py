"""Scheduler interface.

Reference: ``python/ray/tune/schedulers/trial_scheduler.py`` — schedulers
see every streamed result and answer CONTINUE/STOP (+ optional
clone-from directives for PBT).
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class TrialScheduler:
    CONTINUE = "CONTINUE"
    STOP = "STOP"

    def set_metric(self, metric: Optional[str], mode: Optional[str]) -> None:
        if getattr(self, "metric", None) is None:
            self.metric = metric
        if getattr(self, "mode", None) is None:
            self.mode = mode or "max"

    def on_trial_result(self, controller, trial, result: Dict[str, Any]) -> str:
        return self.CONTINUE

    def on_trial_complete(self, controller, trial,
                          result: Optional[Dict[str, Any]]) -> None:
        pass

    def choose_trial_to_run(self, controller):
        """Default: FIFO over pending trials."""
        for t in controller.trials:
            if t.status == "PENDING":
                return t
        return None


class FIFOScheduler(TrialScheduler):
    pass
