"""HyperBand: bracketed successive halving.

Reference: ``python/ray/tune/schedulers/hyperband.py`` — trials are
assigned round-robin to brackets with different (initial budget, halving
aggressiveness) trade-offs; within a bracket, survivors at each milestone
are the top ``1/eta`` by metric.  Versus ASHA (async_hyperband.py), the
bracket structure hedges the choice of grace period; decisions here stay
asynchronous per-report (no barrier), matching the reference's practical
behavior under streaming results.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class _Bracket:
    def __init__(self, r0: int, max_t: int, eta: float):
        self.milestones: List[int] = []
        t = r0
        while t < max_t:
            self.milestones.append(int(t))
            t = int(math.ceil(t * eta))
        self.recorded: Dict[int, List[float]] = {m: [] for m in self.milestones}


class HyperBandScheduler(TrialScheduler):
    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 max_t: int = 81, reduction_factor: float = 3):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.eta = reduction_factor
        s_max = int(math.floor(math.log(max_t, reduction_factor)))
        # bracket s starts at budget max_t / eta^s (classic HyperBand)
        self.brackets = [
            _Bracket(max(1, int(max_t / reduction_factor ** s)),
                     max_t, reduction_factor)
            for s in range(s_max, -1, -1)]
        self._assignment: Dict[str, int] = {}
        self._next_bracket = 0

    def _bracket_for(self, trial) -> _Bracket:
        b = self._assignment.get(trial.id)
        if b is None:
            b = self._next_bracket % len(self.brackets)
            self._assignment[trial.id] = b
            self._next_bracket += 1
        return self.brackets[b]

    def on_trial_result(self, controller, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr)
        val = result.get(self.metric)
        if t is None or val is None:
            return self.CONTINUE
        if t >= self.max_t:
            return self.STOP
        sign = 1.0 if self.mode == "max" else -1.0
        bracket = self._bracket_for(trial)
        decision = self.CONTINUE
        for m in bracket.milestones:
            if t >= m and m not in trial.rungs_hit:
                trial.rungs_hit.add(m)
                vals = bracket.recorded[m]
                vals.append(sign * float(val))
                k = max(1, int(math.ceil(len(vals) / self.eta)))
                cutoff = sorted(vals, reverse=True)[k - 1]
                if sign * float(val) < cutoff:
                    decision = self.STOP
        return decision
