"""Population-based training.

Reference: ``python/ray/tune/schedulers/pbt.py`` — every
``perturbation_interval`` steps, bottom-quantile trials EXPLOIT a
top-quantile trial (clone its latest checkpoint) and EXPLORE (perturb its
hyperparameters).  Implemented stop-and-clone style: the controller stops
the bottom trial and relaunches it with the mutated config and the donor
checkpoint (the reference's in-place restore is an optimization of the
same semantics).
"""

from __future__ import annotations

import random
from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class PopulationBasedTraining(TrialScheduler):
    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._scores: Dict[str, float] = {}

    def _mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search.sample import Domain
        out = dict(config)
        rng = np.random.default_rng(self._rng.randrange(2**31))
        for key, spec in self.mutations.items():
            if key not in out:
                continue
            if self._rng.random() < self.resample_p:
                if isinstance(spec, Domain):
                    out[key] = spec.sample(rng)
                elif isinstance(spec, (list, tuple)):
                    out[key] = self._rng.choice(list(spec))
                elif callable(spec):
                    out[key] = spec()
            else:
                factor = self._rng.choice([0.8, 1.2])
                if isinstance(out[key], (int, float)) and \
                        not isinstance(out[key], bool):
                    out[key] = type(out[key])(out[key] * factor)
                elif isinstance(spec, (list, tuple)):
                    out[key] = self._rng.choice(list(spec))
        return out

    def on_trial_result(self, controller, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr, 0)
        val = result.get(self.metric)
        if val is None:
            return self.CONTINUE
        sign = 1.0 if self.mode == "max" else -1.0
        self._scores[trial.id] = sign * float(val)
        last = self._last_perturb.get(trial.id, 0)
        if t - last < self.interval:
            return self.CONTINUE
        self._last_perturb[trial.id] = t
        scores = sorted(self._scores.items(), key=lambda kv: kv[1])
        n = len(scores)
        if n < 2:
            return self.CONTINUE
        k = max(1, int(n * self.quantile))
        bottom = [tid for tid, _ in scores[:k]]
        top = [tid for tid, _ in scores[-k:]]
        if trial.id in bottom:
            donor_id = self._rng.choice(top)
            donor = controller.get_trial(donor_id)
            if donor is not None and donor.latest_checkpoint_path:
                # stop-and-clone: relaunch with donor ckpt + mutated config
                controller.request_clone(
                    trial, self._mutate(donor.config),
                    donor.latest_checkpoint_path)
                return self.STOP
        return self.CONTINUE
