"""PB2: population-based bandit hyperparameter optimization.

Reference: ``python/ray/tune/schedulers/pb2.py`` (Parker-Holder et al.,
"Provably Efficient Online Hyperparameter Optimization with
Population-Based Bandits", NeurIPS 2020).  PBT's EXPLOIT step is kept
(bottom-quantile trials clone a top-quantile trial's checkpoint); the
EXPLORE step replaces random perturbation with a **time-varying GP-UCB
bandit**: the scheduler records, for every perturbation window, the
hyperparameter point used and the reward improvement it produced, fits a
GP over (time, hyperparams) → improvement, and sends the cloned trial to
the UCB-argmax point inside ``hyperparam_bounds``.

Implemented from the paper against this package's GP-free stack (the
reference wraps GPy): a small numpy RBF-kernel GP with the paper's
time-decay treatment folded in as an extra kernel dimension, UCB argmax
by candidate sampling.  Same controller contract as PBT
(``request_clone`` stop-and-clone; tune/_internal/controller.py:107).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.tune.schedulers.pbt import PopulationBasedTraining


class _TinyGP:
    """RBF-kernel GP regression, numpy-only (no hyperparameter fitting —
    fixed unit lengthscale on normalized inputs, the paper's default
    regime; jitter keeps the Cholesky well-posed)."""

    def __init__(self, noise: float = 1e-2, lengthscale: float = 0.3):
        self.noise = noise
        self.ls = lengthscale
        self._X: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._L: Optional[np.ndarray] = None

    def _k(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (self.ls ** 2))

    def fit(self, X: np.ndarray, y: np.ndarray) -> None:
        self._X = X
        K = self._k(X, X) + self.noise * np.eye(len(X))
        self._L = np.linalg.cholesky(K)
        self._alpha = np.linalg.solve(
            self._L.T, np.linalg.solve(self._L, y))

    def predict(self, Xs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        Ks = self._k(Xs, self._X)
        mu = Ks @ self._alpha
        v = np.linalg.solve(self._L, Ks.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-9, None)
        return mu, np.sqrt(var)


class PB2(PopulationBasedTraining):
    """PBT with GP-UCB explore over ``hyperparam_bounds``.

    hyperparam_bounds: {key: [low, high]} continuous ranges the bandit
        searches (the reference PB2 API; log-scaled keys can simply pass
        log-space bounds and exp in the trainable).
    """

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 perturbation_interval: int = 5,
                 hyperparam_bounds: Optional[Dict[str, List[float]]] = None,
                 quantile_fraction: float = 0.25,
                 seed: Optional[int] = None):
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds "
                             "({key: [low, high]})")
        super().__init__(time_attr=time_attr, metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},  # explore is the bandit
                         quantile_fraction=quantile_fraction, seed=seed)
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self._keys = sorted(self.bounds)
        self._np_rng = np.random.default_rng(seed)
        # bandit dataset: rows (t_RAW, x_norm...) -> reward improvement;
        # the time column is normalized by the dataset's max at FIT time
        # (per-row normalization at record time would give every row the
        # same ~1.0 coordinate — a time-blind GP)
        self._data_X: List[List[float]] = []
        self._data_y: List[float] = []
        self._prev_score: Dict[str, float] = {}

    # ----------------------------------------------------------- encoding
    def _norm(self, config: Dict[str, Any]) -> List[float]:
        out = []
        for k in self._keys:
            lo, hi = self.bounds[k]
            v = float(config.get(k, lo))
            out.append((v - lo) / (hi - lo) if hi > lo else 0.0)
        return out

    def _denorm(self, x: np.ndarray) -> Dict[str, float]:
        return {k: self.bounds[k][0] +
                float(x[i]) * (self.bounds[k][1] - self.bounds[k][0])
                for i, k in enumerate(self._keys)}

    # ------------------------------------------------------------ dataset
    def _record_window(self, trial, t: float, val: float) -> None:
        prev = self._prev_score.get(trial.id)
        self._prev_score[trial.id] = val
        if prev is None:
            return
        self._data_X.append([float(t), *self._norm(trial.config)])
        self._data_y.append(val - prev)

    # ------------------------------------------------------------- explore
    def _mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """GP-UCB argmax over the bounds (overrides PBT's random
        perturbation); falls back to uniform sampling until the bandit
        has data."""
        out = dict(config)
        if len(self._data_y) >= 3:
            X = np.asarray(self._data_X, np.float64)
            X[:, 0] /= max(1e-9, X[:, 0].max())    # normalize raw time col
            y = np.asarray(self._data_y, np.float64)
            ystd = y.std() or 1.0
            gp = _TinyGP()
            gp.fit(X, (y - y.mean()) / ystd)
            n_cand = 256
            cand = self._np_rng.random((n_cand, len(self._keys)))
            t_now = np.ones((n_cand, 1))           # "next window" time
            mu, sd = gp.predict(np.concatenate([t_now, cand], axis=1))
            # GP-UCB beta_t (paper uses the Srinivas schedule; constants
            # folded): sqrt(2 log(|C| t^2 pi^2 / 6 delta)), delta=0.1
            tstep = max(2, len(self._data_y))
            beta = math.sqrt(2 * math.log(
                n_cand * tstep ** 2 * math.pi ** 2 / (6 * 0.1)))
            best = cand[int(np.argmax(mu + beta * sd))]
            out.update(self._denorm(best))
        else:
            for k in self._keys:
                lo, hi = self.bounds[k]
                out[k] = lo + float(self._np_rng.random()) * (hi - lo)
        return out

    def on_trial_result(self, controller, trial,
                        result: Dict[str, Any]) -> str:
        val = result.get(self.metric)
        if val is not None:
            sign = 1.0 if self.mode == "max" else -1.0
            t = result.get(self.time_attr, 0)
            last = self._last_perturb.get(trial.id, 0)
            if t - last >= self.interval:
                # window closing: record (config used, improvement seen)
                self._record_window(trial, t, sign * float(val))
        decision = super().on_trial_result(controller, trial, result)
        if decision == self.STOP:
            # the cloned trial starts a fresh window
            self._prev_score.pop(trial.id, None)
        return decision
