"""ASHA: asynchronous successive halving.

Reference: ``python/ray/tune/schedulers/async_hyperband.py``
(``AsyncHyperBandScheduler`` / alias ``ASHAScheduler``): rungs at
``grace_period * reduction_factor**k``; when a trial reports at a rung it
is stopped unless its metric is in the top ``1/reduction_factor`` of all
results recorded at that rung.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.tune.schedulers.trial_scheduler import TrialScheduler


class AsyncHyperBandScheduler(TrialScheduler):
    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4):
        self.time_attr = time_attr
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace_period = grace_period
        self.rf = reduction_factor
        # rung milestones
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(int(t))
            t *= reduction_factor
        # rung -> recorded metric values
        self._recorded: Dict[int, List[float]] = {r: [] for r in self.rungs}

    def on_trial_result(self, controller, trial, result: Dict[str, Any]) -> str:
        t = result.get(self.time_attr)
        val = result.get(self.metric)
        if t is None or val is None:
            return self.CONTINUE
        if t >= self.max_t:
            return self.STOP
        sign = 1.0 if self.mode == "max" else -1.0
        # rungs this report crosses for the first time
        crossed = [r for r in self.rungs
                   if t >= r and r not in trial.rungs_hit]
        decision = self.CONTINUE
        for rung in crossed:
            trial.rungs_hit.add(rung)
            vals = self._recorded[rung]
            vals.append(sign * float(val))
            k = max(1, int(np.ceil(len(vals) / self.rf)))
            cutoff = sorted(vals, reverse=True)[k - 1]
            if sign * float(val) < cutoff:
                decision = self.STOP
        return decision


ASHAScheduler = AsyncHyperBandScheduler
