"""``@ray_tpu.remote`` for plain functions.

Reference: ``python/ray/remote_function.py`` (SURVEY.md §2.3, §3.2).
``f.remote(*args)`` returns ObjectRef(s); ``f.options(**over).remote(...)``
overrides per-call options with the same names as the reference
(``num_cpus``, ``num_tpus`` standing in for ``num_gpus``, ``resources``,
``num_returns``, ``max_retries``, ``retry_exceptions``,
``scheduling_strategy``, ``name``, ``runtime_env``).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private import worker as _worker
from ray_tpu.util.scheduling_strategies import strategy_to_spec

_DEFAULTS = dict(num_returns=1, num_cpus=1, num_tpus=0, resources=None,
                 max_retries=None, retry_exceptions=False,
                 scheduling_strategy=None, name=None, runtime_env=None)


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._function = fn
        self._options = {**_DEFAULTS, **(options or {})}
        functools.update_wrapper(self, fn)

    def remote(self, *args: Any, **kwargs: Any):
        o = self._options
        w = _worker.global_worker()
        refs = w.submit(
            self._function, args, kwargs,
            num_returns=o["num_returns"], num_cpus=o["num_cpus"],
            num_tpus=o["num_tpus"], resources=o["resources"],
            max_retries=o["max_retries"], retry_exceptions=o["retry_exceptions"],
            scheduling_strategy=strategy_to_spec(o["scheduling_strategy"]),
            name=o["name"] or getattr(self._function, "__name__", "task"),
            runtime_env=o["runtime_env"])
        return refs[0] if o["num_returns"] == 1 else refs

    def options(self, **overrides: Any) -> "RemoteFunction":
        merged = {**self._options}
        for k, v in overrides.items():
            if k == "num_gpus":  # accept the reference spelling; map to TPU chips
                k = "num_tpus"
            if k not in _DEFAULTS:
                raise ValueError(f"unknown option {k!r}")
            merged[k] = v
        return RemoteFunction(self._function, merged)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._function.__name__!r} cannot be called "
            "directly; use .remote()")

    @property
    def func(self):
        """The underlying local function (for testing)."""
        return self._function
