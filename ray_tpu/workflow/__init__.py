"""Durable workflows: checkpointed DAGs that survive driver restarts.

Reference: ``python/ray/workflow/`` (SURVEY.md §2.5) — steps are logged to
storage before/after execution; ``resume`` replays completed steps from
storage and re-executes the rest.  API:

    @workflow.step
    def fetch(x): ...

    node = combine.bind(fetch.bind(1), fetch.bind(2))
    workflow.run(node, workflow_id="demo", storage="/path")
    workflow.resume("demo", node, storage="/path")   # after a crash

Each step runs as one cluster task; results are pickled per-step under
``<storage>/<workflow_id>/<step>.pkl`` with a ``status.json`` index, so a
resumed run only executes steps without a checkpoint (exactly-once per
successful step, at-least-once overall — the reference's model).
"""

from __future__ import annotations

import json
import pickle
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu

_DEFAULT_STORAGE = "/tmp/rtpu_workflows"


class WorkflowStepNode:
    """A DAG node: a step function bound to (possibly node-valued) args."""

    def __init__(self, fn, args: tuple, kwargs: dict, name: str,
                 max_retries: int):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = name
        self.max_retries = max_retries

    def __repr__(self):
        return f"WorkflowStepNode({self.name})"


class _Step:
    def __init__(self, fn, name: Optional[str] = None, max_retries: int = 3):
        self._fn = fn
        self._name = name or fn.__name__
        self._max_retries = max_retries

    def bind(self, *args, **kwargs) -> WorkflowStepNode:
        return WorkflowStepNode(self._fn, args, kwargs, self._name,
                                self._max_retries)

    def options(self, *, name: Optional[str] = None,
                max_retries: Optional[int] = None) -> "_Step":
        return _Step(self._fn, name or self._name,
                     self._max_retries if max_retries is None else max_retries)

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


def step(fn=None, **opts):
    """Decorator marking a function as a workflow step."""
    if fn is None:
        return lambda f: _Step(f, **opts)
    return _Step(fn)


# ------------------------------------------------------------------ storage
class _Store:
    def __init__(self, storage: str, workflow_id: str):
        self.root = Path(storage) / workflow_id
        self.root.mkdir(parents=True, exist_ok=True)

    def status_path(self) -> Path:
        return self.root / "status.json"

    def read_status(self) -> dict:
        try:
            return json.loads(self.status_path().read_text())
        except (FileNotFoundError, json.JSONDecodeError):
            return {"status": "RUNNING", "steps": {}}

    def write_status(self, st: dict) -> None:
        tmp = self.status_path().with_suffix(".tmp")
        tmp.write_text(json.dumps(st, indent=2))
        tmp.replace(self.status_path())

    def has_result(self, step_key: str) -> bool:
        return (self.root / f"{step_key}.pkl").exists()

    def load_result(self, step_key: str) -> Any:
        with open(self.root / f"{step_key}.pkl", "rb") as f:
            return pickle.load(f)

    def save_result(self, step_key: str, value: Any) -> None:
        tmp = self.root / f"{step_key}.pkl.tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        tmp.replace(self.root / f"{step_key}.pkl")


# ---------------------------------------------------------------- execution
def _topo_order(node: WorkflowStepNode) -> List[WorkflowStepNode]:
    """Post-order unique traversal: dependencies before dependents."""
    seen: Dict[int, WorkflowStepNode] = {}
    order: List[WorkflowStepNode] = []

    def visit(n):
        if id(n) in seen:
            return
        seen[id(n)] = n
        for a in list(n.args) + list(n.kwargs.values()):
            if isinstance(a, WorkflowStepNode):
                visit(a)
        order.append(n)

    visit(node)
    return order


def _step_keys(order: List[WorkflowStepNode]) -> Dict[int, str]:
    """Stable step keys: name + occurrence index in topo order."""
    counts: Dict[str, int] = {}
    keys = {}
    for n in order:
        i = counts.get(n.name, 0)
        counts[n.name] = i + 1
        keys[id(n)] = f"{n.name}_{i}"
    return keys


def run(node: WorkflowStepNode, *, workflow_id: Optional[str] = None,
        storage: str = _DEFAULT_STORAGE) -> Any:
    """Execute the DAG durably; returns the root node's result."""
    workflow_id = workflow_id or f"wf_{int(time.time() * 1000):x}"
    store = _Store(storage, workflow_id)
    status = store.read_status()
    if status.get("status") == "SUCCEEDED" and \
            status.get("root") in status["steps"]:
        return store.load_result(status["root"])

    order = _topo_order(node)
    keys = _step_keys(order)
    status["status"] = "RUNNING"
    status.setdefault("steps", {})
    status["root"] = keys[id(node)]
    store.write_status(status)

    # Independent branches run CONCURRENTLY: every step whose deps are
    # resolved is submitted; results are checkpointed as they complete.
    results: Dict[int, Any] = {}
    for n in order:
        key = keys[id(n)]
        if store.has_result(key):
            results[id(n)] = store.load_result(key)
            status["steps"][key] = "SUCCEEDED"

    def deps(n):
        return [a for a in list(n.args) + list(n.kwargs.values())
                if isinstance(a, WorkflowStepNode)]

    remaining = [n for n in order if id(n) not in results]
    in_flight: Dict[Any, WorkflowStepNode] = {}  # ref -> node
    failure: Optional[BaseException] = None
    while remaining or in_flight:
        launched = []
        for n in remaining:
            if failure is not None:
                break
            if all(id(d) in results for d in deps(n)):
                def resolve(v):
                    return results[id(v)] \
                        if isinstance(v, WorkflowStepNode) else v
                args = tuple(resolve(a) for a in n.args)
                kwargs = {k: resolve(v) for k, v in n.kwargs.items()}
                task = ray_tpu.remote(max_retries=n.max_retries)(n.fn)
                in_flight[task.remote(*args, **kwargs)] = n
                launched.append(n)
        remaining = [n for n in remaining if n not in launched]
        if not in_flight:
            break
        done, _ = ray_tpu.wait(list(in_flight), num_returns=1)
        n = in_flight.pop(done[0])
        key = keys[id(n)]
        try:
            value = ray_tpu.get(done[0])
        except Exception as e:  # noqa: BLE001
            status["steps"][key] = "FAILED"
            failure = e
            continue  # drain remaining in-flight steps (checkpoint them)
        store.save_result(key, value)
        status["steps"][key] = "SUCCEEDED"
        store.write_status(status)
        results[id(n)] = value

    if failure is not None:
        status["status"] = "FAILED"
        store.write_status(status)
        raise failure
    status["status"] = "SUCCEEDED"
    store.write_status(status)
    return results[id(node)]


# ----------------------------------------------------------------- control
def resume(workflow_id: str, node: WorkflowStepNode, *,
           storage: str = _DEFAULT_STORAGE) -> Any:
    """Re-run a workflow: completed steps load from storage, the rest
    execute.  The DAG must be re-supplied (this framework does not pickle
    step closures into storage; the reference serializes the DAG — noted
    as a capability difference in the docstring)."""
    return run(node, workflow_id=workflow_id, storage=storage)


def get_status(workflow_id: str, *,
               storage: str = _DEFAULT_STORAGE) -> Optional[dict]:
    p = Path(storage) / workflow_id / "status.json"
    try:
        return json.loads(p.read_text())
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def list_all(*, storage: str = _DEFAULT_STORAGE) -> List[Tuple[str, str]]:
    root = Path(storage)
    out = []
    if root.is_dir():
        for d in sorted(root.iterdir()):
            st = get_status(d.name, storage=storage)
            if st is not None:
                out.append((d.name, st.get("status", "UNKNOWN")))
    return out


def delete(workflow_id: str, *, storage: str = _DEFAULT_STORAGE) -> None:
    import shutil
    shutil.rmtree(Path(storage) / workflow_id, ignore_errors=True)
