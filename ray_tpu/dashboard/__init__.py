"""Dashboard-lite: a JSON/Prometheus HTTP endpoint over cluster state.

Reference: ``python/ray/dashboard/`` (SURVEY.md §2.3) — aiohttp server +
React UI.  This build keeps the API surface (REST endpoints over live
cluster state, Prometheus metrics) and serves a single-file vanilla-JS
UI (``_index.py``) instead of a TypeScript build; everything is stdlib
``http.server`` on a thread.

Endpoints:
  GET /                    — live UI (summary tiles + tabbed tables)
  GET /api/cluster_summary — nodes/resources/tasks/actors/objects rollup
  GET /api/nodes|actors|tasks|objects|workers|placement_groups
  GET /api/timeline        — Chrome trace JSON
  GET /metrics             — Prometheus exposition (cluster-merged)
  GET /metrics/history     — head-TSDB range query (?series=<expr>
                             [&window=600][&step=10]; DESIGN.md §4k) —
                             history + the UI's sparkline feed
  GET /profile/flame       — continuous-profiling flamegraph SVG
                             (?window=5m[&proc=ROLE:PID]; DESIGN.md §4o)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_server: Optional[ThreadingHTTPServer] = None


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # quiet
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj) -> None:
        self._send(200, json.dumps(obj, indent=2, default=str).encode(),
                   "application/json")

    def do_GET(self):  # noqa: N802 - http.server API
        from ray_tpu.util import metrics, state
        try:
            if self.path == "/metrics":
                text = metrics.prometheus_text(metrics.collect_cluster())
                self._send(200, text.encode(), "text/plain; version=0.0.4")
            elif self.path.startswith("/metrics/history"):
                # TSDB range query (DESIGN.md §4k): the UI's sparkline
                # feed.  ?series=<expr>[&window=600][&step=10] — the
                # expression is instant-evaluated at each step over the
                # trailing window.
                import time as _time
                from urllib.parse import parse_qs, urlparse
                qs = parse_qs(urlparse(self.path).query)
                expr = (qs.get("series") or qs.get("expr") or [None])[0]
                if not expr:
                    self._send(400, b"missing ?series=<expr>",
                               "text/plain")
                    return
                try:
                    window = float((qs.get("window") or ["600"])[0])
                    step = float((qs.get("step") or [str(max(
                        window / 60.0, 1.0))])[0])
                except ValueError:
                    self._send(400, b"window/step must be numbers",
                               "text/plain")
                    return
                end = _time.time()
                from ray_tpu.util.tsdb import QueryError
                try:
                    rows = state.metrics_history(
                        expr, start=end - window, end=end, step=step)
                except QueryError as e:
                    # only a malformed expression is the CLIENT's fault;
                    # RPC/head failures fall to the outer 500 handler
                    self._send(400, f"bad expression: {e}".encode(),
                               "text/plain")
                    return
                self._json({"expr": expr, "window_s": window,
                            "step_s": step, "results": rows})
            elif self.path.startswith("/profile/flame"):
                # continuous-profiling flamegraph (DESIGN.md §4o):
                # ?window=<dur>[&proc=ROLE:PID] → inline SVG over the
                # head ProfileStore's trailing window.
                from urllib.parse import parse_qs, urlparse
                from ray_tpu.util import profiler as profiler_mod
                from ray_tpu.util.tsdb import QueryError
                qs = parse_qs(urlparse(self.path).query)
                try:
                    window = profiler_mod.parse_duration(
                        (qs.get("window") or ["5m"])[0])
                    resp = state.profile(
                        window_s=window,
                        proc=(qs.get("proc") or [None])[0])
                except QueryError as e:
                    self._send(400, f"bad query: {e}".encode(),
                               "text/plain")
                    return
                if resp.get("disabled"):
                    self._send(404, b"profiler disabled on head",
                               "text/plain")
                    return
                svg = profiler_mod.render_flame_svg(
                    resp.get("stacks", {}),
                    title=f"ray_tpu flame — {window:.0f}s window, "
                          f"{resp.get('samples', 0)} samples")
                self._send(200, svg.encode(), "image/svg+xml")
            elif self.path == "/api/cluster_summary":
                self._json(state.cluster_summary())
            elif self.path == "/api/nodes":
                self._json(state.list_nodes())
            elif self.path == "/api/actors":
                self._json(state.list_actors())
            elif self.path == "/api/tasks":
                self._json(state.list_tasks())
            elif self.path == "/api/objects":
                self._json(state.list_objects())
            elif self.path == "/api/workers":
                self._json(state.list_workers())
            elif self.path == "/api/placement_groups":
                self._json(state.list_placement_groups())
            elif self.path == "/api/timeline":
                import ray_tpu
                self._json(ray_tpu.timeline())
            elif self.path == "/api/logs":
                self._json(_list_logs())
            elif self.path.startswith("/api/logs/"):
                from urllib.parse import parse_qs, urlparse
                u = urlparse(self.path)
                name = u.path[len("/api/logs/"):]
                try:
                    tail = int(parse_qs(u.query).get("tail", ["200"])[0])
                except ValueError:
                    self._send(400, b"tail must be an integer",
                               "text/plain")
                    return
                text = _read_log(name, tail)
                if text is None:
                    self._send(404, b"no such log", "text/plain")
                else:
                    self._send(200, text.encode("utf-8", "replace"),
                               "text/plain")
            elif self.path == "/":
                from ray_tpu.dashboard._index import INDEX_HTML
                self._send(200, INDEX_HTML.encode(), "text/html")
            else:
                self._send(404, b"not found", "text/plain")
        except Exception as e:  # noqa: BLE001
            self._send(500, str(e).encode(), "text/plain")


def _logs_dir():
    from ray_tpu._private import worker as worker_mod
    w = worker_mod.try_global_worker()
    if w is None or w.session is None:
        return None
    return w.session.path / "logs"


def _list_logs():
    """Reference: the dashboard's per-node log listing (SURVEY.md §5.5)."""
    d = _logs_dir()
    if d is None or not d.is_dir():
        return []
    out = []
    for p in sorted(d.glob("*.log")):
        try:
            out.append({"name": p.name, "bytes": p.stat().st_size})
        except OSError:
            pass
    return out


def _read_log(name: str, tail: int):
    """Tail one session log file.  The name must resolve INSIDE the logs
    dir — a traversal path (../gcs_state/...) must 404, not read."""
    d = _logs_dir()
    if d is None:
        return None
    p = (d / name).resolve()
    if not str(p).startswith(str(d.resolve()) + "/") or not p.is_file():
        return None
    tail = max(1, min(tail, 10000))
    # bounded read: a multi-GB log must not be loaded whole to serve a
    # 200-line tail — seek back a generous per-line budget instead
    budget = tail * 4096
    with open(p, "rb") as f:
        f.seek(0, 2)
        size = f.tell()
        f.seek(max(0, size - budget))
        data = f.read()
    lines = data.decode("utf-8", "replace").splitlines()
    if size > budget and lines:
        lines = lines[1:]  # first line is likely a partial
    return "\n".join(lines[-tail:]) + "\n"


def start_dashboard(host: str = "127.0.0.1",
                    port: int = 8265) -> ThreadingHTTPServer:
    """Start the dashboard HTTP server (daemon thread); returns the server."""
    global _server
    srv = ThreadingHTTPServer((host, port), _Handler)
    threading.Thread(target=srv.serve_forever, name="dashboard",
                     daemon=True).start()
    _server = srv
    return srv


def stop_dashboard() -> None:
    global _server
    if _server is not None:
        _server.shutdown()
        _server = None
