"""Single-file dashboard UI (no build step, no external deps).

Reference: ``python/ray/dashboard/client/`` is a React/TypeScript app; this
build ships the same information surface as one static page of vanilla JS
polling the REST API — cluster summary tiles plus tabbed live tables for
nodes, workers, actors, tasks, objects, and placement groups.
"""

INDEX_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
  :root { --fg:#1a1a1a; --muted:#6b6b6b; --line:#e3e3e3; --bg:#fafafa;
          --accent:#2563eb; --ok:#15803d; --bad:#b91c1c; }
  * { box-sizing:border-box; }
  body { margin:0; font:14px/1.45 system-ui,-apple-system,sans-serif;
         color:var(--fg); background:var(--bg); }
  header { padding:14px 20px; background:#fff;
           border-bottom:1px solid var(--line); display:flex;
           align-items:baseline; gap:14px; }
  header h1 { font-size:16px; margin:0; }
  header .sub { color:var(--muted); font-size:12px; }
  .tiles { display:flex; flex-wrap:wrap; gap:12px; padding:16px 20px; }
  .tile { background:#fff; border:1px solid var(--line); border-radius:8px;
          padding:10px 16px; min-width:130px; }
  .tile .v { font-size:22px; font-weight:600; }
  .tile .l { color:var(--muted); font-size:12px; }
  nav { display:flex; gap:2px; padding:0 20px; }
  nav button { border:1px solid var(--line); border-bottom:none;
               background:#f1f1f1; padding:7px 14px; cursor:pointer;
               border-radius:6px 6px 0 0; font:inherit; }
  nav button.on { background:#fff; font-weight:600;
                  color:var(--accent); }
  main { margin:0 20px 20px; background:#fff;
         border:1px solid var(--line); border-radius:0 8px 8px 8px;
         overflow:auto; }
  table { border-collapse:collapse; width:100%; }
  th,td { text-align:left; padding:6px 12px; white-space:nowrap;
          border-bottom:1px solid var(--line); font-size:13px; }
  th { position:sticky; top:0; background:#fff; color:var(--muted);
       font-weight:600; }
  td.num { font-variant-numeric:tabular-nums; }
  .ok { color:var(--ok); } .bad { color:var(--bad); }
  .empty { padding:24px; color:var(--muted); }
</style></head>
<body>
<header><h1>ray_tpu</h1>
  <span class="sub" id="session"></span>
  <span class="sub" id="updated"></span>
  <span class="sub" style="margin-left:auto">
    <a href="/metrics">metrics</a> &middot;
    <a href="/api/timeline">timeline</a></span></header>
<div class="tiles" id="tiles"></div>
<nav id="tabs"></nav>
<main id="table"></main>
<script>
const TABS = {
  nodes: ["node_id","alive","num_workers","resources_total",
          "resources_available","labels"],
  workers: ["worker_id","node_id","pid","state","actor_id"],
  actors: ["actor_id","class_name","state","name","node_id","pid"],
  tasks: ["task_id","name","state","worker_id"],
  objects: ["object_id","loc","size","refcount","state"],
  placement_groups: ["pg_id","name","strategy","state","bundles",
                     "assignment"],
};
let tab = "nodes";
const esc = s => String(s).replace(/[&<>"']/g, c => ({
  "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;",
}[c]));
const fmt = v => {
  // every API value is attacker-influencable (actor names, labels,
  // error strings) — escape BEFORE any innerHTML interpolation
  if (v === null || v === undefined) return "";
  if (typeof v === "boolean")
    return `<span class="${v ? "ok" : "bad"}">${v}</span>`;
  if (typeof v === "object") return esc(JSON.stringify(v));
  if (typeof v === "string" && /^(ALIVE|READY|ok|idle|FINISHED)$/.test(v))
    return `<span class="ok">${v}</span>`;
  if (typeof v === "string" && /^(DEAD|FAILED|dead|ERROR)$/.test(v))
    return `<span class="bad">${v}</span>`;
  return esc(String(v));
};
function renderTabs() {
  document.getElementById("tabs").innerHTML = Object.keys(TABS).map(t =>
    `<button class="${t===tab?"on":""}"
       onclick="tab='${t}';renderTabs();refresh()">${t}</button>`).join("");
}
async function refresh() {
  try {
    const s = await (await fetch("/api/cluster_summary")).json();
    const count = x => (x && typeof x === "object")
      ? Object.values(x).reduce((a, b) => a + (+b || 0), 0) : (x ?? 0);
    const tiles = [
      ["nodes", count(s.nodes)], ["actors", count(s.actors)],
      ["tasks", count(s.tasks)], ["objects", s.objects.count],
      ["object bytes", (s.objects.total_bytes/1048576).toFixed(1)+" MB"],
      ["CPU avail", (s.resources_available.CPU??0) + " / " +
                    (s.resources_total.CPU??0)],
    ];
    if ((s.resources_total.TPU??0) > 0)
      tiles.push(["TPU avail", (s.resources_available.TPU??0) + " / " +
                               s.resources_total.TPU]);
    document.getElementById("tiles").innerHTML = tiles.map(([l,v]) =>
      `<div class="tile"><div class="v">${v}</div>
       <div class="l">${l}</div></div>`).join("");
    document.getElementById("session").textContent = s.session || "";
    const rows = await (await fetch("/api/" + tab)).json();
    const cols = TABS[tab];
    document.getElementById("table").innerHTML = rows.length ?
      `<table><thead><tr>${cols.map(c=>`<th>${c}</th>`).join("")}</tr>
       </thead><tbody>${rows.map(r =>
         `<tr>${cols.map(c => `<td class="${typeof r[c]==="number"?
           "num":""}">${fmt(r[c])}</td>`).join("")}</tr>`).join("")}
       </tbody></table>`
      : `<div class="empty">no ${tab}</div>`;
    document.getElementById("updated").textContent =
      "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    document.getElementById("updated").textContent = "refresh failed: " + e;
  }
}
renderTabs(); refresh(); setInterval(refresh, 2000);
</script></body></html>
"""
