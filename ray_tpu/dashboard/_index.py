"""Single-file dashboard UI (no build step, no external deps).

Reference: ``python/ray/dashboard/client/`` is a React/TypeScript app; this
build ships the same information surface as one static page of vanilla JS
polling the REST API — cluster summary tiles plus tabbed live tables for
nodes, workers, actors, tasks, objects, and placement groups.
"""

INDEX_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
  :root { --fg:#1a1a1a; --muted:#6b6b6b; --line:#e3e3e3; --bg:#fafafa;
          --accent:#2563eb; --ok:#15803d; --bad:#b91c1c; }
  * { box-sizing:border-box; }
  body { margin:0; font:14px/1.45 system-ui,-apple-system,sans-serif;
         color:var(--fg); background:var(--bg); }
  header { padding:14px 20px; background:#fff;
           border-bottom:1px solid var(--line); display:flex;
           align-items:baseline; gap:14px; }
  header h1 { font-size:16px; margin:0; }
  header .sub { color:var(--muted); font-size:12px; }
  .tiles { display:flex; flex-wrap:wrap; gap:12px; padding:16px 20px; }
  .tile { background:#fff; border:1px solid var(--line); border-radius:8px;
          padding:10px 16px; min-width:130px; }
  .tile .v { font-size:22px; font-weight:600; }
  .tile .l { color:var(--muted); font-size:12px; }
  .tile svg.spark { display:block; margin-top:4px; }
  .tile svg.spark polyline { fill:none; stroke:var(--accent);
                             stroke-width:1.5; }
  nav { display:flex; gap:2px; padding:0 20px; }
  nav button { border:1px solid var(--line); border-bottom:none;
               background:#f1f1f1; padding:7px 14px; cursor:pointer;
               border-radius:6px 6px 0 0; font:inherit; }
  nav button.on { background:#fff; font-weight:600;
                  color:var(--accent); }
  main { margin:0 20px 20px; background:#fff;
         border:1px solid var(--line); border-radius:0 8px 8px 8px;
         overflow:auto; }
  table { border-collapse:collapse; width:100%; }
  th,td { text-align:left; padding:6px 12px; white-space:nowrap;
          border-bottom:1px solid var(--line); font-size:13px; }
  th { position:sticky; top:0; background:#fff; color:var(--muted);
       font-weight:600; }
  td.num { font-variant-numeric:tabular-nums; }
  .ok { color:var(--ok); } .bad { color:var(--bad); }
  .empty { padding:24px; color:var(--muted); }
</style></head>
<body>
<header><h1>ray_tpu</h1>
  <span class="sub" id="session"></span>
  <span class="sub" id="updated"></span>
  <span class="sub" style="margin-left:auto">
    <a href="/metrics">metrics</a> &middot;
    <a href="/api/timeline">timeline</a></span></header>
<div class="tiles" id="tiles"></div>
<nav id="tabs"></nav>
<main id="table"></main>
<script>
const TABS = {
  nodes: ["node_id","alive","num_workers","resources_total",
          "resources_available","labels"],
  workers: ["worker_id","node_id","pid","state","actor_id"],
  actors: ["actor_id","class_name","state","name","node_id","pid"],
  tasks: ["task_id","name","state","worker_id"],
  objects: ["object_id","loc","size","refcount","state"],
  placement_groups: ["pg_id","name","strategy","state","bundles",
                     "assignment"],
};
let tab = "nodes";
// header sparklines: tile label -> TSDB expression served by
// /metrics/history (the head keeps the history; one GET per tile)
const SPARKS = {
  "tasks/s": "sum(rate(rtpu_tasks_total[60s]))",
  "serve req/s": "sum(rate(rtpu_serve_requests_total[60s]))",
  "train p50, slowest rank (s)":
    "max(quantile_over_time(0.5, rtpu_train_step_seconds[10m]))",
};
let sparkData = {};   // label -> [[ts, v], ...]
const esc = s => String(s).replace(/[&<>"']/g, c => ({
  "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;",
}[c]));
function sparkline(points) {
  // inline SVG polyline over the last window; flat/empty history
  // renders an empty strip (no misleading axis)
  if (!points || points.length < 2) return "";
  const vs = points.map(p => p[1]);
  const [w, h] = [96, 22];
  const lo = Math.min(...vs), hi = Math.max(...vs);
  const span = (hi - lo) || 1;
  const pts = points.map((p, i) =>
    `${(i / (points.length - 1) * w).toFixed(1)},` +
    `${(h - 2 - (p[1] - lo) / span * (h - 4)).toFixed(1)}`).join(" ");
  return `<svg class="spark" width="${w}" height="${h}"
    viewBox="0 0 ${w} ${h}"><polyline points="${pts}"/></svg>`;
}
async function refreshSparks() {
  for (const [label, expr] of Object.entries(SPARKS)) {
    try {
      const r = await (await fetch("/metrics/history?series=" +
        encodeURIComponent(expr) + "&window=600&step=15")).json();
      const rows = r.results || [];
      sparkData[label] = rows.length ? rows[0].points : [];
    } catch (e) { /* head TSDB disabled: tiles stay sparkline-free */ }
  }
}
const fmt = v => {
  // every API value is attacker-influencable (actor names, labels,
  // error strings) — escape BEFORE any innerHTML interpolation
  if (v === null || v === undefined) return "";
  if (typeof v === "boolean")
    return `<span class="${v ? "ok" : "bad"}">${v}</span>`;
  if (typeof v === "object") return esc(JSON.stringify(v));
  if (typeof v === "string" && /^(ALIVE|READY|ok|idle|FINISHED)$/.test(v))
    return `<span class="ok">${v}</span>`;
  if (typeof v === "string" && /^(DEAD|FAILED|dead|ERROR)$/.test(v))
    return `<span class="bad">${v}</span>`;
  return esc(String(v));
};
function renderTabs() {
  document.getElementById("tabs").innerHTML = Object.keys(TABS).map(t =>
    `<button class="${t===tab?"on":""}"
       onclick="tab='${t}';renderTabs();refresh()">${t}</button>`).join("");
}
async function refresh() {
  try {
    const s = await (await fetch("/api/cluster_summary")).json();
    const count = x => (x && typeof x === "object")
      ? Object.values(x).reduce((a, b) => a + (+b || 0), 0) : (x ?? 0);
    const spark = l => sparkline(sparkData[l]);
    const tiles = [
      ["nodes", count(s.nodes)], ["actors", count(s.actors)],
      ["tasks", count(s.tasks)], ["objects", s.objects.count],
      ["object bytes", (s.objects.total_bytes/1048576).toFixed(1)+" MB"],
      ["CPU avail", (s.resources_available.CPU??0) + " / " +
                    (s.resources_total.CPU??0)],
    ];
    // history-backed tiles: shown once the head TSDB has data for them
    for (const label of Object.keys(SPARKS)) {
      const pts = sparkData[label] || [];
      if (pts.length) tiles.push([label, pts[pts.length-1][1].toFixed(1)]);
    }
    if ((s.resources_total.TPU??0) > 0)
      tiles.push(["TPU avail", (s.resources_available.TPU??0) + " / " +
                               s.resources_total.TPU]);
    document.getElementById("tiles").innerHTML = tiles.map(([l,v]) =>
      `<div class="tile"><div class="v">${v}</div>
       <div class="l">${l}</div>${spark(l)}</div>`).join("");
    document.getElementById("session").textContent = s.session || "";
    const rows = await (await fetch("/api/" + tab)).json();
    const cols = TABS[tab];
    document.getElementById("table").innerHTML = rows.length ?
      `<table><thead><tr>${cols.map(c=>`<th>${c}</th>`).join("")}</tr>
       </thead><tbody>${rows.map(r =>
         `<tr>${cols.map(c => `<td class="${typeof r[c]==="number"?
           "num":""}">${fmt(r[c])}</td>`).join("")}</tr>`).join("")}
       </tbody></table>`
      : `<div class="empty">no ${tab}</div>`;
    document.getElementById("updated").textContent =
      "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    document.getElementById("updated").textContent = "refresh failed: " + e;
  }
}
renderTabs(); refreshSparks().then(refresh);
setInterval(refresh, 2000); setInterval(refreshSparks, 15000);
</script></body></html>
"""
