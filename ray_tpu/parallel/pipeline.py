"""Pipeline parallelism: single-program microbatch pipelining over the
``pipeline`` mesh axis.

Reference contrast (SURVEY.md §2.4): Ray core has no pipeline parallelism —
its ecosystem reaches PP by placement-grouping actors around DeepSpeed/Alpa,
shipping activations through the object store between stage processes.  The
TPU-native inversion: all stages live in ONE compiled SPMD program; stage
s→s+1 activation transfer is a ``ppermute`` over the ``pipeline`` mesh axis
(ICI neighbor hop), and the fill/drain schedule is a ``lax.scan`` — XLA
overlaps the permute with the next microbatch's compute.

Schedule: GPipe-style fill/drain over ``num_microbatches`` microbatches and
S stages: tick t runs microbatch ``t - s`` on stage ``s``; bubble fraction is
``(S-1)/(num_microbatches + S - 1)``, so pick num_microbatches >= 4*S.
Gradients flow through the schedule automatically — ``ppermute`` and
``lax.scan`` are differentiable, so the same program serves fwd+bwd (the
backward pass is the reversed pipeline XLA derives).

Layout contract: stage parameters are pytrees whose leaves carry a leading
``num_stages`` axis sharded ``P("pipeline", ...)`` (the stacked-layer layout
``models/gpt2.py`` already uses for ``lax.scan`` over blocks — reshaped from
(L, ...) to (S, L/S, ...) by ``stack_stages``).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def stack_stages(params: Any, num_stages: int) -> Any:
    """(L, ...) stacked-layer params → (S, L/S, ...) stage-major layout."""
    def leaf(p):
        L = p.shape[0]
        if L % num_stages:
            raise ValueError(
                f"{L} layers not divisible by {num_stages} pipeline stages")
        return p.reshape(num_stages, L // num_stages, *p.shape[1:])
    return jax.tree_util.tree_map(leaf, params)


def unstack_stages(params: Any) -> Any:
    """Inverse of :func:`stack_stages`."""
    return jax.tree_util.tree_map(
        lambda p: p.reshape(p.shape[0] * p.shape[1], *p.shape[2:]), params)


def split_microbatches(batch: Any, num_microbatches: int) -> Any:
    """(B, ...) → (num_microbatches, B/num_microbatches, ...)."""
    def leaf(x):
        B = x.shape[0]
        if B % num_microbatches:
            raise ValueError(f"batch {B} not divisible by "
                             f"{num_microbatches} microbatches")
        return x.reshape(num_microbatches, B // num_microbatches, *x.shape[1:])
    return jax.tree_util.tree_map(leaf, batch)


def merge_microbatches(y: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda x: x.reshape(x.shape[0] * x.shape[1], *x.shape[2:]), y)


def pipeline_apply(
        stage_fn: Callable[[Any, jax.Array], jax.Array],
        stage_params: Any,
        x_micro: jax.Array,
        *,
        mesh: Mesh,
        axis: str = "pipeline",
        remat: bool = True) -> jax.Array:
    """Run ``stage_fn`` as an S-stage pipeline over microbatched input.

    ``stage_fn(params_for_one_stage, x) -> y`` must preserve the activation
    shape (the transformer-block contract).  ``stage_params`` leaves have
    leading dim S (see :func:`stack_stages`); ``x_micro`` is
    ``(num_microbatches, mb, ...)``.  Returns ``(num_microbatches, mb, ...)``
    outputs (the last stage's results, replicated over the pipeline axis).

    Everything except the ``pipeline`` axis stays in GSPMD-automatic mode, so
    data/tensor/context sharding of the microbatch dims composes with this.
    """
    S = mesh.shape[axis]
    num_micro = x_micro.shape[0]
    if S == 1:
        f = jax.checkpoint(stage_fn) if remat else stage_fn
        squeezed = jax.tree_util.tree_map(lambda p: p[0], stage_params)
        return jax.vmap(lambda xb: f(squeezed, xb))(x_micro)
    if num_micro < S:
        raise ValueError(f"need >= {S} microbatches for {S} stages")

    fwd = [(i, (i + 1) % S) for i in range(S)]
    f = jax.checkpoint(stage_fn) if remat else stage_fn

    # XLA-CPU workaround: the backward pass psums the replicated input's
    # cotangent over the pipeline axis, and bf16 all-reduces crash the CPU
    # backend's ChangeOpDataType pass.  Cast at the boundary on CPU only;
    # TPU keeps bf16 end to end.
    io_dtype = x_micro.dtype
    cast_io = (jax.default_backend() == "cpu" and io_dtype == jnp.bfloat16)
    if cast_io:
        x_micro = x_micro.astype(jnp.float32)

    def per_shard(local_params, x_mb):
        if cast_io:
            x_mb = x_mb.astype(io_dtype)
        # local_params leaves: (1, L/S, ...) — this stage's slice
        p = jax.tree_util.tree_map(lambda q: q[0], local_params)
        stage = jax.lax.axis_index(axis)
        T = num_micro + S - 1
        # emit buffer in f32 under the CPU workaround: all_gather's
        # *transpose* is a reduce-scatter, which must not be bf16 either
        ys0 = jnp.zeros(x_mb.shape,
                        jnp.float32 if cast_io else x_mb.dtype)
        state0 = jnp.zeros_like(x_mb[0])

        def tick(carry, t):
            state, ys = carry
            # stage 0 ingests microbatch t (clamped during drain); others
            # consume the activation ppermute'd from stage s-1 last tick
            inp = jnp.where(stage == 0,
                            x_mb[jnp.minimum(t, num_micro - 1)], state)
            out = f(p, inp)
            nxt = jax.lax.ppermute(out, axis, fwd)
            # last stage emits microbatch t-(S-1) once the pipe is full
            idx = jnp.clip(t - (S - 1), 0, num_micro - 1)
            emit = jnp.logical_and(stage == S - 1, t >= S - 1)
            ys = jnp.where(
                emit,
                jax.lax.dynamic_update_index_in_dim(
                    ys, out.astype(ys.dtype), idx, 0), ys)
            return (nxt, ys), None

        (_, ys), _ = jax.lax.scan(tick, (state0, ys0), jnp.arange(T))
        # replicate the last stage's buffer to every pipeline rank
        # (all_gather + index, not a masked psum: reductions over bf16 hit
        # an XLA-CPU ChangeOpDataType crash when cloning the all-reduce)
        return jax.lax.all_gather(ys, axis)[S - 1]

    pspec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)
    from ray_tpu._private.jax_compat import shard_map
    out = shard_map(
        per_shard, mesh=mesh,
        in_specs=(pspec, P()), out_specs=P(),
        axis_names=frozenset({axis}), check_vma=False,
    )(stage_params, x_micro)
    return out.astype(io_dtype) if cast_io else out


def pick_num_microbatches(batch_size: int, num_stages: int,
                          target_multiple: int = 4) -> int:
    """Largest divisor of batch_size that is <= target_multiple * stages
    (enough microbatches to amortize the fill/drain bubble)."""
    want = max(num_stages, min(batch_size, target_multiple * num_stages))
    for m in range(want, 0, -1):
        if batch_size % m == 0 and m >= num_stages:
            return m
    return 1
