"""Multi-controller (multi-process) SPMD runtime.

Reference: ``python/ray/train/torch/config.py`` (SURVEY.md §3.4) — the
reference's worker-group backend calls ``dist.init_process_group("nccl")``
on every worker so the group becomes one communicator domain.  The
TPU-native analog is **multi-controller JAX**: every worker process calls
``jax.distributed.initialize(coordinator, num_processes, process_id)``,
after which ``jax.devices()`` is the GLOBAL device list and one pjit
program spans all processes — XLA inserts the cross-host collectives
(ICI/DCN on a real pod; gloo on the CPU rig).

This module is the thin, framework-owned wrapper the Train backend and
the dryrun harness share:

- ``initialize()`` — config-safe setup.  On the CPU rig it pins the
  per-process virtual device count (``jax_num_cpu_devices`` wins over any
  inherited ``--xla_force_host_platform_device_count`` flag) and selects
  the gloo cross-process collective implementation; on a real TPU pod
  both knobs are no-ops and the call reduces to the stock
  ``jax.distributed.initialize``.
- ``gather_to_host()`` / ``put_global()`` — checkpoint plumbing: a
  cross-process-sharded pytree is gathered to plain numpy on EVERY
  process (so any rank can write a full checkpoint), and restored by
  re-placing host arrays against global shardings (``jax.device_put``
  has global semantics when every process holds the same host value).

The CPU rig (N processes × ``jax_num_cpu_devices`` each, gloo) stands in
for an N-host TPU slice exactly the way the reference's gloo CI rig
stands in for NCCL.
"""

from __future__ import annotations

from typing import Any, Optional

__all__ = [
    "initialize", "shutdown", "is_distributed", "process_index",
    "process_count", "gather_to_host", "put_global",
]


def initialize(coordinator_address: str, num_processes: int,
               process_id: int, *, local_device_count: Optional[int] = None,
               cpu_collectives: str = "gloo",
               init_timeout_s: Optional[float] = None) -> None:
    """Join this process to a multi-controller JAX program domain.

    Must run before the process's first device query (the backend is
    initialized lazily on first use; config updates after that raise).

    local_device_count: per-process device count on the CPU platform
        (virtual-host rig).  Ignored on real accelerators, where the
        platform defines the local devices.
    cpu_collectives: cross-process collective implementation for the CPU
        platform ("gloo" or "mpi"); ignored elsewhere.
    """
    import os

    import jax

    if num_processes <= 1:
        return
    # Effective platform: the env var when set, else the jax_platforms
    # config.  Empty means "auto" — on a CPU-only host that resolves to
    # cpu, so apply the CPU knobs then too: both are no-ops for a process
    # whose default backend turns out to be a real accelerator
    # (jax_num_cpu_devices only shapes the cpu platform's device list and
    # cpu_collectives only affects cpu cross-process transfers).
    platform = (os.environ.get("JAX_PLATFORMS")
                or getattr(jax.config, "jax_platforms", None)
                or "").split(",")[0]
    if platform in ("cpu", ""):
        if local_device_count:
            try:
                jax.config.update("jax_num_cpu_devices",
                                  int(local_device_count))
            except AttributeError:
                # older jaxlib (≤0.4.x): the only device-count knob is the
                # XLA flag, honored because the backend isn't built yet
                flag = ("--xla_force_host_platform_device_count="
                        f"{int(local_device_count)}")
                kept = [f for f in os.environ.get("XLA_FLAGS", "").split()
                        if not f.startswith(
                            "--xla_force_host_platform_device_count")]
                os.environ["XLA_FLAGS"] = " ".join(kept + [flag])
        if cpu_collectives:
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  cpu_collectives)
            except AttributeError:
                if cpu_collectives == "gloo":
                    try:  # pre-rename spelling of the same knob
                        jax.config.update("jax_cpu_enable_gloo_collectives",
                                          True)
                    except AttributeError:
                        pass
    kw: dict = dict(coordinator_address=coordinator_address,
                    num_processes=num_processes, process_id=process_id)
    if init_timeout_s is not None:
        kw["initialization_timeout"] = int(init_timeout_s)
    jax.distributed.initialize(**kw)


def shutdown() -> None:
    """Leave the program domain (idempotent, best-effort)."""
    try:
        import jax
        jax.distributed.shutdown()
    except Exception:  # noqa: BLE001 - never initialized / already down
        pass


def is_distributed() -> bool:
    import jax
    return jax.process_count() > 1


def process_index() -> int:
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()


def gather_to_host(tree: Any) -> Any:
    """Sharded pytree → numpy pytree of GLOBAL values on every process.

    The multi-controller checkpoint path: ``jax.device_get`` alone
    cannot read non-addressable shards, so each leaf rides a
    ``process_allgather`` (an XLA all-gather across the processes) and
    lands as a full host array everywhere — any rank can then persist a
    complete checkpoint, and a restarted group of a DIFFERENT size can
    still restore it.  Single-process trees pass through via device_get.
    """
    import jax
    import numpy as np

    if not is_distributed():
        return jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x)), tree)
    from jax.experimental import multihost_utils

    return jax.tree_util.tree_map(
        lambda x: np.asarray(multihost_utils.process_allgather(x, tiled=True)),
        tree)


def put_global(tree: Any, shardings: Any) -> Any:
    """Host (numpy) pytree → globally-sharded device arrays.

    Every process must hold the SAME host values (the ``gather_to_host``
    contract); ``jax.device_put`` then transfers only each process's
    addressable shards.
    """
    import jax

    return jax.tree_util.tree_map(
        lambda h, sh: jax.device_put(h, sh), tree, shardings)
