"""TPU-first optimizers: HBM-compact AdamW (low-precision moments).

Reference contrast: the reference framework's train integrations wrap torch
optimizers inside worker actors (reference: ``python/ray/train/torch/``),
with f32 state resident per replica and DDP syncing grads at runtime.  On a
16GB-HBM TPU chip the optimizer state IS the capacity wall: f32 Adam moments
for GPT-2-1.5B are 12.5GB alone, and the optimizer phase of the train step
is HBM-bandwidth-floored (15.1ms of f32 state traffic at the flagship bench
config, benchmarks/results/step_breakdown_r03.md).  Storing moments in bf16
halves both the footprint and the traffic; the update MATH stays f32 — the
storage dtype only bounds what survives between steps.

Numerics: bf16 has f32's exponent range and ~3 significant digits.  EMA
increments are a fixed fraction of the running value ((1-b1)=10%,
(1-b2)=2-5% per step), far above bf16's ~0.4% ulp, so the moment EMAs track.
This is the same regime as widely-deployed 8-bit Adam — and strictly more
conservative.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp
import optax


def scale_by_adam_compact(
        b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
        mu_dtype: Any = jnp.bfloat16,
        nu_dtype: Any = jnp.bfloat16) -> optax.GradientTransformation:
    """``optax.scale_by_adam`` with BOTH moments stored in a compact dtype.

    optax's own ``mu_dtype`` covers only the first moment; the second moment
    (same size) stays f32 there.  Update math is f32 throughout: moments are
    upcast, blended with the f32-cast gradient, used for the update, and
    only the carried state is downcast.
    """
    mu_dtype = jnp.dtype(mu_dtype)
    nu_dtype = jnp.dtype(nu_dtype)

    def init(params):
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, dtype=mu_dtype), params),
            nu=jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, dtype=nu_dtype), params))

    def update(updates, state, params=None):
        del params
        count = state.count + 1
        c = count.astype(jnp.float32)
        bc1 = 1.0 - jnp.power(jnp.float32(b1), c)
        bc2 = 1.0 - jnp.power(jnp.float32(b2), c)

        def blend(g, m, v):
            g32 = g.astype(jnp.float32)
            m32 = b1 * m.astype(jnp.float32) + (1.0 - b1) * g32
            v32 = b2 * v.astype(jnp.float32) + (1.0 - b2) * g32 * g32
            u = (m32 / bc1) / (jnp.sqrt(v32 / bc2) + eps)
            # the update leaves f32 — downstream transforms (weight decay,
            # lr scale) and the apply-add run in f32; only carried state
            # is compact
            return u, m32.astype(mu_dtype), v32.astype(nu_dtype)

        out = jax.tree_util.tree_map(blend, updates, state.mu, state.nu)
        new_updates, new_mu, new_nu = jax.tree_util.tree_transpose(
            jax.tree_util.tree_structure(updates),
            jax.tree_util.tree_structure((0, 0, 0)), out)
        return new_updates, optax.ScaleByAdamState(
            count=count, mu=new_mu, nu=new_nu)

    return optax.GradientTransformation(init, update)


def adamw_compact(
        learning_rate: Union[float, Callable[[jax.Array], jax.Array]],
        *, b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
        weight_decay: float = 0.0, clip: Optional[float] = None,
        mu_dtype: Any = jnp.bfloat16,
        nu_dtype: Any = jnp.bfloat16) -> optax.GradientTransformation:
    """AdamW with compact moment storage (drop-in for ``optax.adamw``)."""
    parts = []
    if clip is not None:
        parts.append(optax.clip_by_global_norm(clip))
    parts += [
        scale_by_adam_compact(b1=b1, b2=b2, eps=eps,
                              mu_dtype=mu_dtype, nu_dtype=nu_dtype),
        optax.add_decayed_weights(weight_decay),
        optax.scale_by_learning_rate(learning_rate),
    ]
    return optax.chain(*parts)


def apply_updates_mixed(params: Any, updates: Any) -> Any:
    """``optax.apply_updates`` with the ADD in f32.

    With bf16 master params (the only way GPT-2-XL + moments fit 16GB on one
    chip) ``p + u`` in bf16 loses any update below ~0.4% of the weight —
    i.e. almost all of them.  Upcasting for the add keeps the common
    magnitude-cancellation error one rounding, matching how TPU mixed-
    precision recipes apply weight updates.  For f32 params this is
    bit-identical to ``optax.apply_updates``.
    """
    def add(p, u):
        if u is None:
            return p
        return (p.astype(jnp.float32) + u.astype(jnp.float32)).astype(p.dtype)

    return jax.tree_util.tree_map(add, params, updates,
                                  is_leaf=lambda x: x is None)
