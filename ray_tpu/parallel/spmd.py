"""SPMD train-program assembly: mesh + sharding rules + optax → one jit.

Reference contrast: Ray Train assembles torch DDP process groups around the
user's loop (reference: ``python/ray/train/_internal/backend_executor.py``,
``train/torch/config.py``); gradients sync via NCCL calls at runtime.  Here
the whole training step — forward, backward, gradient "allreduce", optimizer
— is ONE compiled XLA program over the mesh; data/tensor/context parallel
collectives are inserted by GSPMD and ride ICI (SURVEY.md §5.8 item 3).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel import mesh as mesh_lib
from ray_tpu.parallel.mesh import MeshConfig, Rules, TRANSFORMER_RULES


@dataclass
class TrainState:
    """Minimal train state pytree (flax-free so sharding rules stay simple)."""
    step: jax.Array
    params: Any
    opt_state: Any

    def tree_flatten(self):
        return (self.step, self.params, self.opt_state), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.step, s.params, s.opt_state), None),
    lambda _, c: TrainState(*c))


def default_optimizer(lr: float = 3e-4, weight_decay: float = 0.01,
                      warmup: int = 100, total_steps: int = 10_000,
                      b2: float = 0.95, clip: float = 1.0,
                      moments_dtype: Any = None) -> optax.GradientTransformation:
    """AdamW with warmup-cosine schedule.  ``moments_dtype`` (e.g.
    ``jnp.bfloat16``) stores BOTH Adam moments compactly — halves the
    optimizer's HBM footprint and its bandwidth-floored step phase
    (parallel/optim.py); None keeps optax's f32 state."""
    sched = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup, max(total_steps, warmup + 1), end_value=lr * 0.1)
    if moments_dtype is not None:
        from ray_tpu.parallel.optim import adamw_compact
        return adamw_compact(sched, b1=0.9, b2=b2,
                             weight_decay=weight_decay, clip=clip,
                             mu_dtype=moments_dtype, nu_dtype=moments_dtype)
    return optax.chain(optax.clip_by_global_norm(clip),
                       optax.adamw(sched, b1=0.9, b2=b2,
                                   weight_decay=weight_decay))


def state_specs(state: TrainState, rules: Rules) -> TrainState:
    """PartitionSpecs for a TrainState: params by rules; opt-state moments
    mirror their param's spec; scalars replicated."""
    pspecs = mesh_lib.param_specs(state.params, rules)

    def opt_leaf_spec(leaf):
        # Adam moments have the same shape as params; match by shape lookup.
        shape = getattr(leaf, "shape", ())
        spec = shape_index.get(tuple(shape))
        return spec if spec is not None else P()

    shape_index: Dict[tuple, P] = {}
    flat_p = jax.tree_util.tree_leaves_with_path(state.params)
    flat_s = jax.tree_util.tree_leaves(pspecs)
    for (path, leaf), spec in zip(flat_p, flat_s):
        shape_index.setdefault(tuple(leaf.shape), spec)

    ospecs = jax.tree_util.tree_map(opt_leaf_spec, state.opt_state)
    return TrainState(step=P(), params=pspecs, opt_state=ospecs)


@dataclass
class SpmdProgram:
    """A compiled distributed training step and its placement metadata."""
    mesh: Mesh
    mesh_config: MeshConfig
    init_fn: Callable[[jax.Array], TrainState]     # sharded init
    step_fn: Callable[[TrainState, Any], Tuple[TrainState, Dict[str, jax.Array]]]
    state_shardings: Any
    batch_sharding: Any


def build_train_program(
        *, loss_fn: Callable[[Any, Any], jax.Array],
        init_params_fn: Callable[[jax.Array], Any],
        optimizer: Optional[optax.GradientTransformation] = None,
        mesh_config: Optional[MeshConfig] = None,
        mesh: Optional[Mesh] = None,
        rules: Rules = TRANSFORMER_RULES,
        batch_rank: int = 2,
        donate_state: bool = True,
        donate_batch: bool = False,
        accum_steps: int = 1,
        accum_dtype: Any = None) -> SpmdProgram:
    """Assemble the one-jit distributed train step.

    ``loss_fn(params, batch) -> scalar``; GSPMD derives every collective from
    the shardings — there is no explicit allreduce anywhere.

    ``accum_steps > 1`` runs microbatch gradient accumulation INSIDE the one
    jit: the global batch is split on its leading dim into ``accum_steps``
    microbatches and a ``lax.scan`` accumulates grads before one optimizer
    update.  Activation memory scales with the MICRObatch, so batch sizes
    that OOM outright fit (the r3 sweep's HBM-OOM rows; VERDICT r3 #1).
    ``accum_dtype`` sets the accumulator dtype (default: the grad dtype —
    pass ``jnp.bfloat16`` to halve accumulator HBM when params are f32).
    """
    optimizer = optimizer or default_optimizer()
    if mesh is None:
        mesh_config = (mesh_config or MeshConfig()).resolved(
            len(jax.devices()))
        mesh = mesh_lib.build_mesh(mesh_config)
    else:
        mesh_config = (mesh_config or MeshConfig()).resolved(mesh.size)

    # Shapes-only init to derive shardings without materializing params.
    abstract_params = jax.eval_shape(init_params_fn, jax.random.key(0))
    abstract_state = TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        params=abstract_params,
        opt_state=jax.eval_shape(optimizer.init, abstract_params))
    specs = state_specs(
        TrainState(step=None, params=abstract_params,
                   opt_state=abstract_state.opt_state), rules)
    state_sh = TrainState(
        step=NamedSharding(mesh, P()),
        params=mesh_lib.named_shardings(mesh, specs.params),
        opt_state=mesh_lib.named_shardings(mesh, specs.opt_state))
    batch_sh = NamedSharding(mesh, mesh_lib.batch_spec(mesh_config, batch_rank))

    def _init(rng: jax.Array) -> TrainState:
        params = init_params_fn(rng)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=optimizer.init(params))

    init_fn = jax.jit(_init, out_shardings=state_sh)

    def _grads(params: Any, batch: Any):
        # Runs at trace time: model code (e.g. ring attention) can pick up
        # the program mesh via mesh_lib.get_ambient_mesh() to nest shard_map.
        with mesh_lib.ambient_mesh(mesh):
            return jax.value_and_grad(loss_fn)(params, batch)

    def _grads_accum(params: Any, batch: Any):
        # Microbatch split on the leading (batch) dim.  The reshape keeps
        # the data-parallel sharding on the microbatch dim (constraint
        # below) so each scan iteration is the same SPMD program at 1/A
        # batch; the accumulator is carried state, the activations die with
        # each iteration.
        A = accum_steps

        def split(x):
            if getattr(x, "ndim", 0) == 0 or x.shape[0] % A:
                raise ValueError(
                    f"batch dim {getattr(x, 'shape', ())} not divisible "
                    f"by accum_steps={A}")
            mb = x.reshape(A, x.shape[0] // A, *x.shape[1:])
            spec = mesh_lib.batch_spec(mesh_config, mb.ndim - 1)
            return jax.lax.with_sharding_constraint(
                mb, NamedSharding(mesh, P(None, *spec)))

        mbs = jax.tree_util.tree_map(split, batch)
        acc0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, accum_dtype or p.dtype), params)

        def body(carry, mb):
            loss_acc, g_acc = carry
            loss, grads = _grads(params, mb)
            g_acc = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(a.dtype), g_acc, grads)
            return (loss_acc + loss, g_acc), None

        (loss_sum, acc), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), acc0), mbs)
        inv = jnp.float32(1.0 / A)
        grads = jax.tree_util.tree_map(
            lambda a, p: (a.astype(jnp.float32) * inv).astype(p.dtype),
            acc, params)
        return loss_sum * inv, grads

    def _step(state: TrainState, batch: Any):
        if accum_steps > 1:
            loss, grads = _grads_accum(state.params, batch)
        else:
            loss, grads = _grads(state.params, batch)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        from ray_tpu.parallel.optim import apply_updates_mixed
        params = apply_updates_mixed(state.params, updates)
        new = TrainState(step=state.step + 1, params=params,
                         opt_state=opt_state)
        gnorm = optax.global_norm(grads)
        return new, {"loss": loss, "grad_norm": gnorm,
                     "step": new.step.astype(jnp.float32)}

    # Donation: the WHOLE TrainState — params AND both Adam moments —
    # aliases its output buffers (in/out shardings match leaf-for-leaf,
    # so XLA reuses every buffer in place; the optimizer phase is
    # HBM-bandwidth-floored and an un-donated moment tree would double
    # its traffic AND its footprint).  ``donate_batch`` additionally
    # donates the input batch for callers that feed a fresh batch every
    # step (streaming ingest, train_bench) — never for callers that
    # re-feed one batch (bench.py's steady-state loop).
    donate: Tuple[int, ...] = (0,) if donate_state else ()
    if donate_batch:
        donate = donate + (1,)
    step_fn = jax.jit(
        _step,
        in_shardings=(state_sh, batch_sh),
        out_shardings=(state_sh, NamedSharding(mesh, P())),
        donate_argnums=donate)

    # XLA watchdog step region (DESIGN.md §4q): one program for this
    # SpmdProgram's life (COMPILE_BUDGETS["train.step"]), zero host
    # transfers inside the dispatch.  Callers' device_get of the
    # metrics dict happens on THEIR side of the region and stays legal.
    from ray_tpu._private.xla_watchdog import compile_budget
    step_budget = compile_budget("train.step")

    def guarded_step(state: TrainState, batch: Any):
        with step_budget:
            return step_fn(state, batch)

    return SpmdProgram(mesh=mesh, mesh_config=mesh_config, init_fn=init_fn,
                       step_fn=guarded_step, state_shardings=state_sh,
                       batch_sharding=batch_sh)


def shard_batch(program: SpmdProgram, batch: Any) -> Any:
    """Host batch (numpy pytree) → device arrays with the batch sharding."""
    def put(x):
        rank = getattr(x, "ndim", 0)
        sh = NamedSharding(program.mesh,
                           mesh_lib.batch_spec(program.mesh_config, rank))
        return jax.device_put(x, sh)
    return jax.tree_util.tree_map(put, batch)
