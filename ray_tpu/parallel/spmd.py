"""SPMD train-program assembly: mesh + sharding rules + optax → one jit.

Reference contrast: Ray Train assembles torch DDP process groups around the
user's loop (reference: ``python/ray/train/_internal/backend_executor.py``,
``train/torch/config.py``); gradients sync via NCCL calls at runtime.  Here
the whole training step — forward, backward, gradient "allreduce", optimizer
— is ONE compiled XLA program over the mesh; data/tensor/context parallel
collectives are inserted by GSPMD and ride ICI (SURVEY.md §5.8 item 3).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.parallel import mesh as mesh_lib
from ray_tpu.parallel.mesh import MeshConfig, Rules, TRANSFORMER_RULES


@dataclass
class TrainState:
    """Minimal train state pytree (flax-free so sharding rules stay simple)."""
    step: jax.Array
    params: Any
    opt_state: Any

    def tree_flatten(self):
        return (self.step, self.params, self.opt_state), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.step, s.params, s.opt_state), None),
    lambda _, c: TrainState(*c))


def default_optimizer(lr: float = 3e-4, weight_decay: float = 0.01,
                      warmup: int = 100, total_steps: int = 10_000,
                      b2: float = 0.95, clip: float = 1.0) -> optax.GradientTransformation:
    sched = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup, max(total_steps, warmup + 1), end_value=lr * 0.1)
    return optax.chain(optax.clip_by_global_norm(clip),
                       optax.adamw(sched, b1=0.9, b2=b2,
                                   weight_decay=weight_decay))


def state_specs(state: TrainState, rules: Rules) -> TrainState:
    """PartitionSpecs for a TrainState: params by rules; opt-state moments
    mirror their param's spec; scalars replicated."""
    pspecs = mesh_lib.param_specs(state.params, rules)

    def opt_leaf_spec(leaf):
        # Adam moments have the same shape as params; match by shape lookup.
        shape = getattr(leaf, "shape", ())
        spec = shape_index.get(tuple(shape))
        return spec if spec is not None else P()

    shape_index: Dict[tuple, P] = {}
    flat_p = jax.tree_util.tree_leaves_with_path(state.params)
    flat_s = jax.tree_util.tree_leaves(pspecs)
    for (path, leaf), spec in zip(flat_p, flat_s):
        shape_index.setdefault(tuple(leaf.shape), spec)

    ospecs = jax.tree_util.tree_map(opt_leaf_spec, state.opt_state)
    return TrainState(step=P(), params=pspecs, opt_state=ospecs)


@dataclass
class SpmdProgram:
    """A compiled distributed training step and its placement metadata."""
    mesh: Mesh
    mesh_config: MeshConfig
    init_fn: Callable[[jax.Array], TrainState]     # sharded init
    step_fn: Callable[[TrainState, Any], Tuple[TrainState, Dict[str, jax.Array]]]
    state_shardings: Any
    batch_sharding: Any


def build_train_program(
        *, loss_fn: Callable[[Any, Any], jax.Array],
        init_params_fn: Callable[[jax.Array], Any],
        optimizer: Optional[optax.GradientTransformation] = None,
        mesh_config: Optional[MeshConfig] = None,
        mesh: Optional[Mesh] = None,
        rules: Rules = TRANSFORMER_RULES,
        batch_rank: int = 2,
        donate_state: bool = True) -> SpmdProgram:
    """Assemble the one-jit distributed train step.

    ``loss_fn(params, batch) -> scalar``; GSPMD derives every collective from
    the shardings — there is no explicit allreduce anywhere.
    """
    optimizer = optimizer or default_optimizer()
    if mesh is None:
        mesh_config = (mesh_config or MeshConfig()).resolved(
            len(jax.devices()))
        mesh = mesh_lib.build_mesh(mesh_config)
    else:
        mesh_config = (mesh_config or MeshConfig()).resolved(mesh.size)

    # Shapes-only init to derive shardings without materializing params.
    abstract_params = jax.eval_shape(init_params_fn, jax.random.key(0))
    abstract_state = TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        params=abstract_params,
        opt_state=jax.eval_shape(optimizer.init, abstract_params))
    specs = state_specs(
        TrainState(step=None, params=abstract_params,
                   opt_state=abstract_state.opt_state), rules)
    state_sh = TrainState(
        step=NamedSharding(mesh, P()),
        params=mesh_lib.named_shardings(mesh, specs.params),
        opt_state=mesh_lib.named_shardings(mesh, specs.opt_state))
    batch_sh = NamedSharding(mesh, mesh_lib.batch_spec(mesh_config, batch_rank))

    def _init(rng: jax.Array) -> TrainState:
        params = init_params_fn(rng)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=optimizer.init(params))

    init_fn = jax.jit(_init, out_shardings=state_sh)

    def _step(state: TrainState, batch: Any):
        # Runs at trace time: model code (e.g. ring attention) can pick up
        # the program mesh via mesh_lib.get_ambient_mesh() to nest shard_map.
        with mesh_lib.ambient_mesh(mesh):
            loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        new = TrainState(step=state.step + 1, params=params,
                         opt_state=opt_state)
        gnorm = optax.global_norm(grads)
        return new, {"loss": loss, "grad_norm": gnorm,
                     "step": new.step.astype(jnp.float32)}

    step_fn = jax.jit(
        _step,
        in_shardings=(state_sh, batch_sh),
        out_shardings=(state_sh, NamedSharding(mesh, P())),
        donate_argnums=(0,) if donate_state else ())

    return SpmdProgram(mesh=mesh, mesh_config=mesh_config, init_fn=init_fn,
                       step_fn=step_fn, state_shardings=state_sh,
                       batch_sharding=batch_sh)


def shard_batch(program: SpmdProgram, batch: Any) -> Any:
    """Host batch (numpy pytree) → device arrays with the batch sharding."""
    def put(x):
        rank = getattr(x, "ndim", 0)
        sh = NamedSharding(program.mesh,
                           mesh_lib.batch_spec(program.mesh_config, rank))
        return jax.device_put(x, sh)
    return jax.tree_util.tree_map(put, batch)
