"""Device-mesh assembly and sharding rules (the GSPMD heart of the framework).

The reference (Ray) has no notion of a device mesh: its parallelism is
"N actors + NCCL process groups" (reference: ``python/ray/util/collective``,
``python/ray/train/_internal/backend_executor.py``).  The TPU-native design
inverts this (SURVEY.md §7.1): parallelism *inside* a worker group is a single
compiled pjit/shard_map program over a ``jax.sharding.Mesh``, and the
framework's job is assembling that mesh and placing named shardings.

Canonical logical mesh axes (superset of every parallelism the reference's
ecosystem reaches via third-party libs, SURVEY.md §2.4):

======== ============================================ =====================
axis     shards                                       collective traffic
======== ============================================ =====================
data     batch (pure DP)                              grad allreduce
fsdp     batch + parameter shards (ZeRO-3 style)      allgather/reducescatter
pipeline transformer layer blocks (PP stages)         ppermute activations
context  sequence dimension (CP, ring attention)      ppermute KV blocks
seq      sequence dimension BETWEEN blocks (SP:       allgather/reducescatter
         norms/residuals/dropout shard over tokens)   fused into matmul rings
tensor   hidden/heads (Megatron TP)                   allreduce activations
expert   MoE experts (EP)                             all-to-all tokens
======== ============================================ =====================

``seq`` vs ``context``: ``context`` shards the sequence *through*
attention (ring/Ulysses rotate KV so no device ever sees full T);
``seq`` shards the sequence in the regions *between* attention and MLP
(Korthikanti et al. 2022) — layer norms, residual adds and the
optimizer-visible activations live on T/seq tokens per device, and the
boundary all-gather/reduce-scatter legs are folded into the adjacent
projection matmuls by ``ray_tpu.ops.collective_matmul`` so they hide
behind partial-product compute instead of serializing the step.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, replace
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "fsdp", "pipeline", "context", "seq", "tensor", "expert")


@dataclass(frozen=True)
class MeshConfig:
    """Logical parallelism layout; -1 on ``data`` absorbs remaining devices."""

    data: int = -1
    fsdp: int = 1
    pipeline: int = 1
    context: int = 1
    seq: int = 1
    tensor: int = 1
    expert: int = 1

    def resolved(self, n_devices: int) -> "MeshConfig":
        sizes = self.as_dict()
        fixed = [v for v in sizes.values() if v != -1]
        free = [k for k, v in sizes.items() if v == -1]
        if len(free) > 1:
            raise ValueError("at most one mesh axis may be -1")
        prod = math.prod(fixed)
        if free:
            if n_devices % prod:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {sizes}")
            sizes[free[0]] = n_devices // prod
        elif prod != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {prod} devices, have {n_devices}")
        return MeshConfig(**sizes)

    def as_dict(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXES}

    @property
    def num_devices(self) -> int:
        return math.prod(v for v in self.as_dict().values())

    def batch_axes(self) -> Tuple[str, ...]:
        """Mesh axes the global batch is sharded over."""
        return tuple(a for a in ("data", "fsdp") if self.as_dict()[a] != 1) \
            or ("data",)


# --------------------------------------------------------------------------
# Ambient mesh: lets model code reach the program mesh at TRACE time (e.g.
# ops/ring_attention wrapping shard_map inside a pjit region).  Set by
# ray_tpu.parallel.spmd around step tracing; plain contextvar — no jax
# global state involved.
# --------------------------------------------------------------------------
import contextlib
import contextvars

_AMBIENT_MESH: contextvars.ContextVar[Optional[Mesh]] = \
    contextvars.ContextVar("ray_tpu_ambient_mesh", default=None)


def get_ambient_mesh() -> Optional[Mesh]:
    return _AMBIENT_MESH.get()


@contextlib.contextmanager
def ambient_mesh(mesh: Mesh):
    token = _AMBIENT_MESH.set(mesh)
    try:
        yield mesh
    finally:
        _AMBIENT_MESH.reset(token)


def build_mesh(config: MeshConfig,
               devices: Optional[Sequence[Any]] = None) -> Mesh:
    """Assemble a ``jax.sharding.Mesh`` with the canonical axis names.

    Axis order puts ``pipeline``/``data`` outermost (DCN-friendly) and
    ``tensor`` innermost (highest-traffic → shortest ICI hops), matching how
    ``jax.experimental.mesh_utils`` assigns physical adjacency.
    """
    devices = list(devices if devices is not None else jax.devices())
    cfg = config.resolved(len(devices))
    shape = tuple(cfg.as_dict()[a] for a in AXES)
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=np.asarray(devices))
    except Exception:  # noqa: BLE001 - heterogeneous/virtual devices
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def single_device_mesh(device: Optional[Any] = None) -> Mesh:
    dev = device if device is not None else jax.devices()[0]
    return Mesh(np.asarray([dev]).reshape((1,) * len(AXES)), AXES)


# --------------------------------------------------------------------------
# Logical → physical sharding rules (t5x-style, but regex over param paths).
# --------------------------------------------------------------------------

# (param-path regex, PartitionSpec) — first match wins.  Paths are
# "/"-joined pytree keys, e.g. "blocks/attn_qkv/kernel".
Rules = List[Tuple[str, P]]

# Megatron-style 2D(+) sharding for transformer blocks.  ``fsdp`` shards the
# non-tensor dim of every matrix (ZeRO-3); ``tensor`` shards heads/hidden.
# ``blocks/...`` params are STACKED with a leading n_layer axis (lax.scan
# layout, see ray_tpu/models/gpt2.py) — that axis maps to ``pipeline``
# (size 1 unless PP is on, in which case stages own layer ranges).
TRANSFORMER_RULES: Rules = [
    (r".*wte$",                     P("tensor", "fsdp")),   # (vocab, embed)
    (r".*wpe$",                     P(None, "fsdp")),       # (pos, embed)
    (r".*blocks/attn_qkv/kernel$",  P("pipeline", "fsdp", None, "tensor")),
    (r".*blocks/attn_qkv/bias$",    P("pipeline", None, "tensor")),
    (r".*blocks/attn_out/kernel$",  P("pipeline", "tensor", "fsdp")),
    (r".*blocks/attn_out/bias$",    P("pipeline", "fsdp")),
    (r".*blocks/mlp_in/kernel$",    P("pipeline", "fsdp", "tensor")),
    (r".*blocks/mlp_in/bias$",      P("pipeline", "tensor")),
    (r".*blocks/mlp_out/kernel$",   P("pipeline", "tensor", "fsdp")),
    (r".*blocks/mlp_out/bias$",     P("pipeline", "fsdp")),
    (r".*blocks/(ln_1|ln_2)/(scale|bias)$", P("pipeline", None)),
    # Non-stacked variants (single-layer modules, BERT/ResNet dense layers).
    (r".*attn_qkv/kernel$",         P("fsdp", None, "tensor")),
    (r".*attn_out/kernel$",         P("tensor", "fsdp")),
    (r".*mlp_in/kernel$",           P("fsdp", "tensor")),
    (r".*mlp_out/kernel$",          P("tensor", "fsdp")),
    (r".*(ln_1|ln_2|ln_f)/(scale|bias)$", P(None)),
    (r".*", P(None)),
]


# Logical ACTIVATION axis → mesh axis (SNIPPETS.md [3] lineage: the
# sharding-rules table whose ``"seq": None  # TODO`` this fills).  Params
# are matched by the regex Rules above; intermediate activations are
# placed by logical-axis name through :func:`activation_spec`.  A value
# may be one mesh axis, a tuple of mesh axes (the dim shards over their
# product), or None (replicated).
ACTIVATION_RULES: Dict[str, Any] = {
    "batch": ("data", "fsdp"),     # batch dim: DP (+ ZeRO-3 data shards)
    "seq": ("seq", "tensor"),      # sequence-parallel region BETWEEN
                                   # attention and MLP: tokens shard over
                                   # the dedicated seq axis AND the tensor
                                   # group (Megatron-SP composition) —
                                   # norms/residuals never replicate work
    "seq_attn": "context",         # sequence THROUGH attention (ring CP)
    "heads": "tensor",             # attention heads (Megatron TP)
    "embed": None,                 # residual-stream feature dim
    "mlp": "tensor",               # MLP hidden dim
    "kv": None,                    # per-head feature dim
    "vocab": "tensor",             # logits vocab dim
}


def activation_spec(*logical: Optional[str]) -> P:
    """PartitionSpec for an activation from logical axis names.

    ``activation_spec("batch", "seq", "embed")`` is the canonical
    residual-stream placement between transformer blocks.  ``None``
    entries pass through as replicated dims.
    """
    parts = []
    for name in logical:
        if name is None:
            parts.append(None)
            continue
        if name not in ACTIVATION_RULES:
            raise KeyError(f"unknown logical activation axis {name!r} "
                           f"(have {sorted(ACTIVATION_RULES)})")
        parts.append(ACTIVATION_RULES[name])
    return P(*parts)


def constrain(x, *logical: Optional[str]):
    """Pin an intermediate activation to its logical placement.

    ``constrain(q, "batch", "seq_attn", "heads", "kv")`` applies
    ``with_sharding_constraint`` against the ambient program mesh
    (:func:`get_ambient_mesh`, set by spmd.build_train_program at trace
    time) — inside jit this forces GSPMD to materialize the declared
    layout at that point instead of whatever propagation guessed;
    outside any ambient mesh (unit tests, the serving engine's
    single-host jit) it is a no-op passthrough.  Dims whose mesh-axis
    product does not divide the dim size are left unconstrained (same
    tolerance the param rules get from NamedSharding itself).

    This is the live half of the ``ACTIVATION_RULES`` contract: rtlint's
    meshaxes pass fails on rules no ``constrain()``/``activation_spec()``
    names (``mesh-activation-dead``) and on names no rule declares
    (``mesh-activation-undeclared``).
    """
    mesh = get_ambient_mesh()
    if mesh is None or mesh.empty:
        return x
    sizes = dict(mesh.shape)
    parts = []
    for dim, name in zip(getattr(x, "shape", ()), logical):
        axes = ACTIVATION_RULES.get(name) if name is not None else None
        if name is not None and name not in ACTIVATION_RULES:
            raise KeyError(f"unknown logical activation axis {name!r} "
                           f"(have {sorted(ACTIVATION_RULES)})")
        if axes is None:
            parts.append(None)
            continue
        group = axes if isinstance(axes, tuple) else (axes,)
        group = tuple(a for a in group if a in sizes)
        total = 1
        for a in group:
            total *= sizes[a]
        if not group or total <= 1 or dim % total:
            parts.append(None)
        else:
            parts.append(axes)
    import jax
    if all(p is None for p in parts):
        return x
    from jax.sharding import NamedSharding
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*parts)))


def spec_for_path(path: str, rules: Rules) -> P:
    for pat, spec in rules:
        if re.fullmatch(pat, path):
            return spec
    return P(None)


def _tree_paths(tree: Any, prefix: str = "") -> Any:
    if isinstance(tree, dict):
        return {k: _tree_paths(v, f"{prefix}/{k}" if prefix else str(k))
                for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        seq = [_tree_paths(v, f"{prefix}/{i}" if prefix else str(i))
               for i, v in enumerate(tree)]
        if hasattr(tree, "_fields"):  # namedtuple: positional constructor
            return type(tree)(*seq)
        return type(tree)(seq)
    return prefix


def param_specs(params: Any, rules: Rules = TRANSFORMER_RULES,
                extra_leading: Optional[str] = None) -> Any:
    """Pytree of PartitionSpecs matching ``params``.

    ``extra_leading`` prepends a mesh axis to every spec (used for stacked
    scan-over-layers params whose leading dim is the layer index → sharded
    over ``pipeline`` when PP is on).
    """
    paths = _tree_paths(params)

    def leaf(path, p):
        spec = spec_for_path(path, rules)
        if extra_leading is not None:
            spec = P(extra_leading, *spec)
        nd = np.ndim(p) if not hasattr(p, "ndim") else p.ndim
        # trim/pad the spec to the leaf's rank
        parts = tuple(spec)[:nd]
        parts = parts + (None,) * (nd - len(parts))
        return P(*parts)

    return jax.tree_util.tree_map(leaf, paths, params)


def named_shardings(mesh: Mesh, specs: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P))


def shard_params(mesh: Mesh, params: Any,
                 rules: Rules = TRANSFORMER_RULES) -> Any:
    """Place a host pytree onto the mesh per the rules (lazy, via device_put)."""
    shardings = named_shardings(mesh, param_specs(params, rules))
    return jax.device_put(params, shardings)


def batch_spec(config: MeshConfig, rank: int = 2) -> P:
    """Sharding for a (batch, seq, ...) array: batch over data(+fsdp),
    sequence over context.  The ``seq`` axis deliberately does NOT shard
    the input tokens: (B, T+1) token blocks are rarely divisible by it,
    and the sequence-parallel scatter happens at the manual-region
    boundary inside the step (models/gpt2.py) where T is."""
    axes: List[Any] = [config.batch_axes()]
    if rank >= 2:
        axes.append("context" if config.context != 1 else None)
    axes += [None] * (rank - len(axes))
    return P(*axes)


def local_batch_size(global_batch: int, config: MeshConfig,
                     n_devices: int) -> int:
    cfg = config.resolved(n_devices)
    denom = math.prod(cfg.as_dict()[a] for a in cfg.batch_axes())
    if global_batch % denom:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"data-parallel degree {denom}")
    return global_batch // denom
